"""wirecheck: wire & durable-format schema verification (the fourth
machine-checked invariant layer — docs/design/wirecheck.md).

graftlint checks the AST, shardcheck the lowered IR, racecheck the lock
discipline; wirecheck checks the PROTOCOL. The control plane speaks ~60
serde dataclasses (common/messages.py) and persists five durable JSON
families (state-store speed/planner/nodes/dataset documents and the
``DatasetShardCheckpoint``), and a production fleet rolls upgrades: at
any moment an N-1 agent talks to an N master (or the inverse), and a
relaunched master reads durable state an older binary wrote. Version
skew safety used to be convention — scattered "skew-safe" comments and
per-site ``getattr`` fallbacks, with one documented-but-unfixed hazard
(the OverloadedResponse AttributeError class). wirecheck makes it a
checked-in contract, three ways:

1. **Schema registry** (``lint/wire_schema.json``): field names, type
   hints and default-presence of every registered message, plus the
   version of every registered durable format, extracted from the live
   registries and two-sided-diffed like ``lock_order.json`` — ANY
   drift (field added/removed, type changed, default dropped, format
   version bumped) fails until ``--fix-wire-schema`` records it as a
   reviewable one-line diff with a compat note (``--wire-note``).
   Fields recorded as added to an EXISTING message are auto-marked
   ``skew_guarded`` — they postdate the baseline, so WC002 requires
   their reads to tolerate absence.

2. **Skew rules** over the AST (graftlint suppression syntax applies):

   - WC001 default-less wire field: an N-1 peer's message lacks the
     new field, and ``cls(**kwargs)`` with no default raises TypeError
     at DECODE time — the worst place, inside the transport.
   - WC002 unguarded skew-field read: a consumer reading a
     ``skew_guarded`` field via plain attribute access. Under skew the
     object at that site can be the typed fallback (``SimpleResponse``
     from an old master that did not know the request) — the newest
     fields meet the oldest masters, so their reads must be
     absence-tolerant (``getattr`` with a default), which is exactly
     the convention every shipped skew-safe field already follows.
   - WC003 unknown-message hard-fail: every ``deserialize`` call site
     outside serde must lexically handle
     :class:`~dlrover_tpu.common.serde.UnknownMessageError` — servers
     degrade to ``SimpleResponse``, clients raise the typed taxonomy
     error — so an unknown ``_t`` can never escape as a raw
     ValueError (the OverloadedResponse bug class). A blanket
     ``except Exception`` deliberately does NOT count: that is the
     abort-INTERNAL path, not a skew degrade.
   - WC004 non-string dict keys in a message hint: serde's JSON wire
     round-trips dict keys as strings, so ``Dict[int, ...]`` silently
     changes key type across one hop (now also banned at runtime by
     ``serde._encode``).

3. **Golden corpus** (``lint/wire_corpus/``): serialized bytes of every
   registered message (instances synthesized from type hints) and
   every durable format — including FROZEN legacy variants (the
   version-less 5-element ``doing_meta`` checkpoint) — replayed on
   every run: current code must decode every checked-in byte stream
   and reproduce every recorded field value. Adding a field with a
   default keeps the old corpus decodable (that IS the N-1 test); a
   breaking change fails replay and forces an explicit, reviewable
   ``--fix-wire-corpus`` regeneration. Known limit: the gate replays
   the corpus checked in at the PR's head, so a regeneration in the
   same PR as the breaking change passes mechanically — the defense is
   that the regeneration is a diff a reviewer sees, next to the schema
   history entry that must accompany it.

The runtime companion is :mod:`dlrover_tpu.lint.skew_shim` + the fleet
harness ``version_skew`` scenarios: a serde-level shim makes the
in-process wire behave like an N-1 peer (fields dropped, unknown types
answered the old way), gated on exactly-once convergence and zero raw
decode errors in both skew directions.

Stdlib-only (ast + json + dataclasses + typing): runs in the dep-free
CI lint job alongside graftlint and racecheck.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import typing
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.lint import engine
from dlrover_tpu.lint.engine import SourceFile, Violation

DEFAULT_SCHEMA = os.path.join(os.path.dirname(__file__), "wire_schema.json")
DEFAULT_CORPUS_DIR = os.path.join(os.path.dirname(__file__), "wire_corpus")
#: the package root the AST rules scan by default
DEFAULT_PATHS = (os.path.dirname(os.path.dirname(__file__)),)

WC_RULES = [
    ("WC001", "defaultless-wire-field",
     "wire-message field without a default: an N-1 peer's message "
     "lacking it TypeErrors cls(**kwargs) at decode"),
    ("WC002", "unguarded-skew-field-read",
     "plain read of a skew_guarded (post-baseline) message field: must "
     "tolerate absence via getattr — under skew the object can be the "
     "typed SimpleResponse fallback"),
    ("WC003", "unknown-message-hard-fail",
     "deserialize call site without UnknownMessageError handling: an "
     "unknown _t must degrade (SimpleResponse / typed taxonomy error), "
     "never escape as a raw ValueError"),
    ("WC004", "non-string-dict-keys",
     "Dict[non-str, ...] in a wire-message hint: JSON round-trips keys "
     "as strings, silently changing the key type on the peer"),
    ("WC005", "schema-drift",
     "wire/durable schema differs from the checked-in "
     "wire_schema.json: record the change with --fix-wire-schema"),
    ("WC006", "corpus-replay",
     "golden corpus replay failure: current code cannot decode (or no "
     "longer reproduces) checked-in serialized bytes"),
]

#: receiver names that conventionally hold a decoded wire object; WC002
#: matches only these bases, trading recall for precision (a plain read
#: through any other name is invisible to the rule — documented limit)
WIRE_BASES = frozenset(
    {"resp", "response", "request", "req", "reply", "grant", "ack"}
)

#: durable formats whose payload is itself a dataclass — field lists
#: are extracted into the schema like message fields
_DURABLE_DATACLASSES = {
    "dataset_shard_ckpt": (
        "dlrover_tpu.master.shard.dataset_manager",
        "DatasetShardCheckpoint",
    ),
}


# ---------------------------------------------------------------------------
# registries
# ---------------------------------------------------------------------------


def message_registry() -> Dict[str, type]:
    """Every wire-serializable class, by importing BOTH vocabulary
    modules (the ``@message`` decorator registers on import). Keep this
    list in sync with every module that defines ``@message`` classes —
    a vocabulary module missing here would make the schema gate
    import-order-dependent (and under-scoped)."""
    import dlrover_tpu.brain.messages  # noqa: F401  (registration)
    import dlrover_tpu.common.messages  # noqa: F401  (registration)
    from dlrover_tpu.common import serde

    return dict(serde._REGISTRY)


def durable_formats():
    """Every registered durable format, by importing the writers."""
    import dlrover_tpu.master.shard.dataset_manager  # noqa: F401
    import dlrover_tpu.master.state_store  # noqa: F401
    from dlrover_tpu.common import versioned_format

    return dict(versioned_format.FORMATS)


def _durable_dataclass(name: str):
    spec = _DURABLE_DATACLASSES.get(name)
    if spec is None:
        return None
    import importlib

    return getattr(importlib.import_module(spec[0]), spec[1])


# ---------------------------------------------------------------------------
# schema extraction + two-sided diff
# ---------------------------------------------------------------------------


def _type_str(hint: Any) -> str:
    """Stable, human-auditable rendering of a type hint."""
    if hint is None:
        return "Any"
    if hint is type(None):  # noqa: E721
        return "None"
    origin = typing.get_origin(hint)
    if origin is None:
        return getattr(hint, "__name__", str(hint))
    args = typing.get_args(hint)
    if origin is typing.Union:
        non_none = [a for a in args if a is not type(None)]  # noqa: E721
        if len(args) == len(non_none) + 1 and len(non_none) == 1:
            return f"Optional[{_type_str(non_none[0])}]"
        return "Union[" + ", ".join(_type_str(a) for a in args) + "]"
    base = {list: "List", dict: "Dict", tuple: "Tuple", set: "Set"}.get(
        origin, getattr(origin, "__name__", str(origin))
    )
    if not args:
        return base
    return base + "[" + ", ".join(_type_str(a) for a in args) + "]"


_MISSING = dataclasses.MISSING


def extract_schema() -> Dict:
    """The live registries rendered as the schema document's structural
    half (metadata like ``skew_guarded``/``note`` lives only in the
    checked-in file and is merged on ``--fix``)."""
    messages: Dict[str, Dict] = {}
    for name, cls in sorted(message_registry().items()):
        hints = typing.get_type_hints(cls)
        fields: Dict[str, Dict] = {}
        for f in dataclasses.fields(cls):
            fields[f.name] = {
                "type": _type_str(hints.get(f.name)),
                "default": (
                    f.default is not _MISSING
                    or f.default_factory is not _MISSING
                ),
            }
        messages[name] = {"fields": fields}
    durable: Dict[str, Dict] = {}
    for name, fmt in sorted(durable_formats().items()):
        entry: Dict[str, Any] = {"version": fmt.version}
        cls = _durable_dataclass(name)
        if cls is not None:
            entry["fields"] = sorted(
                f.name for f in dataclasses.fields(cls)
            )
        durable[name] = entry
    return {"messages": messages, "durable": durable}


def load_schema(path: str = DEFAULT_SCHEMA) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def diff_schema(current: Dict, baseline: Dict) -> List[str]:
    """Two-sided structural diff, one human line per drift. Empty =
    clean. BOTH directions fail: an unrecorded addition and a stale
    baseline entry are equally drift."""
    out: List[str] = []
    cur_msgs = current.get("messages", {})
    base_msgs = baseline.get("messages", {})
    for name in sorted(set(cur_msgs) - set(base_msgs)):
        out.append(f"message {name} added (not in wire_schema.json)")
    for name in sorted(set(base_msgs) - set(cur_msgs)):
        out.append(
            f"message {name} removed (still in wire_schema.json) — "
            "removal breaks every peer still sending it"
        )
    for name in sorted(set(cur_msgs) & set(base_msgs)):
        cf = cur_msgs[name].get("fields", {})
        bf = base_msgs[name].get("fields", {})
        for fname in sorted(set(cf) - set(bf)):
            kind = (
                "WITHOUT a default (breaks N-1 decode)"
                if not cf[fname]["default"]
                else "with a default (safe add — still record it)"
            )
            out.append(f"field {name}.{fname} added {kind}")
        for fname in sorted(set(bf) - set(cf)):
            out.append(
                f"field {name}.{fname} removed — peers still sending it "
                "are fine (serde drops unknowns) but every consumer "
                "reading it breaks; record with a compat note"
            )
        for fname in sorted(set(cf) & set(bf)):
            if cf[fname]["type"] != bf[fname]["type"]:
                out.append(
                    f"field {name}.{fname} type changed "
                    f"{bf[fname]['type']} -> {cf[fname]['type']}"
                )
            if bf[fname]["default"] and not cf[fname]["default"]:
                out.append(
                    f"field {name}.{fname} LOST its default — an N-1 "
                    "peer's message lacking it now TypeErrors at decode"
                )
    cur_dur = current.get("durable", {})
    base_dur = baseline.get("durable", {})
    for name in sorted(set(cur_dur) - set(base_dur)):
        out.append(f"durable format {name} added")
    for name in sorted(set(base_dur) - set(cur_dur)):
        out.append(f"durable format {name} removed")
    for name in sorted(set(cur_dur) & set(base_dur)):
        cv, bv = cur_dur[name].get("version"), base_dur[name].get("version")
        if cv != bv:
            out.append(
                f"durable format {name} version changed {bv} -> {cv} — "
                "regenerate its corpus entry and keep the legacy pin"
            )
        cfields = cur_dur[name].get("fields")
        bfields = base_dur[name].get("fields")
        if cfields is not None and bfields is not None and cfields != bfields:
            added = sorted(set(cfields) - set(bfields))
            removed = sorted(set(bfields) - set(cfields))
            out.append(
                f"durable format {name} fields changed "
                f"(+{added or '[]'} -{removed or '[]'})"
            )
    return out


def write_schema(
    path: str, current: Dict, old: Optional[Dict], note: str = ""
) -> Dict:
    """Record the current extraction, preserving per-field metadata
    from the old file and auto-marking fields newly added to EXISTING
    messages as ``skew_guarded`` (they postdate the baseline — WC002
    will require absence-tolerant reads). Appends a history entry with
    the diff and the operator's compat note."""
    old = old or {"messages": {}, "durable": {}, "revision": 0,
                  "history": []}
    changes = diff_schema(current, old)
    merged = json.loads(json.dumps(current))  # deep copy
    old_msgs = old.get("messages", {})
    for name, m in merged["messages"].items():
        bf = old_msgs.get(name, {}).get("fields", {})
        existed = name in old_msgs
        for fname, f in m["fields"].items():
            if fname in bf:
                for meta in ("skew_guarded", "note"):
                    if meta in bf[fname]:
                        f[meta] = bf[fname][meta]
            elif existed:
                f["skew_guarded"] = True
    revision = int(old.get("revision", 0)) + (1 if changes else 0)
    data = {
        "comment": (
            "wirecheck wire & durable-format schema registry "
            "(docs/design/wirecheck.md). Two-sided-diffed by CI: any "
            "drift fails until recorded with: python -m dlrover_tpu."
            "lint --wire --fix-wire-schema --wire-note '<why this is "
            "compatible>'. skew_guarded marks fields added after a "
            "message first shipped — WC002 requires their reads to "
            "tolerate absence."
        ),
        "revision": revision,
        "history": list(old.get("history", [])),
        "messages": merged["messages"],
        "durable": merged["durable"],
    }
    if changes:
        data["history"].append({
            "revision": revision,
            "note": note or "(no compat note given)",
            "changes": changes,
        })
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def guarded_field_names(schema: Dict) -> Set[str]:
    """Field names WC002 enforces: marked ``skew_guarded`` in EVERY
    message that has a field of that name. A name that is guarded in
    one message and baseline in another (e.g. ``digest``: post-baseline
    on GlobalStepReport, born-with on WorkerReport) is ambiguous to a
    name-based AST rule and is skipped — a documented precision/recall
    trade."""
    seen: Dict[str, List[bool]] = {}
    for m in schema.get("messages", {}).values():
        for fname, f in m.get("fields", {}).items():
            seen.setdefault(fname, []).append(
                bool(f.get("skew_guarded", False))
            )
    return {n for n, flags in seen.items() if all(flags)}


def skew_baseline_drops(schema: Optional[Dict] = None) -> Dict[str, List[str]]:
    """message -> skew_guarded fields: the machine-readable
    approximation of "what an N-1 peer does not know", used by the
    fleet harness's version_skew shim as its default drop set."""
    schema = schema or load_schema() or {}
    out: Dict[str, List[str]] = {}
    for name, m in schema.get("messages", {}).items():
        fields = sorted(
            f for f, meta in m.get("fields", {}).items()
            if meta.get("skew_guarded")
        )
        if fields:
            out[name] = fields
    return out


# ---------------------------------------------------------------------------
# golden corpus: synthesis, write, replay
# ---------------------------------------------------------------------------


def synth_value(hint: Any, salt: str, registry: Dict[str, type],
                depth: int = 0) -> Any:
    """A deterministic representative value for a type hint. Depth-
    bounded so a (hypothetical) recursive message terminates."""
    if depth > 4:
        return None
    origin = typing.get_origin(hint)
    if hint is None or hint is Any:
        return f"any-{salt}"
    if origin is typing.Union:
        non_none = [a for a in typing.get_args(hint)
                    if a is not type(None)]  # noqa: E721
        return synth_value(non_none[0], salt, registry, depth) \
            if non_none else None
    if origin in (list, tuple, set) or hint in (list, tuple, set):
        args = typing.get_args(hint)
        if origin is tuple or hint is tuple:
            if args and args[-1] is not Ellipsis:
                return tuple(
                    synth_value(a, f"{salt}.{i}", registry, depth + 1)
                    for i, a in enumerate(args)
                )
            return (1, 2)
        elem = (
            synth_value(args[0], f"{salt}.0", registry, depth + 1)
            if args else f"item-{salt}"
        )
        return [elem]
    if origin is dict or hint is dict:
        args = typing.get_args(hint)
        val = (
            synth_value(args[1], f"{salt}.v", registry, depth + 1)
            if len(args) == 2 else f"val-{salt}"
        )
        return {f"k-{salt}": val}
    if hint is str:
        return f"s-{salt}"
    if hint is bool:
        return True
    if hint is int:
        return 7
    if hint is float:
        return 1.5
    if hint is bytes:
        return b"\x00\x01\xfe"
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        return synth_instance(hint, registry, depth + 1)
    return f"opaque-{salt}"


def synth_instance(cls: type, registry: Dict[str, type],
                   depth: int = 0) -> Any:
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        kwargs[f.name] = synth_value(
            hints.get(f.name), f"{cls.__name__}.{f.name}", registry, depth
        )
    return cls(**kwargs)


#: frozen durable-format pins. "current" entries regenerate with
#: --fix-wire-corpus; ".legacy" entries are FROZEN artifacts of the
#: pre-versioning writers (never regenerated from live code — they pin
#: that old bytes stay decodable forever).
_STATE_PAYLOADS: Dict[str, Dict] = {
    "state_speed": {"job_uid": "corpus", "global_step": 42,
                    "total_downtime": 3.5},
    "state_nodes": {"job_uid": "corpus",
                    "nodes": {"0": {"status": "RUNNING"}}},
    "state_planner": {"job_uid": "corpus",
                      "planner": {"ledger": [], "cooldown_until": 0.0}},
    "state_dataset": {"job_uid": "corpus",
                      "params": {"dataset_name": "d", "dataset_size": 200},
                      "ckpt": {"dataset_name": "d", "todo": [[0, 200]]},
                      "time": 1.0},
}

_LEGACY_DURABLE: Dict[str, Dict] = {
    # the pre-versioning shard checkpoint: no _format/_v, and the
    # doing_meta entry carries only 5 elements (pre-lease writer) — the
    # decode must fill the fence with -1 (legacy per-task dispatch)
    "dataset_shard_ckpt": {
        "dataset_name": "corpus",
        "todo": [[100, 200]],
        "doing": [[0, 100]],
        "epoch": 1,
        "completed_records": 300,
        "partition_offsets": {},
        "doing_meta": [[7, 3, "", 0, 100]],
        "task_id_seq": 8,
    },
    "state_speed": {"job_uid": "corpus", "global_step": 42,
                    "total_downtime": 3.5},
    "state_nodes": {"job_uid": "corpus",
                    "nodes": {"0": {"status": "RUNNING"}}},
    "state_planner": {"job_uid": "corpus",
                      "planner": {"ledger": [], "cooldown_until": 0.0}},
    "state_dataset": {"job_uid": "corpus",
                      "params": {"dataset_name": "d", "dataset_size": 200},
                      "ckpt": {"dataset_name": "d", "todo": [[0, 200]]},
                      "time": 1.0},
}


def _current_shard_ckpt():
    cls = _durable_dataclass("dataset_shard_ckpt")
    return cls(
        dataset_name="corpus",
        todo=[[100, 200], [200, 300]],
        doing=[[0, 100]],
        epoch=1,
        completed_records=300,
        partition_offsets={"p0": 300},
        doing_meta=[[7, 3, "", 0, 100, 5]],
        task_id_seq=8,
        epoch_unit="pass",
        epoch_factor=1,
        leases=[[3, 5, 1234.5, [7], 1200.0]],
        lease_seq=6,
    )


def _durable_current_doc(name: str) -> Dict:
    if name == "dataset_shard_ckpt":
        return json.loads(_current_shard_ckpt().to_json())
    fmt = durable_formats()[name]
    return fmt.wrap(dict(_STATE_PAYLOADS[name]))


def write_corpus(corpus_dir: str = DEFAULT_CORPUS_DIR) -> List[str]:
    """(Re)generate the golden corpus: one ``msg.<Name>.json`` per
    registered message, one ``durable.<fmt>.json`` per durable format,
    and — written only if absent — the frozen ``durable.<fmt>.legacy
    .json`` pins. Removes corpus files for messages that no longer
    exist (their removal is separately gated by the schema diff).
    Returns the written file names."""
    from dlrover_tpu.common import serde

    os.makedirs(corpus_dir, exist_ok=True)
    registry = message_registry()
    written: List[str] = []
    wanted: Set[str] = set()
    for name, cls in sorted(registry.items()):
        data = json.loads(serde.serialize(synth_instance(cls, registry)))
        fn = f"msg.{name}.json"
        wanted.add(fn)
        _write_json(os.path.join(corpus_dir, fn), data)
        written.append(fn)
    for name in sorted(durable_formats()):
        fn = f"durable.{name}.json"
        wanted.add(fn)
        _write_json(os.path.join(corpus_dir, fn), _durable_current_doc(name))
        written.append(fn)
        legacy = _LEGACY_DURABLE.get(name)
        lfn = f"durable.{name}.legacy.json"
        if legacy is not None:
            wanted.add(lfn)
            lpath = os.path.join(corpus_dir, lfn)
            if not os.path.exists(lpath):  # frozen: write-once
                _write_json(lpath, legacy)
                written.append(lfn)
    for fn in os.listdir(corpus_dir):
        if fn.endswith(".json") and fn not in wanted and not \
                fn.endswith(".legacy.json"):
            os.remove(os.path.join(corpus_dir, fn))
    return written


def _write_json(path: str, data: Dict):
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def check_corpus(corpus_dir: str = DEFAULT_CORPUS_DIR) -> List[str]:
    """Replay every corpus file through current code. One human line
    per failure; empty = clean. The decode side IS the N-1 gate: every
    checked-in byte stream is a message some shipped version wrote."""
    from dlrover_tpu.common import serde

    out: List[str] = []
    if not os.path.isdir(corpus_dir):
        return [f"corpus directory {corpus_dir} missing — run "
                "--fix-wire-corpus"]
    files = sorted(
        fn for fn in os.listdir(corpus_dir) if fn.endswith(".json")
    )
    registry = message_registry()
    formats = durable_formats()
    have_msgs = {
        fn[len("msg."):-len(".json")] for fn in files
        if fn.startswith("msg.")
    }
    for name in sorted(set(registry) - have_msgs):
        out.append(
            f"message {name} has no corpus file — run --fix-wire-corpus"
        )
    for fn in files:
        path = os.path.join(corpus_dir, fn)
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            out.append(f"{fn}: unreadable: {e}")
            continue
        if fn.startswith("msg."):
            out.extend(_replay_message(fn, data, registry, serde))
        elif fn.startswith("durable."):
            out.extend(_replay_durable(fn, data, formats))
    return out


def _replay_message(fn: str, data: Dict, registry, serde) -> List[str]:
    name = fn[len("msg."):-len(".json")]
    if name not in registry:
        return [
            f"{fn}: message {name} no longer registered — old peers "
            "still send it; record the removal in the schema and "
            "regenerate the corpus"
        ]
    try:
        # this IS the corpus gate: any decode failure (Unknown-
        # MessageError included) is caught and REPORTED as a WC006
        # finding — the degrade path is the report itself
        # graftlint: disable=WC003
        obj = serde.deserialize(
            json.dumps(data, separators=(",", ":")).encode()
        )
    except Exception as e:
        return [f"{fn}: DECODE FAILED (an N-1 peer's bytes no longer "
                f"decode): {type(e).__name__}: {e}"]
    if type(obj).__name__ != name:
        return [f"{fn}: decoded as {type(obj).__name__}, expected {name}"]
    try:
        reenc = serde._encode(obj)
    except Exception as e:
        return [f"{fn}: re-encode failed: {type(e).__name__}: {e}"]
    out = []
    for key, val in data.items():
        if key == "_t":
            continue
        if key not in reenc:
            out.append(
                f"{fn}: field {name}.{key} present in corpus but dropped "
                "by decode (field removed?) — consumers of old senders "
                "lose data silently"
            )
        elif reenc[key] != val:
            out.append(
                f"{fn}: field {name}.{key} value drift: corpus {val!r} "
                f"-> decoded-re-encoded {reenc[key]!r}"
            )
    return out


def _replay_durable(fn: str, data: Dict, formats) -> List[str]:
    body = fn[len("durable."):-len(".json")]
    legacy = body.endswith(".legacy")
    name = body[:-len(".legacy")] if legacy else body
    if name not in formats:
        return [f"{fn}: durable format {name} no longer registered"]
    if name == "dataset_shard_ckpt":
        return _replay_shard_ckpt(fn, data, legacy)
    fmt = formats[name]
    if not legacy and int(data.get("_v", -1)) != fmt.version:
        return [
            f"{fn}: corpus stamped v{data.get('_v')} but {name} is "
            f"registered at v{fmt.version} — regenerate the corpus "
            "after recording the version bump"
        ]
    try:
        payload = fmt.parse(data)
    except Exception as e:
        return [f"{fn}: parse failed: {type(e).__name__}: {e}"]
    out = []
    for key, val in data.items():
        if key in ("_format", "_v"):
            continue
        if payload.get(key) != val:
            out.append(
                f"{fn}: durable payload key {key!r} drift: {val!r} -> "
                f"{payload.get(key)!r}"
            )
    return out


def _replay_shard_ckpt(fn: str, data: Dict, legacy: bool) -> List[str]:
    cls = _durable_dataclass("dataset_shard_ckpt")
    try:
        ckpt = cls.from_json(json.dumps(data))
    except Exception as e:
        return [f"{fn}: from_json failed: {type(e).__name__}: {e}"]
    out = []
    if not legacy and int(data.get("_v", -1)) != \
            durable_formats()["dataset_shard_ckpt"].version:
        out.append(
            f"{fn}: corpus stamped v{data.get('_v')} but the format is "
            f"v{durable_formats()['dataset_shard_ckpt'].version} — "
            "regenerate after recording the version bump"
        )
    for entry in ckpt.doing_meta:
        if len(entry) != 6:
            out.append(
                f"{fn}: doing_meta entry {entry!r} not normalized to 6 "
                "elements"
            )
    if legacy and ckpt.doing_meta and ckpt.doing_meta[0][5] != -1:
        out.append(
            f"{fn}: legacy 5-element doing_meta decoded fence "
            f"{ckpt.doing_meta[0][5]!r}, expected -1"
        )
    for key in ("dataset_name", "epoch", "completed_records",
                "task_id_seq"):
        if key in data and getattr(ckpt, key) != data[key]:
            out.append(
                f"{fn}: {key} drift: {data[key]!r} -> "
                f"{getattr(ckpt, key)!r}"
            )
    return out


# ---------------------------------------------------------------------------
# AST rules WC001-WC004
# ---------------------------------------------------------------------------


def _is_message_class(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(target, ast.Name) and target.id == "message":
            return True
        if isinstance(target, ast.Attribute) and target.attr == "message":
            return True
    return False


def _wc001_wc004(src: SourceFile) -> List[Violation]:
    out: List[Violation] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ClassDef) or not \
                _is_message_class(node):
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign):
                continue
            fname = getattr(stmt.target, "id", "?")
            if stmt.value is None:
                out.append(src.violation(
                    "WC001", stmt,
                    f"wire field {node.name}.{fname} has no default: an "
                    "N-1 peer's message lacks it and cls(**kwargs) "
                    "TypeErrors at decode — give it a default",
                ))
            bad_key = _non_str_dict_key(stmt.annotation)
            if bad_key is not None:
                out.append(src.violation(
                    "WC004", stmt,
                    f"wire field {node.name}.{fname} is Dict[{bad_key}, "
                    "...]: JSON round-trips keys as str, silently "
                    "changing the key type on the peer — stringify "
                    "explicitly (serde._encode now rejects non-str "
                    "keys at runtime)",
                ))
    return out


def _non_str_dict_key(annotation: ast.AST) -> Optional[str]:
    for node in ast.walk(annotation):
        if not isinstance(node, ast.Subscript):
            continue
        base = node.value
        base_name = (
            base.id if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else ""
        )
        if base_name not in ("Dict", "dict", "Mapping"):
            continue
        sl = node.slice
        if isinstance(sl, ast.Tuple) and sl.elts:
            key = sl.elts[0]
            key_name = (
                key.id if isinstance(key, ast.Name)
                else key.attr if isinstance(key, ast.Attribute) else None
            )
            if key_name is not None and key_name != "str":
                return key_name
    return None


def _wc002(src: SourceFile, guarded: Set[str]) -> List[Violation]:
    out: List[Violation] = []
    if not guarded:
        return out
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Attribute):
            continue
        if not isinstance(node.ctx, ast.Load):
            continue
        if node.attr not in guarded:
            continue
        base = node.value
        if not (isinstance(base, ast.Name) and base.id in WIRE_BASES):
            continue
        parent = getattr(node, "_graftlint_parent", None)
        if isinstance(parent, ast.Call) and parent.func is node:
            continue  # method call, not a field read
        out.append(src.violation(
            "WC002", node,
            f"plain read of skew-guarded field .{node.attr}: under "
            "version skew this object can be the typed SimpleResponse "
            "fallback (old master, unknown request type) — use "
            f"getattr({base.id}, \"{node.attr}\", <default>)",
        ))
    return out


def _wc003(src: SourceFile) -> List[Violation]:
    if src.rel_path.endswith("common/serde.py"):
        return []
    out: List[Violation] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = (
            node.func.id if isinstance(node.func, ast.Name)
            else node.func.attr if isinstance(node.func, ast.Attribute)
            else ""
        )
        if fname != "deserialize":
            continue
        if not _unknown_handled(node):
            out.append(src.violation(
                "WC003", node,
                "deserialize call without UnknownMessageError handling "
                "in an enclosing try: an unknown _t (version skew) "
                "must degrade to SimpleResponse (servers) or the typed "
                "taxonomy error (clients), never escape as a raw "
                "ValueError — and a blanket `except Exception` is the "
                "abort path, not a skew degrade",
            ))
    return out


def _unknown_handled(call: ast.Call) -> bool:
    node: ast.AST = call
    parent = getattr(node, "_graftlint_parent", None)
    while parent is not None:
        if isinstance(parent, ast.Try) and node in parent.body:
            for handler in parent.handlers:
                if handler.type is not None and _mentions_unknown(
                        handler.type):
                    return True
        node, parent = parent, getattr(
            parent, "_graftlint_parent", None
        )
    return False


def _mentions_unknown(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in (
                "UnknownMessageError", "UnknownMessageTypeError"):
            return True
        if isinstance(n, ast.Attribute) and n.attr in (
                "UnknownMessageError", "UnknownMessageTypeError"):
            return True
    return False


def ast_message_classes(paths: Sequence[str]) -> Dict[str, str]:
    """Every ``@message``-decorated class name found by walking the
    SOURCE under ``paths`` -> its file. Cross-checked against the
    runtime registry in :func:`run`: a vocabulary module that
    :func:`message_registry` does not import would otherwise be
    silently excluded from the schema diff, the corpus, WC002's guard
    set and the skew shim's drop map — exactly how the 11
    brain/messages.py classes were import-order-invisible to this
    gate's first extraction."""
    out: Dict[str, str] = {}
    for full, display in engine.iter_py_files(paths):
        try:
            with open(full, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=full)
        except (OSError, SyntaxError, ValueError):
            continue  # reported as an error by check_ast's own walk
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and _is_message_class(node):
                out[node.name] = display
    return out


def check_ast(
    paths: Sequence[str], schema: Optional[Dict]
) -> Tuple[List[Violation], List[str]]:
    guarded = guarded_field_names(schema or {})
    violations: List[Violation] = []
    errors: List[str] = []
    for full, display in engine.iter_py_files(paths):
        try:
            with open(full, encoding="utf-8") as f:
                text = f.read()
            src = SourceFile(full, text, rel_path=display)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{display}: unparsable: {e}")
            continue
        found = (
            _wc001_wc004(src) + _wc002(src, guarded) + _wc003(src)
        )
        violations.extend(
            v for v in found if not src.suppressed(v.rule, v.line)
        )
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations, errors


# ---------------------------------------------------------------------------
# one-call entry (CLI and tests share it)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WireResult:
    violations: List[Violation]  # AST findings
    schema_drift: List[str]
    corpus_failures: List[str]
    errors: List[str]

    @property
    def failed(self) -> bool:
        return bool(
            self.violations or self.schema_drift
            or self.corpus_failures or self.errors
        )


def run(
    paths: Optional[Sequence[str]] = None,
    schema_path: str = DEFAULT_SCHEMA,
    corpus_dir: str = DEFAULT_CORPUS_DIR,
    fix_schema: bool = False,
    fix_corpus: bool = False,
    note: str = "",
) -> WireResult:
    current = extract_schema()
    baseline = load_schema(schema_path)
    if fix_schema:
        write_schema(schema_path, current, baseline, note=note)
        baseline = load_schema(schema_path)
    if fix_corpus:
        write_corpus(corpus_dir)
    drift: List[str] = []
    if baseline is None:
        drift.append(
            f"no schema at {schema_path} — record one with "
            "--fix-wire-schema"
        )
    else:
        drift = diff_schema(current, baseline)
    # the AST<->registry cross-check: every @message class in the
    # scanned SOURCE must be reachable through message_registry()'s
    # imports, or the whole gate is silently under-scoped for it
    registered = set(message_registry())
    for name, where in sorted(ast_message_classes(
            paths or DEFAULT_PATHS).items()):
        if name not in registered:
            drift.append(
                f"message {name} ({where}) is @message-decorated but "
                "NOT in the runtime registry — its module is missing "
                "from wirecheck.message_registry()'s vocabulary "
                "imports, so the schema/corpus/skew gates cannot see it"
            )
    corpus = check_corpus(corpus_dir)
    violations, errors = check_ast(
        paths or DEFAULT_PATHS, baseline or current
    )
    return WireResult(violations, drift, corpus, errors)


def report(result: WireResult, out=None) -> None:
    import sys

    out = out or sys.stdout
    for v in result.violations:
        print(v.format(), file=out)
    for line in result.schema_drift:
        print(f"WC005 schema drift: {line}", file=out)
    for line in result.corpus_failures:
        print(f"WC006 corpus: {line}", file=out)
    for e in result.errors:
        print(f"ERROR {e}", file=out)
    print(
        f"wirecheck: {len(result.violations)} AST violation(s), "
        f"{len(result.schema_drift)} schema drift(s), "
        f"{len(result.corpus_failures)} corpus failure(s), "
        f"{len(result.errors)} error(s)",
        file=out,
    )
