"""shardcheck: static analysis of the *lowered* step program (SC rules).

graftlint (rules.py) machine-checks elasticity invariants at the Python
AST level; every truly expensive bug this repo shipped lived **below**
the AST — in the program XLA actually runs:

- the GSPMD ``jnp.concatenate`` miscompile that doubled every target id
  (an unreduced replica sum the source code could never show);
- adam moments coming back from the step re-sharded, silently changing
  step N+1's input signature (recompile under jit, hard reject under
  AOT);
- the dense ``[B, T, V]`` f32 logits materialization chunked-CE exists
  to kill.

So this module reads the IR itself. Two texts, both obtained for free
from the warm-compile machinery (``ElasticTrainer.lower_step`` lowers
the step for *any* admissible world from shape avatars — live or not —
so the whole analysis runs on CPU, in CI, with no TPU attached):

- **StableHLO** (``lowered.as_text()``): global shapes, the entry
  signature's per-arg/per-result ``mhlo.sharding`` strings and the
  ``tf.aliasing_output`` donation links, explicit ``@Sharding``
  constraint sites. Feeds SC002/SC003/SC004.
- **optimized HLO** (``compiled.as_text()``): the post-GSPMD per-device
  program where the collectives are real ops with replica groups and
  shapes. Feeds SC001/SC005.

Rules (each encodes a shipped bug — see docs/design/shardcheck.md):

SC001  collective census: count + size every all-gather / all-reduce /
       reduce-scatter / collective-permute / all-to-all per mesh axis
       and diff against a checked-in per-(mesh, config-hash) contract.
SC002  replicated-large-tensor: an explicitly sharding-constrained
       intermediate above a byte threshold left fully replicated while
       the mesh has data axes to shard it over.
SC003  dense-vocab materialization: a float dot_general result carrying
       BOTH the sequence and the full vocab dim (the chunked-CE
       regression gate).
SC004  output-sharding drift: a donated state input whose paired output
       sharding is missing (left to XLA — free to drift) or different.
SC005  host transfer inside the jitted step: host callbacks, infeed /
       outfeed, host send/recv.
SC006  exposed-DCN-bytes: the exposed/overlapped split of slice-boundary
       transfers diffed against the contract (the overlap schedule's
       regression gate).
SC007  custom-call census: every non-benign custom-call (the Pallas /
       Mosaic kernels) recorded per contract — a contracted kernel
       vanishing from the lowered step is a silent fallback to the
       reference path, a new un-contracted one is an unreviewed kernel.

Everything here is text analysis over the two IR strings plus a small
``StepProgram`` context object — no jax import, no device use — so the
rules themselves are unit-testable from canned IR and the module stays
importable in the dep-free lint environment. Lowering the program to
GET the text (CLI ``--hlo``, trainer hook) is the caller's job.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dlrover_tpu.lint.engine import Severity, Violation

#: contracts shipped with the package (``--fix-contracts`` rewrites)
DEFAULT_CONTRACTS_DIR = os.path.join(os.path.dirname(__file__), "contracts")

#: the world-shape vocabulary lives in common/world.py now (the
#: WorldDescriptor refactor): the contract-spec grammar, the canonical
#: axis order and the parse/format pair are defined ONCE there and
#: re-exported here for the existing call sites — shardcheck, the
#: trainer hook, the CLI and the planner all describe a program's world
#: through the same checked type instead of four re-derivations
from dlrover_tpu.common.world import (  # noqa: F401  (re-exports)
    CANONICAL_AXES,
    ZERO1_SUFFIX,
    WorldDescriptor,
    contract_spec_of,
    mesh_spec_of,
    parse_contract_spec,
    parse_mesh_spec,
)


class ShardcheckError(RuntimeError):
    """Raised by the strict lower-time hook when the compiled step
    program violates an SC rule."""

    def __init__(self, violations: Sequence[Violation]):
        self.violations = list(violations)
        super().__init__(
            f"{len(self.violations)} shardcheck violation(s):\n"
            + "\n".join(v.format() for v in self.violations)
        )


#: collective HLO opcodes the census tracks (``-start`` variants fold
#: into their base op: async pairs describe one transfer)
COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

#: dtype byte widths for HLO/StableHLO shape strings
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "i8": 1,
    "s16": 2, "u16": 2, "i16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "i32": 4, "f32": 4,
    "s64": 8, "u64": 8, "i64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

#: SC001 default: byte growth beyond this fraction of the contract
#: fails even when no new collective appeared
DEFAULT_BYTE_TOLERANCE = 0.10

#: SC002 default: "large" means a global tensor above this many bytes
#: (CPU-mesh tests pass explicit tiny thresholds)
DEFAULT_REPLICATED_BYTES = 256 << 20

#: StableHLO custom_call targets that are partitioner plumbing, not
#: host transfers
_BENIGN_CUSTOM_CALLS = {
    "Sharding",
    "SPMDFullToShardShape",
    "SPMDShardToFullShape",
    "MoveToHost",  # explicit host offload is its own, opted-in feature
    "MoveToDevice",
    "AllocateBuffer",
    "LayoutConstraint",
}

_HOST_CALLBACK_HINTS = ("cpu_callback", "host_callback", "py_callback")

#: custom_call targets that ARE the device kernels this repo ships
#: (Pallas lowers through Mosaic to ``tpu_custom_call``). Never host
#: transfers — SC005 must not flag them — and exactly what the SC007
#: census exists to track.
_DEVICE_KERNEL_HINTS = ("tpu_custom_call", "mosaic", "triton_kernel_call")


# ---------------------------------------------------------------------------
# shape / sharding string parsing
# ---------------------------------------------------------------------------


def shape_bytes(shape_str: str) -> int:
    """Bytes of an HLO shape string like ``f32[2,16,64]`` (layout
    ``{...}`` already stripped by the caller's regex). Tuples and
    opaque/token shapes return 0 — they never matter for a census."""
    m = re.match(r"([a-z]+[0-9]*)\[([0-9,]*)\]$", shape_str.strip())
    if not m:
        return 0
    width = _DTYPE_BYTES.get(m.group(1))
    if width is None:
        return 0
    n = 1
    dims = m.group(2)
    if dims:
        for d in dims.split(","):
            n *= int(d)
    return n * width


def tensor_type_dims(type_str: str) -> Tuple[Tuple[int, ...], str]:
    """``'8x16x256xf32'`` → ((8, 16, 256), 'f32'); scalars → ((), dtype).
    Unparsable (dynamic dims, complex element syntax) → ((), '')."""
    parts = type_str.strip().split("x")
    if not parts:
        return (), ""
    dtype = parts[-1]
    dims: List[int] = []
    for p in parts[:-1]:
        if not p.isdigit():
            return (), ""
        dims.append(int(p))
    if not re.match(r"^[a-z]+[0-9]*$", dtype):
        return (), ""
    return tuple(dims), dtype


def tensor_type_bytes(type_str: str) -> int:
    dims, dtype = tensor_type_dims(type_str)
    width = _DTYPE_BYTES.get(dtype)
    if width is None:
        return 0
    n = 1
    for d in dims:
        n *= d
    return n * width


@dataclasses.dataclass(frozen=True)
class ParsedSharding:
    """One ``mhlo.sharding`` / HLO sharding string, reduced to what the
    rules need: how many ways the tensor is tiled (model shards) and
    how many ways each tile is replicated."""

    raw: str
    kind: str  # "replicated" | "maximal" | "tiled" | "unknown"
    tile_dims: Tuple[int, ...] = ()
    num_devices: int = 0
    replicate_ways: int = 1

    @property
    def tile_count(self) -> int:
        n = 1
        for d in self.tile_dims:
            n *= d
        return n


def parse_sharding(raw: str) -> ParsedSharding:
    """Parse the V1 sharding syntax jax prints into ``mhlo.sharding``:
    ``{replicated}``, ``{maximal device=0}``,
    ``{devices=[2,2,2]<=[8] last_tile_dim_replicate}`` (the trailing
    tile dim is the replication factor), iota/transpose device lists."""
    s = raw.strip().strip("{}").strip()
    if s == "replicated" or s == "":
        return ParsedSharding(raw, "replicated")
    if s.startswith("maximal"):
        return ParsedSharding(raw, "maximal")
    m = re.match(r"devices=\[([0-9,]+)\]", s)
    if not m:
        return ParsedSharding(raw, "unknown")
    dims = tuple(int(d) for d in m.group(1).split(","))
    n = 1
    for d in dims:
        n *= d
    if "last_tile_dim_replicate" in s:
        return ParsedSharding(
            raw, "tiled", tile_dims=dims[:-1], num_devices=n,
            replicate_ways=dims[-1],
        )
    return ParsedSharding(raw, "tiled", tile_dims=dims, num_devices=n)


# ---------------------------------------------------------------------------
# replica-group parsing + mesh-axis attribution
# ---------------------------------------------------------------------------


def parse_replica_groups(attr: str) -> List[Tuple[int, ...]]:
    """Both HLO forms: explicit ``{{0,2},{1,3}}`` and iota
    ``[4,2]<=[8]`` / ``[4,2]<=[2,2,2]T(2,1,0)`` (arange over the
    reshape dims, transposed by the permutation, regrouped row-major)."""
    attr = attr.strip()
    if attr.startswith("{"):
        groups = []
        for grp in re.findall(r"\{([0-9,\s]*)\}", attr):
            ids = tuple(int(x) for x in grp.replace(" ", "").split(",") if x)
            if ids:
                groups.append(ids)
        return groups
    m = re.match(
        r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?", attr
    )
    if not m:
        return []
    out_dims = [int(x) for x in m.group(1).split(",")]
    src_dims = [int(x) for x in m.group(2).split(",")]
    total = 1
    for d in src_dims:
        total *= d
    ids = list(range(total))
    if m.group(3):
        perm = [int(x) for x in m.group(3).split(",")]
        # arange reshaped to src_dims, transposed by perm, flattened —
        # index arithmetic without numpy (this module stays dep-free)
        strides = [1] * len(src_dims)
        for i in range(len(src_dims) - 2, -1, -1):
            strides[i] = strides[i + 1] * src_dims[i + 1]
        t_dims = [src_dims[p] for p in perm]
        t_strides = [strides[p] for p in perm]
        flat: List[int] = []

        def _emit(prefix_idx: List[int], depth: int):
            if depth == len(t_dims):
                flat.append(
                    sum(i * s for i, s in zip(prefix_idx, t_strides))
                )
                return
            for i in range(t_dims[depth]):
                _emit(prefix_idx + [i], depth + 1)

        _emit([], 0)
        ids = flat
    if len(out_dims) == 1:
        return [tuple(ids)]
    group_size = out_dims[-1]
    n_groups = 1
    for d in out_dims[:-1]:
        n_groups *= d
    return [
        tuple(ids[g * group_size:(g + 1) * group_size])
        for g in range(n_groups)
    ]


def parse_source_target_pairs(attr: str) -> List[Tuple[int, int]]:
    return [
        (int(a), int(b))
        for a, b in re.findall(r"\{(\d+),(\d+)\}", attr)
    ]


class MeshCoords:
    """Maps a replica-group member to its coordinate along each mesh
    axis, so a group of participants can be attributed to the axes its
    members vary over.

    ``axis_sizes`` follows the mesh's axis order. Group members in
    post-GSPMD HLO are **logical device-assignment positions** (the
    partition index), NOT hardware device ids — and jax builds the
    assignment in ``mesh.devices.flat`` order, so a member decodes
    directly as a flat index into the mesh shape. (Mapping through
    hardware ids would invert the attribution on any mesh whose device
    order is permuted — every real TPU torus mesh.)

    ``n_slices > 1`` adds LINK-CLASS attribution: the multislice
    layout is slice-major over the outermost (dp) axis
    (``parallel/mesh.py _build_multislice_mesh``), so a device-
    assignment position's slice is simply ``position // per_slice`` —
    and a replica group whose members span more than one slice is a
    collective that crosses DCN."""

    def __init__(self, axis_sizes: Dict[str, int], n_slices: int = 1):
        self.axis_sizes = dict(axis_sizes)
        self.axes = list(axis_sizes)
        n = 1
        for s in axis_sizes.values():
            n *= s
        self.num_devices = n
        self.n_slices = max(1, int(n_slices))
        if self.n_slices > 1 and n % self.n_slices:
            # a world that doesn't tile into slices cannot be slice-
            # attributed; fail soft to single-slice (everything "ici")
            # rather than mis-labeling — the mesh builder would have
            # rejected this topology anyway
            self.n_slices = 1
        self._per_slice = (
            n // self.n_slices if self.n_slices > 1 else n
        )

    def slice_of(self, position: int) -> int:
        """Slice of a device-assignment position (slice-major layout)."""
        if self._per_slice <= 0:
            return 0
        return position // self._per_slice

    def slices_spanned(self, members: Sequence[int]) -> int:
        """Distinct slices a replica group's members live on."""
        if self.n_slices <= 1:
            return 1
        return len({self.slice_of(m) for m in members}) or 1

    def link_of_groups(self, groups: Sequence[Sequence[int]]) -> Tuple[
        str, int
    ]:
        """``("ici"|"dcn", max slices spanned by any group)``. Empty
        groups (= every device participates) span all slices."""
        if self.n_slices <= 1:
            return "ici", 1
        if not groups:
            return "dcn", self.n_slices
        spanned = max(self.slices_spanned(g) for g in groups)
        return ("dcn" if spanned > 1 else "ici"), spanned

    def link_of_pairs(self, pairs: Sequence[Tuple[int, int]]) -> Tuple[
        str, int
    ]:
        """collective-permute link class: any pair crossing a slice
        boundary makes the op ride DCN."""
        if self.n_slices <= 1:
            return "ici", 1
        spanned = 1
        for s, t in pairs:
            if s != t and self.slice_of(s) != self.slice_of(t):
                spanned = 2
                break
        return ("dcn" if spanned > 1 else "ici"), spanned

    def coords(self, position: int) -> Optional[Tuple[int, ...]]:
        if not 0 <= position < self.num_devices:
            return None
        out = []
        for axis in reversed(self.axes):
            size = self.axis_sizes[axis]
            out.append(position % size)
            position //= size
        return tuple(reversed(out))

    def _varying_axes(self, members: Sequence[int]) -> Optional[List[str]]:
        coord_list = [self.coords(m) for m in members]
        if any(c is None for c in coord_list):
            return None
        varying = []
        for i, axis in enumerate(self.axes):
            if len({c[i] for c in coord_list}) > 1:
                varying.append(axis)
        return varying

    def attribute_groups(self, groups: Sequence[Sequence[int]]) -> str:
        """Axis label for a replica-group list: the axes whose
        coordinates vary inside the groups — ``"dp"``, ``"fsdp"``,
        ``"dp+fsdp"`` for a fused data reduce, ``"unattributed"`` when
        ids fall outside the mesh. Always named by the actual axes
        (never collapsed to a "world" label): the same logical
        collective must key the same census cell on every mesh shape,
        or contracts stop being comparable across meshes."""
        if not groups:
            # num_replicas-style empty groups = every device participates
            varying = {a for a, s in self.axis_sizes.items() if s > 1}
        else:
            varying = set()
            for g in groups:
                v = self._varying_axes(g)
                if v is None:
                    return "unattributed"
                varying.update(v)
        if not varying:
            return "self"
        return "+".join(a for a in self.axes if a in varying)

    def attribute_pairs(self, pairs: Sequence[Tuple[int, int]]) -> str:
        """collective-permute: attribute by the axes source and target
        coordinates differ over (self-pairs ignored)."""
        varying: set = set()
        for s, t in pairs:
            if s == t:
                continue
            v = self._varying_axes([s, t])
            if v is None:
                return "unattributed"
            varying.update(v)
        if not varying:
            return "self"
        return "+".join(a for a in self.axes if a in varying)


# ---------------------------------------------------------------------------
# compiled-HLO collective census (SC001 substrate)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    kind: str
    shape: str  # result shape, e.g. "f32[2,16,64]"
    bytes: int  # per-device contribution (see parse_collectives)
    axes: str  # mesh-axis label ("fsdp", "dp+fsdp", "tp", ...)
    line: int  # 1-indexed line in the HLO text
    #: link class: "dcn" when any replica group spans >1 slice of a
    #: multislice device assignment, else "ici" (single-slice meshes
    #: are all-ici by construction)
    link: str = "ici"
    #: modeled per-device bytes this op moves ACROSS the slice
    #: boundary (0 for ici ops) — see parse_collectives
    dcn_bytes: int = 0


_COLLECTIVE_RE = re.compile(
    r"=\s*(?:\(?)(?:[a-z0-9]+\[[0-9,]*\])"
    r"[^=]*?\b(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\("
)
_SHAPE_RE = re.compile(r"[a-z]+[0-9]*\[[0-9,]*\]")


def _result_shape(line: str, op_start: int, is_async: bool) -> str:
    """The RESULT payload shape of a collective op line. Sync ops:
    the (possibly tuple of) shapes before the op name are all results
    — variadic collectives sum below. Async ``-start`` ops: the tuple
    is (operand…, result…); the LAST element is the result, so the
    census records the same bytes whether XLA lowered the transfer
    sync or async."""
    eq = line.find("= ")
    seg = line[eq + 2:op_start] if eq >= 0 else line[:op_start]
    shapes = _SHAPE_RE.findall(seg)
    if not shapes:
        return ""
    if is_async or len(shapes) == 1:
        return shapes[-1]
    return "+".join(shapes)  # sync variadic: every element is a result


def parse_collectives(
    hlo_text: str, coords: MeshCoords
) -> List[CollectiveOp]:
    """Every collective op in an optimized HLO module, with its payload
    and mesh-axis attribution. ``-done`` halves of async pairs are
    skipped (the ``-start`` carries the transfer).

    Byte accounting is the PER-DEVICE CONTRIBUTION of one op — the same
    unit the analytic comm ledger uses (profiler/comm.py, "what one
    rank sends"): the full reduced tensor for all-reduce, the scattered
    shard for reduce-scatter, and for all-gather the operand shard each
    rank contributes (result bytes / participants), NOT the gathered
    result. Counting the gathered result would overstate an all-gather
    by the axis size against every other op — and make the
    allreduce→reduce-scatter+all-gather rewrite (zero-1) read as MORE
    communication when it moves strictly less per link.

    On a multislice assignment (``coords.n_slices > 1``) each op also
    carries its LINK class and modeled per-device DCN bytes — what the
    op moves across the slice boundary. The contribution unit cannot
    express this (a flat reduce-scatter over dp and the hierarchical
    DCN leg scatter the same result shape while moving very different
    bytes over the slow link), so the DCN model follows the op's
    *operand*, the analytic-formula approach the comm ledger already
    takes for bandwidth: with ``s`` = slices the group spans and
    ``frac = 1 - 1/s`` (the share of a uniformly-partitioned payload
    that is remote),

    - all-reduce / all-to-all: operand == result → ``result × frac``;
    - reduce-scatter: operand = result × participants → that × frac
      (the un-scattered input is what rides the ring past the cut);
    - all-gather: every remote shard crosses once → gathered result ×
      frac;
    - collective-permute: the full payload crosses iff the pair does.

    A model, not a packet count — its value is that flat and
    hierarchical variants of the same reduction are scored by the same
    rule, so the 2slice contracts can assert the hierarchy's DCN bytes
    are ~1/dp_in of the flat path's and veto a regression that moves
    bytes back onto the slow link."""
    out: List[CollectiveOp] = []
    for lineno, line in enumerate(hlo_text.splitlines(), start=1):
        if "-done" in line:
            continue
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        shape = _result_shape(line, m.start(1), m.group(2) is not None)
        raw_bytes = sum(shape_bytes(s) for s in shape.split("+"))
        nbytes = raw_bytes
        if kind == "collective-permute":
            pairs = parse_source_target_pairs(
                _attr(line, "source_target_pairs")
            )
            axes = coords.attribute_pairs(pairs)
            link, spanned = coords.link_of_pairs(pairs)
            participants = 1
        else:
            groups = parse_replica_groups(_attr(line, "replica_groups"))
            axes = coords.attribute_groups(groups)
            link, spanned = coords.link_of_groups(groups)
            participants = (
                len(groups[0]) if groups and groups[0]
                else max(coords.num_devices, 1)
            )
            if kind == "all-gather":
                nbytes //= max(participants, 1)
        dcn_bytes = 0
        if link == "dcn":
            frac = 1.0 - 1.0 / max(spanned, 2)
            if kind == "collective-permute":
                dcn_bytes = raw_bytes
            elif kind == "reduce-scatter":
                dcn_bytes = int(raw_bytes * participants * frac)
            else:
                dcn_bytes = int(raw_bytes * frac)
        out.append(
            CollectiveOp(
                kind=kind,
                shape=shape,
                bytes=nbytes,
                axes=axes,
                line=lineno,
                link=link,
                dcn_bytes=dcn_bytes,
            )
        )
    return out


def _attr(line: str, name: str) -> str:
    """Value of ``name=...`` in an HLO op line, balanced over {}/[]/()
    — handles the iota forms ``[4,2]<=[8]`` and
    ``[4,2]<=[2,2,2]T(2,1,0)``, which continue past their first ``]``."""
    idx = line.find(name + "=")
    if idx < 0:
        return ""
    i = idx + len(name) + 1
    depth = 0
    start = i
    while i < len(line):
        c = line[i]
        if c in "{[(":
            depth += 1
        elif c in "}])":
            depth -= 1
            if depth == 0 and line[i + 1:i + 2] not in ("<", "T"):
                return line[start:i + 1]
        elif c == "," and depth == 0:
            return line[start:i]
        i += 1
    return line[start:]


def collective_census(
    hlo_text: str, coords: MeshCoords
) -> Dict[str, Dict[str, int]]:
    """``{"all-gather|fsdp": {"count": N, "bytes": B}, ...}`` — the
    SC001 fingerprint. Bytes are per-device contributions (see
    ``parse_collectives``) summed over static ops (a scan body counts
    once: the fingerprint tracks the *program*, not the per-step issue
    count — accum lives in the comm ledger, not here).

    On a multislice assignment every cell additionally carries
    ``dcn_bytes`` — the modeled bytes its ops move across the slice
    boundary (0 for cells whose ops all stay on ICI). Cell KEYS are
    link-free on purpose: the flat and hierarchical programs label the
    same logical reduction ``…|dp`` on every topology, so their
    censuses stay comparable and only the link split differs."""
    multislice = coords.n_slices > 1
    census: Dict[str, Dict[str, int]] = {}
    for op in parse_collectives(hlo_text, coords):
        key = f"{op.kind}|{op.axes}"
        cell = census.setdefault(key, {"count": 0, "bytes": 0})
        if multislice:
            cell.setdefault("dcn_bytes", 0)
            cell["dcn_bytes"] += op.dcn_bytes
        cell["count"] += 1
        cell["bytes"] += op.bytes
    return census


def census_dcn_bytes(census: Dict[str, Dict[str, int]]) -> int:
    """Total modeled DCN bytes of a (multislice) census."""
    return sum(c.get("dcn_bytes", 0) for c in census.values())


# ---------------------------------------------------------------------------
# SC006 — exposed vs. overlapped DCN bytes (schedule analysis)
# ---------------------------------------------------------------------------
#
# The census counts WHAT crosses the slice boundary; this section asks
# WHEN — can the transfer hide behind compute, or does the step stall
# on it?  It reads the post-GSPMD HLO as a graph of computations and
# classifies every DCN collective as OVERLAPPED or EXPOSED:
#
# - **async pairs** (``-start``/``-done``, how a latency-hiding TPU
#   schedule spells overlap): overlapped iff some compute-class op in
#   the same computation is neither an ancestor of the start nor a
#   descendant of the done — i.e. the scheduler has real work to run
#   while the transfer is in flight.
# - **sync collectives** (CPU contract programs — the CPU backend never
#   emits async pairs, so structure must stand in for the schedule): a
#   DCN collective is overlapped iff it executes inside a ``while``
#   body AND its transitive operand closure *within that body* contains
#   no compute-class op — it consumes only loop-carried state (gtes
#   through passive reshapes/concats), so it is issueable at iteration
#   entry, concurrent with the whole iteration's compute.  This is the
#   shape ``overlap_value_and_grad`` lowers to: the exchange of micro
#   k-1's gradients rides the loop carry while micro k's backward runs.
#   Deliberately conservative: a collective fed by ANY in-iteration
#   compute (the fused hierarchical engine's per-micro DCN leg, the
#   loss psum) counts exposed even though XLA may find partial overlap
#   — partial credit would let a re-serializing change hide behind
#   scheduler luck.
#
# Bytes are weighted by the product of enclosing loop trip counts
# (``backend_config known_trip_count``) so "exposed bytes per step"
# compares schedules honestly: a DCN leg issued once per microbatch
# inside a trip-N accumulation scan costs N transfers; the overlap
# schedule's single post-scan flush costs one.

#: opcodes that ARE the work a transfer could hide behind (plus any
#: collective: a DCN op gated on another transfer is not issueable at
#: iteration entry)
_COMPUTE_OPS = frozenset({
    "dot", "convolution", "cholesky", "triangular-solve", "fft",
    "custom-call", "scatter", "sort",
})

_COMPUTATION_HEAD_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(")
_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_REF_RE = re.compile(r"%([\w.\-]+)")
#: optional shape prefix (absent after a tuple-shaped result has been
#: skipped — ``(s32[], f32[2]{0}) while(...)``), then the opcode; a
#: shape can never false-match the opcode group (``[`` follows it, not
#: ``(``)
_SHAPE_OPCODE_RE = re.compile(
    r"(?:[a-z][a-z0-9]*\[[0-9,]*\](?:\{[^}]*\})?\s*)?([a-z][a-z0-9\-]*)\("
)
_TRIP_RE = re.compile(r"\"known_trip_count\":\{\"n\":\"(\d+)\"\}")


@dataclasses.dataclass
class _HloInstr:
    name: str
    opcode: str
    line: int  # 1-indexed line in the module text
    operands: Tuple[str, ...]  # same-computation value refs
    called: Tuple[str, ...]  # computations fusion/call/cond branches run
    body: str = ""  # while only: the body computation
    trip: int = 1  # while only: known_trip_count (1 when unknown)


@dataclasses.dataclass
class _HloComputation:
    name: str
    entry: bool
    instrs: Dict[str, _HloInstr] = dataclasses.field(default_factory=dict)


def _split_instr_rhs(rhs: str) -> Tuple[str, str, str]:
    """``(opcode, operand_segment, attr_tail)`` of an HLO instruction's
    right-hand side. Tuple-shaped results (``(s32[], f32[2]{0}) while``)
    are skipped by balanced-paren counting — layout tiles like
    ``{1,0:T(8,128)}`` keep parens balanced, so this survives them."""
    s = rhs.lstrip()
    if s.startswith("("):
        depth = 0
        for i, ch in enumerate(s):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    s = s[i + 1:].lstrip()
                    break
    m = _SHAPE_OPCODE_RE.match(s)
    if not m:
        return "", "", ""
    opcode = m.group(1)
    depth, i = 1, m.end()
    start = i
    while i < len(s) and depth:
        if s[i] == "(":
            depth += 1
        elif s[i] == ")":
            depth -= 1
        i += 1
    return opcode, s[start:i - 1], s[i:]


def _called_computations(attr_tail: str) -> Tuple[List[str], str]:
    """``(called, body)``: computation refs in the attributes that mean
    "this op RUNS that computation" (fusion/call/conditional/while —
    NOT ``to_apply`` reducers, which are scalar add/max lambdas), and
    the while body specifically."""
    called: List[str] = []
    body = ""
    for key in ("calls", "body", "condition", "branch_computations"):
        val = _attr(attr_tail, key)
        if not val:
            continue
        refs = _REF_RE.findall(val)
        called.extend(refs)
        if key == "body" and refs:
            body = refs[0]
    return called, body


def _parse_hlo_module(hlo_text: str) -> Dict[str, _HloComputation]:
    """The module as named computations of def-use-linked instructions.
    Line-oriented, like the rest of this file: optimized HLO prints one
    instruction per line and closes every computation with ``}``."""
    comps: Dict[str, _HloComputation] = {}
    current: Optional[_HloComputation] = None
    for lineno, line in enumerate(hlo_text.splitlines(), start=1):
        if current is None:
            m = _COMPUTATION_HEAD_RE.match(line)
            if m and line.rstrip().endswith("{"):
                current = _HloComputation(
                    name=m.group(2), entry=m.group(1) is not None
                )
                comps[current.name] = current
            continue
        if line.startswith("}"):
            current = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2)
        opcode, operand_seg, attr_tail = _split_instr_rhs(rhs)
        if not opcode:
            continue
        called, body = _called_computations(attr_tail)
        trip_m = _TRIP_RE.search(attr_tail)
        current.instrs[name] = _HloInstr(
            name=name,
            opcode=opcode,
            line=lineno,
            operands=tuple(_REF_RE.findall(operand_seg)),
            called=tuple(called),
            body=body,
            trip=int(trip_m.group(1)) if trip_m else 1,
        )
    return comps


def _while_body_context(
    comps: Dict[str, _HloComputation]
) -> Dict[str, Tuple[str, int]]:
    """``{body_computation: (computation holding the while, trip)}``."""
    ctx: Dict[str, Tuple[str, int]] = {}
    for comp in comps.values():
        for ins in comp.instrs.values():
            if ins.opcode == "while" and ins.body:
                ctx[ins.body] = (comp.name, ins.trip)
    return ctx


def _trip_product(
    comp_name: str, while_ctx: Dict[str, Tuple[str, int]]
) -> int:
    """Product of trip counts of every loop enclosing ``comp_name``
    (1 for entry-level code)."""
    product, seen = 1, set()
    while comp_name in while_ctx and comp_name not in seen:
        seen.add(comp_name)
        comp_name, trip = while_ctx[comp_name]
        product *= max(trip, 1)
    return product


def _is_collective_opcode(opcode: str) -> bool:
    return any(
        opcode == c or opcode.startswith(c + "-") for c in COLLECTIVE_OPS
    )


def _computation_has_compute(
    name: str, comps: Dict[str, _HloComputation], memo: Dict[str, bool]
) -> bool:
    if name not in comps:
        return False
    if name in memo:
        return memo[name]
    memo[name] = False  # cycle guard (HLO call graphs are acyclic)
    memo[name] = any(
        _is_compute_instr(ins, comps, memo)
        for ins in comps[name].instrs.values()
    )
    return memo[name]


def _is_compute_instr(
    ins: _HloInstr, comps: Dict[str, _HloComputation], memo: Dict[str, bool]
) -> bool:
    if ins.opcode in _COMPUTE_OPS or _is_collective_opcode(ins.opcode):
        return True
    if ins.called:  # fusion / call / while / conditional
        return any(
            _computation_has_compute(c, comps, memo) for c in ins.called
        )
    return False


def _closure_has_compute(
    start: _HloInstr,
    comp: _HloComputation,
    comps: Dict[str, _HloComputation],
    memo: Dict[str, bool],
) -> bool:
    """Does the transitive operand closure of ``start`` WITHIN ``comp``
    contain a compute-class instruction?  (Refs that are not local
    instruction names — parameters, computation names — terminate.)"""
    stack, seen = list(start.operands), set()
    while stack:
        ref = stack.pop()
        if ref in seen:
            continue
        seen.add(ref)
        ins = comp.instrs.get(ref)
        if ins is None:
            continue
        if _is_compute_instr(ins, comps, memo):
            return True
        stack.extend(ins.operands)
    return False


def _async_pair_overlapped(
    start: _HloInstr,
    comp: _HloComputation,
    comps: Dict[str, _HloComputation],
    memo: Dict[str, bool],
) -> bool:
    """``-start``/``-done`` rule: overlapped iff some compute-class op
    in the same computation is ordered with NEITHER half — not an
    ancestor of the start, not a descendant of the done — so the
    scheduler can run it while the transfer is in flight."""
    done = next(
        (
            i for i in comp.instrs.values()
            if i.opcode.endswith("-done") and start.name in i.operands
        ),
        None,
    )
    users: Dict[str, List[str]] = {}
    for ins in comp.instrs.values():
        for ref in ins.operands:
            users.setdefault(ref, []).append(ins.name)

    def _reach(roots: Iterable[str], edges) -> set:
        out, stack = set(), list(roots)
        while stack:
            ref = stack.pop()
            if ref in out:
                continue
            out.add(ref)
            stack.extend(edges(ref))
        return out

    ancestors = _reach(
        start.operands,
        lambda r: comp.instrs[r].operands if r in comp.instrs else (),
    )
    descendants = _reach(
        users.get(done.name, []) if done is not None else [],
        lambda r: users.get(r, []),
    )
    ordered = ancestors | descendants | {start.name}
    if done is not None:
        ordered.add(done.name)
    return any(
        ins.name not in ordered and _is_compute_instr(ins, comps, memo)
        for ins in comp.instrs.values()
    )


def overlap_report(
    hlo_text: str,
    coords: MeshCoords,
    collectives: Optional[List[CollectiveOp]] = None,
) -> Dict:
    """Classify every DCN collective of an optimized multislice program
    as overlapped or exposed (module docstring above) and total the
    trip-weighted bytes:

    ``{"dcn_exposed_bytes", "dcn_overlapped_bytes", "overlap_ratio",
    "ops": [...]}``

    ``overlap_ratio`` = overlapped / (overlapped + exposed), 0.0 when
    the program moves no DCN bytes at all.  ``ops`` carries the
    per-collective verdicts for the CLI/bench surface; the contract
    stores only the three totals."""
    if collectives is None:
        collectives = parse_collectives(hlo_text, coords)
    dcn = [op for op in collectives if op.link == "dcn" and op.dcn_bytes]
    exposed = overlapped = 0
    rows: List[Dict] = []
    if dcn:
        comps = _parse_hlo_module(hlo_text)
        line_map: Dict[int, Tuple[_HloComputation, _HloInstr]] = {}
        for comp in comps.values():
            for ins in comp.instrs.values():
                line_map[ins.line] = (comp, ins)
        while_ctx = _while_body_context(comps)
        memo: Dict[str, bool] = {}
        for op in dcn:
            hit = line_map.get(op.line)
            if hit is None:  # unparseable line: count it exposed
                exposed += op.dcn_bytes
                continue
            comp, ins = hit
            weight = _trip_product(comp.name, while_ctx)
            nbytes = op.dcn_bytes * weight
            if ins.opcode.endswith("-start"):
                is_overlapped = _async_pair_overlapped(
                    ins, comp, comps, memo
                )
            else:
                is_overlapped = (
                    comp.name in while_ctx
                    and not _closure_has_compute(ins, comp, comps, memo)
                )
            if is_overlapped:
                overlapped += nbytes
            else:
                exposed += nbytes
            rows.append({
                "kind": op.kind,
                "line": op.line,
                "dcn_bytes": nbytes,
                "overlapped": is_overlapped,
            })
    total = exposed + overlapped
    return {
        "dcn_exposed_bytes": int(exposed),
        "dcn_overlapped_bytes": int(overlapped),
        "overlap_ratio": round(overlapped / total, 4) if total else 0.0,
        "ops": rows,
    }


#: SC006: a re-serialization may keep the ratio but still regress the
#: absolute stall (payload growth); exposed bytes get the same growth
#: tolerance as SC001, the ratio an absolute slack for float noise
OVERLAP_RATIO_SLACK = 0.02


def check_overlap_against_contract(
    program: StepProgram,
    contract: Dict,
    byte_tolerance: float = DEFAULT_BYTE_TOLERANCE,
    report: Optional[Dict] = None,
) -> List[Violation]:
    """SC006: diff the program's exposed-vs-overlapped DCN split
    against the contract's recorded ``overlap`` section.  Fails when
    exposed bytes grew beyond tolerance or the overlap ratio dropped —
    both spell "a change re-serialized the DCN leg the schedule used
    to hide".  Silent when the contract has no ``overlap`` section
    (pre-overlap contract vintage) or on a config-hash mismatch (SC001
    already reports that)."""
    ref = contract.get("overlap")
    if not ref:
        return []
    if contract.get("config_hash") and program.config_hash and \
            contract["config_hash"] != program.config_hash:
        return []
    if report is None:
        report = overlap_report(program.hlo, program.coords())
    out: List[Violation] = []
    ref_exposed = ref.get("dcn_exposed_bytes", 0)
    got_exposed = report["dcn_exposed_bytes"]
    if got_exposed > ref_exposed * (1.0 + byte_tolerance) and \
            got_exposed > ref_exposed:
        out.append(
            program.violation(
                "SC006",
                f"exposed DCN bytes grew {ref_exposed} -> {got_exposed} "
                f"(> {byte_tolerance:.0%} tolerance): the step now "
                "STALLS on slice-boundary transfers the contract "
                "records as hidden behind compute — a change "
                "re-serialized the DCN schedule.",
            )
        )
    ref_ratio = float(ref.get("overlap_ratio", 0.0))
    got_ratio = report["overlap_ratio"]
    if ref_ratio > 0.0 and got_ratio < ref_ratio - OVERLAP_RATIO_SLACK:
        out.append(
            program.violation(
                "SC006",
                f"DCN overlap_ratio dropped {ref_ratio:.4f} -> "
                f"{got_ratio:.4f}: transfers the overlap schedule "
                "pipelined behind the accumulation scan are exposed "
                "again — justify and --fix-contracts, or restore the "
                "schedule.",
            )
        )
    return out


# ---------------------------------------------------------------------------
# SC008 — pipeline-schedule contract (bubble fraction + stage handoffs)
# ---------------------------------------------------------------------------
#
# The census counts the pp collectives; SC008 asks whether the
# SCHEDULE that issues them survived. Two dimensions, both recorded in
# the contract's ``pp_schedule`` section:
#
# - **bubble fraction** — the analytic steady-state pipeline bubble of
#   the declared schedule geometry, ``(p-1)/(m·v)`` for (interleaved)
#   1F1B over ideal compute ticks (with ``v = p`` virtual stages this
#   is the classic ``(p-1)/(p·m)``), ``(p-1)/m`` for GPipe-style
#   serial fill/drain. A change that re-serializes the schedule (drops
#   virtual stages, shrinks the microbatch count, flips to gpipe)
#   grows the fraction and fails the diff — same shape as SC006's
#   "the stall came back" check, applied to pp instead of DCN.
# - **stage-handoff pattern** — the static ``collective-permute|pp``
#   instance count of the lowered program. The explicit 1F1B engine
#   unrolls its tick table, so each scheduled hop is its own HLO op; a
#   re-serialization that rolls the handoffs into a scan (or prunes
#   scheduled hops) collapses this count even when the census bytes
#   stay plausible.

#: analytic bubble-fraction slack: the contract stores the model's
#: fraction, the program recomputes it from its own geometry — any
#: real schedule change moves it by >= 1/(m·v), far above float noise
BUBBLE_FRACTION_SLACK = 0.005
#: stage-handoff count tolerance: XLA may merge/split a permute pair
#: across versions; a schedule change moves the count by O(ticks)
PP_PERMUTE_COUNT_TOLERANCE = 0.10


def schedule_bubble_fraction(
    schedule: str, pp: int, microbatches: int, virtual_stages: int = 1
) -> float:
    """Steady-state pipeline bubble of the engine's schedules, as a
    fraction of ideal compute ticks (parallel/pp_schedule.py tick
    model: every microbatch×chunk costs one forward and one backward
    tick per stage).

    - interleaved 1f1b: fill/drain costs ``2(p-1)`` chunk-granular
      ticks against ``2·m·v`` ideal ticks -> ``(p-1)/(m·v)``; with the
      bench geometry ``v = p`` this is the paper's ``(p-1)/(p·m)``.
    - gpipe / non-interleaved 1f1b (``v = 1``): ``(p-1)/m`` — the
      fill/drain is microbatch-granular, so losing interleave DOUBLES
      the bubble at ``v = 2`` and the contract diff sees it.
    """
    p = max(1, int(pp))
    m = max(1, int(microbatches))
    v = max(1, int(virtual_stages)) if schedule == "1f1b" else 1
    if p == 1:
        return 0.0
    return (p - 1) / float(m * v)


def pp_schedule_report(
    program: StepProgram,
    collectives: Optional[List[CollectiveOp]] = None,
) -> Optional[Dict]:
    """The program's pp-schedule fingerprint, or None when the mesh
    has no pp axis. Geometry fields come from the lowering hints
    (``program.pp_schedule``); the handoff evidence from the HLO —
    every collective-permute whose pairs vary over ``pp`` (attribution
    is link-free, so single- and multislice programs fingerprint
    identically):

    - ``ppermute_calls``: static op count. The per-stage layer
      re-layout permutes at schedule entry/exit live here.
    - ``ppermute_hops``: the same ops weighted by their enclosing
      loop trip counts (SC006's honesty rule) — the tick loop rolls
      the per-tick ring hops into a ``while`` whose trip count IS the
      schedule length, so a re-serialization that stretches the
      schedule moves this number even when the static count holds."""
    p = program.axis_sizes.get("pp", 1)
    if p <= 1:
        return None
    if collectives is None:
        collectives = parse_collectives(program.hlo, program.coords())
    pp_ops = [
        op for op in collectives
        if op.kind == "collective-permute" and "pp" in op.axes.split("+")
    ]
    permutes = len(pp_ops)
    hops = 0
    if pp_ops:
        comps = _parse_hlo_module(program.hlo)
        line_map: Dict[int, str] = {}
        for comp in comps.values():
            for ins in comp.instrs.values():
                line_map[ins.line] = comp.name
        while_ctx = _while_body_context(comps)
        for op in pp_ops:
            comp_name = line_map.get(op.line)
            hops += (
                _trip_product(comp_name, while_ctx) if comp_name else 1
            )
    out = {
        "pp": int(p),
        "ppermute_calls": int(permutes),
        "ppermute_hops": int(hops),
    }
    hints = program.pp_schedule or {}
    if hints.get("schedule"):
        m = int(hints.get("microbatches", p))
        v = int(hints.get("virtual_stages", 1))
        out.update({
            "schedule": hints["schedule"],
            "microbatches": m,
            "virtual_stages": v,
            "bubble_fraction": round(
                schedule_bubble_fraction(hints["schedule"], p, m, v), 6
            ),
        })
    return out


def check_pp_schedule_against_contract(
    program: StepProgram,
    contract: Dict,
    report: Optional[Dict] = None,
) -> List[Violation]:
    """SC008: diff the program's pipeline-schedule fingerprint against
    the contract's ``pp_schedule`` section. Fails when the analytic
    bubble fraction grew (the schedule re-serialized — fewer virtual
    stages, fewer microbatches, a gpipe fallback) or the static
    stage-handoff pattern collapsed/exploded. Silent when the contract
    has no ``pp_schedule`` section (non-pp contract) or on a
    config-hash mismatch (SC001 already reports that)."""
    ref = contract.get("pp_schedule")
    if not ref:
        return []
    if contract.get("config_hash") and program.config_hash and \
            contract["config_hash"] != program.config_hash:
        return []
    if report is None:
        report = pp_schedule_report(program)
    out: List[Violation] = []
    if report is None:
        out.append(
            program.violation(
                "SC008",
                f"contract pins a pipeline schedule over pp="
                f"{ref.get('pp')} but the program's mesh has no pp "
                "axis — the pipeline engine was bypassed entirely; "
                "justify and --fix-contracts, or restore the pp "
                "layout.",
            )
        )
        return out
    ref_frac = float(ref.get("bubble_fraction", 0.0))
    got_frac = report.get("bubble_fraction")
    if ref_frac > 0.0 and got_frac is not None and \
            got_frac > ref_frac + BUBBLE_FRACTION_SLACK:
        out.append(
            program.violation(
                "SC008",
                f"pipeline bubble fraction grew {ref_frac:.4f} -> "
                f"{got_frac:.4f} (schedule "
                f"{ref.get('schedule')}/m={ref.get('microbatches')}/"
                f"v={ref.get('virtual_stages')} -> "
                f"{report.get('schedule')}/m={report.get('microbatches')}"
                f"/v={report.get('virtual_stages')}): the schedule "
                "re-serialized — stages idle through a longer "
                "fill/drain than the contract records. Justify and "
                "--fix-contracts, or restore the interleaved 1F1B "
                "schedule.",
            )
        )
    for dim, what in (
        ("ppermute_calls", "static stage-handoff op count"),
        ("ppermute_hops", "trip-weighted stage-handoff executions"),
    ):
        ref_n = int(ref.get(dim, 0))
        got_n = int(report[dim])
        if ref_n <= 0:
            continue
        lo = ref_n * (1.0 - PP_PERMUTE_COUNT_TOLERANCE)
        hi = ref_n * (1.0 + PP_PERMUTE_COUNT_TOLERANCE)
        if not (lo <= got_n <= hi):
            out.append(
                program.violation(
                    "SC008",
                    f"stage-handoff pattern changed: {what} "
                    f"{ref_n} in the contract, {got_n} in the program "
                    f"(> {PP_PERMUTE_COUNT_TOLERANCE:.0%} tolerance). "
                    "The tick loop's trip count IS the schedule "
                    "length — a grown hop count means the schedule "
                    "stretched (extra serial ticks), a collapsed one "
                    "means scheduled hops were pruned. Justify and "
                    "--fix-contracts, or restore the schedule.",
                )
            )
    return out


# ---------------------------------------------------------------------------
# StableHLO entry-signature parsing (SC002/SC003/SC004 substrate)
# ---------------------------------------------------------------------------


_ATTR_BLOCK = r"\{((?:[^{}\"]|\"[^\"]*\")*)\}"
_ARG_RE = re.compile(r"%arg(\d+): tensor<([^>]+)>\s*" + _ATTR_BLOCK)
_RESULT_RE = re.compile(r"tensor<([^>]+)>\s*(?:" + _ATTR_BLOCK + r")?")
_SHARDING_CONSTRAINT_RE = re.compile(
    r"stablehlo\.custom_call @Sharding\(.*?mhlo\.sharding = "
    r"\"([^\"]*)\".*?->\s*tensor<([^>]+)>"
)
_DOT_GENERAL_RE = re.compile(
    r"stablehlo\.dot_general\b.*?->\s*tensor<([^>]+)>"
)


@dataclasses.dataclass(frozen=True)
class EntryArg:
    index: int
    type_str: str
    sharding: Optional[str]
    aliases_output: Optional[int]


@dataclasses.dataclass(frozen=True)
class EntryResult:
    index: int
    type_str: str
    sharding: Optional[str]
    result_info: str  # jax.result_info pytree path, e.g. "[0]['params']…"


def parse_entry_signature(
    stablehlo: str,
) -> Tuple[List[EntryArg], List[EntryResult]]:
    """Args and results of the public @main func, with shardings and
    donation links. jax prints the signature on one (very long) line;
    we slice text between ``@main(`` and the body-opening ``{``."""
    start = stablehlo.find("@main(")
    if start < 0:
        return [], []
    arrow = stablehlo.find(") -> (", start)
    if arrow < 0:
        return [], []
    arg_text = stablehlo[start:arrow]
    # results end at the paren closing the tuple opened by ") -> (" —
    # scanned with quote awareness: sharding strings contain parens
    # (iota transposes like T(1,0)) and braces
    i = arrow + len(") -> (")
    depth = 1
    in_quote = False
    end = len(stablehlo)
    while i < len(stablehlo):
        c = stablehlo[i]
        if c == '"':
            in_quote = not in_quote
        elif not in_quote:
            if c == "(":
                depth += 1
            elif c == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        i += 1
    result_text = stablehlo[arrow + len(") -> ("):end]

    args: List[EntryArg] = []
    for m in _ARG_RE.finditer(arg_text):
        attrs = m.group(3)
        sh = re.search(r'mhlo\.sharding = "([^"]*)"', attrs)
        al = re.search(r"tf\.aliasing_output = (\d+)", attrs)
        args.append(
            EntryArg(
                index=int(m.group(1)),
                type_str=m.group(2),
                sharding=sh.group(1) if sh else None,
                aliases_output=int(al.group(1)) if al else None,
            )
        )
    # bare-typed args (no attr block) won't match _ARG_RE; they carry
    # neither sharding nor aliasing, which is exactly "nothing to check"

    results: List[EntryResult] = []
    for i, m in enumerate(_RESULT_RE.finditer(result_text)):
        attrs = m.group(2) or ""
        sh = re.search(r'mhlo\.sharding = "([^"]*)"', attrs)
        info = re.search(r'jax\.result_info = "([^"]*)"', attrs)
        results.append(
            EntryResult(
                index=i,
                type_str=m.group(1),
                sharding=sh.group(1) if sh else None,
                result_info=info.group(1) if info else "",
            )
        )
    return args, results


# ---------------------------------------------------------------------------
# the analysis context
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StepProgram:
    """Everything shardcheck knows about one lowered step program.

    ``label`` names the program in findings (a pseudo-path, so the
    engine's Violation/report machinery can render them). Semantic
    hints (``seq_len``/``vocab``) gate SC003 — without them the rule
    stays silent rather than guessing."""

    label: str
    stablehlo: str = ""
    hlo: str = ""
    axis_sizes: Dict[str, int] = dataclasses.field(default_factory=dict)
    seq_len: Optional[int] = None
    vocab: Optional[int] = None
    world: int = 0
    config_hash: str = ""
    #: the step was built with zero-1 weight-update sharding: arms the
    #: SC002 replicated-optimizer-moment check (a moment the sharding
    #: rule left replicated across dp>1 defeats the feature's point)
    zero1: bool = False
    #: slices the device assignment spans (slice-major layout): >1
    #: arms the per-link (ici/dcn) census attribution — set for ANY
    #: multislice program, flat or hierarchical, so the census always
    #: shows what the slow link carries
    n_slices: int = 1
    #: the step was built with the latency-hiding overlap schedule
    #: (ops/hier_collectives.py overlap_value_and_grad): arms the
    #: SC006 exposed-vs-overlapped DCN-bytes contract dimension
    overlap: bool = False
    #: gradient-accumulation factor of the step — the overlap analysis
    #: weights in-scan DCN legs by the scan trip count so "exposed
    #: bytes per step" compares schedules honestly (hier-flat exposes
    #: its DCN leg once per MICROBATCH; overlap once per step)
    accum_steps: int = 1
    #: pipeline-schedule geometry hints when the program runs pp > 1:
    #: ``{"schedule": "1f1b"|"gpipe", "microbatches": m,
    #: "virtual_stages": v}`` — arms the SC008 bubble-fraction /
    #: stage-handoff contract dimension. None on non-pp programs (and
    #: on callers that lower without the hints: SC008 then checks the
    #: structural census only).
    pp_schedule: Optional[Dict] = None

    def coords(self) -> MeshCoords:
        return MeshCoords(self.axis_sizes, n_slices=self.n_slices)

    @property
    def data_axis_product(self) -> int:
        """Combined size of the batch-sharding axes (dp·fsdp·ep) — the
        ways a data-parallel tensor *could* be sharded."""
        n = 1
        for axis in ("dp", "fsdp", "ep"):
            n *= self.axis_sizes.get(axis, 1)
        return n

    def violation(
        self,
        rule: str,
        message: str,
        line: int = 1,
        snippet: str = "",
        severity: str = Severity.ERROR,
    ) -> Violation:
        return Violation(
            rule=rule,
            path=self.label,
            line=line,
            col=0,
            message=message,
            snippet=snippet[:160],
            severity=severity,
        )


# ---------------------------------------------------------------------------
# SC001 — collective census vs. contract
# ---------------------------------------------------------------------------


def check_census_against_contract(
    program: StepProgram,
    contract: Dict,
    byte_tolerance: float = DEFAULT_BYTE_TOLERANCE,
    census: Optional[Dict[str, Dict[str, int]]] = None,
) -> List[Violation]:
    """Diff the program's census against a checked-in contract.

    Fails on: a collective cell (op × axes) the contract has never
    seen; count growth in an existing cell; byte growth beyond
    ``byte_tolerance``. Shrinkage passes but is reported as a stale
    note by the CLI (regenerate with ``--fix-contracts`` to bank the
    improvement) — mirroring the graftlint baseline workflow.

    ``census``: pass a precomputed census to skip re-parsing the HLO
    (the CLI computes it once for the check, summary and
    improvements note)."""
    out: List[Violation] = []
    if census is None:
        census = collective_census(program.hlo, program.coords())
    want: Dict[str, Dict[str, int]] = contract.get("census", {})
    if contract.get("config_hash") and program.config_hash and \
            contract["config_hash"] != program.config_hash:
        out.append(
            program.violation(
                "SC001",
                f"contract config_hash {contract['config_hash']} != "
                f"program {program.config_hash}: the contract was "
                "generated for a different model/trainer config — "
                "regenerate with --fix-contracts",
            )
        )
        return out
    for key in sorted(census):
        got = census[key]
        ref = want.get(key)
        if ref is None:
            out.append(
                program.violation(
                    "SC001",
                    f"new collective {key}: {got['count']} op(s), "
                    f"{got['bytes']} bytes — not in the contract. A new "
                    "collective on this axis means the partitioner now "
                    "moves data it did not before; justify and "
                    "--fix-contracts, or fix the sharding.",
                    snippet=key,
                )
            )
            continue
        if got["count"] > ref["count"]:
            out.append(
                program.violation(
                    "SC001",
                    f"collective {key} count grew {ref['count']} -> "
                    f"{got['count']}",
                    snippet=key,
                )
            )
        allowed = ref["bytes"] * (1.0 + byte_tolerance)
        if got["bytes"] > allowed and got["bytes"] > ref["bytes"]:
            out.append(
                program.violation(
                    "SC001",
                    f"collective {key} bytes grew {ref['bytes']} -> "
                    f"{got['bytes']} (> {byte_tolerance:.0%} tolerance)",
                    snippet=key,
                )
            )
        if contract.get("n_slices", 1) > 1:
            # the slow-link veto: a cell whose modeled DCN bytes grew
            # beyond tolerance moved traffic onto the inter-slice link
            # — the exact regression the hierarchical strategy exists
            # to prevent (a contract without slice info records no
            # dcn_bytes and skips this arm)
            ref_dcn = ref.get("dcn_bytes", 0)
            got_dcn = got.get("dcn_bytes", 0)
            if got_dcn > ref_dcn * (1.0 + byte_tolerance) and \
                    got_dcn > ref_dcn:
                out.append(
                    program.violation(
                        "SC001",
                        f"collective {key} DCN bytes grew {ref_dcn} -> "
                        f"{got_dcn}: the program moves more traffic "
                        "across the slice boundary than the contract "
                        "records — the slow link now carries what ICI "
                        "used to.",
                        snippet=key,
                    )
                )
    return out


def census_improvements(
    program_census: Dict[str, Dict[str, int]], contract: Dict
) -> List[str]:
    """Cells where the program now does LESS communication than the
    contract records (vanished, fewer ops, or fewer bytes)."""
    want: Dict[str, Dict[str, int]] = contract.get("census", {})
    notes = []
    for key in sorted(want):
        got = program_census.get(key)
        if got is None:
            notes.append(f"{key}: gone (contract has {want[key]['count']})")
        elif (
            got["count"] < want[key]["count"]
            or got["bytes"] < want[key]["bytes"]
        ):
            notes.append(
                f"{key}: {want[key]['count']}/{want[key]['bytes']}B -> "
                f"{got['count']}/{got['bytes']}B"
            )
        elif got.get("dcn_bytes", 0) < want[key].get("dcn_bytes", 0):
            notes.append(
                f"{key}: dcn {want[key]['dcn_bytes']}B -> "
                f"{got['dcn_bytes']}B (less on the slow link)"
            )
    return notes


# ---------------------------------------------------------------------------
# SC002 — replicated large tensor
# ---------------------------------------------------------------------------


def check_replicated_large(
    program: StepProgram,
    threshold_bytes: int = DEFAULT_REPLICATED_BYTES,
) -> List[Violation]:
    """An explicit ``@Sharding`` constraint that leaves a tensor above
    ``threshold_bytes`` fully replicated while the mesh has data axes
    to shard it over. Scope: constraint sites only — unannotated
    intermediates are XLA's placement choice and fire SC001 via the
    collectives they imply; entry params are the caller's placement
    (pure-dp legitimately replicates every parameter)."""
    out: List[Violation] = []
    if program.data_axis_product <= 1:
        return out
    for lineno, line in enumerate(program.stablehlo.splitlines(), start=1):
        m = _SHARDING_CONSTRAINT_RE.search(line)
        if not m:
            continue
        sharding = parse_sharding(m.group(1))
        nbytes = tensor_type_bytes(m.group(2))
        if nbytes < threshold_bytes:
            continue
        replicated = sharding.kind == "replicated" or (
            sharding.kind == "tiled"
            and sharding.replicate_ways >= program.data_axis_product
            and sharding.tile_count == 1
        )
        if replicated:
            out.append(
                program.violation(
                    "SC002",
                    f"sharding constraint pins tensor<{m.group(2)}> "
                    f"({nbytes} bytes) fully replicated "
                    f"({sharding.raw}) while the mesh has "
                    f"{program.data_axis_product} data-parallel ways to "
                    "shard it — every device holds the whole tensor.",
                    line=lineno,
                    snippet=line.strip(),
                )
            )
    return out


def check_replicated_moments(
    program: StepProgram,
    threshold_bytes: int = DEFAULT_REPLICATED_BYTES,
) -> List[Violation]:
    """SC002, zero-1 arm: a large OPTIMIZER-STATE leaf still replicated
    across dp while the step was built with weight-update sharding on.

    The moments are entry/results, not ``@Sharding`` sites, so the base
    rule never sees them; with zero-1 off their dp replication is the
    documented cost of pure-dp. With zero-1 ON it means the sharding
    rule fell back (non-divisible leading dims) on a leaf big enough
    that the fallback defeats the feature — resolve by reshaping the
    param or accepting it with a contract note. Detection reads the
    pinned output shardings of the ``[0]['opt']…`` results (the step's
    returned optimizer state): ``replicated``, or untiled with a
    replication factor covering the dp ways. Same precision limit as
    the base rule: the sharding string cannot attribute replication to
    a *specific* mesh axis, so a moment that is tiled over some other
    axis (sp/tp) yet still replicated across dp escapes — the
    conservative direction; the alternative misreads a correctly
    dp-sharded, sp-replicated moment as a fallback and (strict mode)
    vetoes a correct build."""
    out: List[Violation] = []
    dp = program.axis_sizes.get("dp", 1)
    if not program.zero1 or dp <= 1:
        return out
    _, results = parse_entry_signature(program.stablehlo)
    for res in results:
        if not res.result_info.startswith("[0]"):
            continue
        if "'opt'" not in res.result_info:
            continue
        nbytes = tensor_type_bytes(res.type_str)
        if nbytes < threshold_bytes:
            continue
        if res.sharding is None:
            continue  # unpinned outputs are SC004's finding
        sharding = parse_sharding(res.sharding)
        replicated = sharding.kind == "replicated" or (
            sharding.kind == "tiled"
            and sharding.tile_count == 1
            and sharding.replicate_ways >= dp
        )
        if replicated:
            out.append(
                program.violation(
                    "SC002",
                    f"zero-1 is on but optimizer moment "
                    f"{res.result_info} (tensor<{res.type_str}>, "
                    f"{nbytes} bytes) is replicated across dp={dp} "
                    f"({sharding.raw}): the weight-update sharding "
                    "rule fell back on this leaf — every dp rank "
                    "still holds the whole moment.",
                    snippet=f"{res.result_info}: {res.sharding}",
                )
            )
    return out


# ---------------------------------------------------------------------------
# SC003 — dense seq×vocab materialization
# ---------------------------------------------------------------------------


def check_dense_vocab(program: StepProgram) -> List[Violation]:
    """A float ``dot_general`` whose RESULT carries both the sequence
    dim and the FULL vocab dim — the dense-logits materialization
    chunked-CE exists to kill. Anchored on dot_general so the one-hot
    embedding lookup (a [B,T,V] *operand* contracted away in the same
    dot) and chunked CE (result carries chunk < vocab columns) stay
    clean. Needs the seq/vocab hints; silent without them."""
    out: List[Violation] = []
    seq, vocab = program.seq_len, program.vocab
    if not seq or not vocab or seq == vocab:
        # seq == vocab would make every attention score matrix look
        # like logits; a config that degenerate cannot be gated here
        return out
    for lineno, line in enumerate(program.stablehlo.splitlines(), start=1):
        m = _DOT_GENERAL_RE.search(line)
        if not m:
            continue
        dims, dtype = tensor_type_dims(m.group(1))
        if not dtype.startswith("f"):
            continue
        if seq in dims and vocab in dims:
            out.append(
                program.violation(
                    "SC003",
                    f"dot_general materializes tensor<{m.group(1)}> "
                    f"carrying both seq={seq} and vocab={vocab}: dense "
                    "logits are back (peak activation O(B*T*V) — use "
                    "the chunked CE path, ops/chunked_ce.py).",
                    line=lineno,
                    snippet=line.strip(),
                )
            )
    return out


# ---------------------------------------------------------------------------
# SC004 — output-sharding drift
# ---------------------------------------------------------------------------


def check_output_sharding_drift(program: StepProgram) -> List[Violation]:
    """The step donates its state and returns it as the first tuple
    element (``jax.result_info`` paths under ``[0]``); the next step
    feeds that output straight back in, so every state output's
    sharding must be PINNED and IDENTICAL to its input's. Three ways
    the lowering shows a violation:

    - the output carries no ``mhlo.sharding`` at all: out_shardings
      were not pinned, XLA is free to return the leaf re-sharded (the
      PR 2 silent-recompile bug — caught here at lower time instead of
      via AOT rejection at the first post-resize step);
    - the output is pinned but its donation alias is GONE: jax drops
      ``tf.aliasing_output`` exactly when the donated input's sharding
      cannot alias the output's — i.e. the pin differs from the input
      (jax also warns "Some donated buffers were not usable");
    - alias intact but the sharding strings differ (bitcast-compatible
      layouts can still alias).

    Skips programs with no ``[0]``-prefixed results (not a step)."""
    out: List[Violation] = []
    args, results = parse_entry_signature(program.stablehlo)
    state_results = [
        r for r in results if r.result_info.startswith("[0]")
    ]
    if not state_results:
        return out
    aliased_arg = {
        a.aliases_output: a for a in args if a.aliases_output is not None
    }
    for res in state_results:
        name = res.result_info
        if res.sharding is None:
            out.append(
                program.violation(
                    "SC004",
                    f"state leaf {name} has no pinned output sharding: "
                    "XLA is free to return it re-sharded, changing the "
                    "next step's input signature (silent recompile "
                    "under jit, hard reject under AOT). Pin "
                    "out_shardings to the input state's shardings.",
                    snippet=f"{name}: -> <unconstrained>",
                )
            )
            continue
        arg = aliased_arg.get(res.index)
        if arg is None:
            out.append(
                program.violation(
                    "SC004",
                    f"state leaf {name} is pinned to {res.sharding} but "
                    "lost its donation alias — the donated input's "
                    "sharding differs from this output pin, so step "
                    "N+1's input signature differs from step N's (and "
                    "the donation saves no memory).",
                    snippet=f"{name}: <donation dropped> -> "
                    f"{res.sharding}",
                )
            )
        elif arg.sharding is not None and arg.sharding != res.sharding:
            out.append(
                program.violation(
                    "SC004",
                    f"state leaf {name} changes sharding across the "
                    f"step: in {arg.sharding} -> out {res.sharding}.",
                    snippet=f"{name}: {arg.sharding} -> {res.sharding}",
                )
            )
    return out


# ---------------------------------------------------------------------------
# SC005 — host transfer inside the step
# ---------------------------------------------------------------------------


def check_host_transfer(program: StepProgram) -> List[Violation]:
    """Host callbacks / infeed / outfeed / host send-recv inside the
    jitted step: each one stalls every participating device on the
    host once per step (or once per scan iteration). Detected in the
    optimized HLO (the partitioner cannot remove them) with a
    StableHLO fallback for text generated before compile."""
    out: List[Violation] = []
    text = program.hlo or program.stablehlo
    for lineno, line in enumerate(text.splitlines(), start=1):
        hit = None
        tgt = re.search(
            r'custom_call_target="([^"]+)"', line
        ) or re.search(r'stablehlo\.custom_call @([\w.\-]+)', line)
        if tgt:
            target = tgt.group(1)
            if target in _BENIGN_CUSTOM_CALLS:
                continue
            if any(h in target.lower() for h in _DEVICE_KERNEL_HINTS):
                # a Pallas/Mosaic device kernel: the opposite of a host
                # transfer. Tracked by the SC007 census, never SC005.
                continue
            if any(h in target.lower() for h in _HOST_CALLBACK_HINTS):
                hit = f"host callback custom-call {target}"
        if hit is None:
            if re.search(r"\binfeed\(", line):
                hit = "infeed"
            elif re.search(r"\boutfeed\(", line):
                hit = "outfeed"
            elif re.search(
                r"\b(send|recv|send-done|recv-done)\(", line
            ) and "is_host_transfer=true" in line:
                hit = "host send/recv"
        if hit:
            out.append(
                program.violation(
                    "SC005",
                    f"{hit} inside the jitted step: the device blocks "
                    "on the host every step (debug callbacks and "
                    "io_callback do not belong in the hot path — hoist "
                    "them out or gate them off for training builds).",
                    line=lineno,
                    snippet=line.strip(),
                )
            )
    return out


# ---------------------------------------------------------------------------
# SC007 — custom-call census (the kernel contract)
# ---------------------------------------------------------------------------

_CC_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_CC_SHAPE_RE = re.compile(r"\b[a-z]+[0-9]*\[[0-9,]*\]")


def custom_call_census(hlo_text: str) -> Dict[str, Dict]:
    """Every non-benign custom-call in the lowered text, keyed by
    target: ``{target: {"count": n, "sites": ["(operands) -> result"]}}``
    with ``sites`` the sorted unique shape signatures.

    This is the kernel inventory of the step program. A Pallas kernel
    that stops lowering (dispatcher flag flipped, ``fused_ce_available``
    regressed, a jax upgrade changing the Mosaic target name) does not
    error — the model silently takes the reference path and only the
    step time notices. Diffing this census against the contract makes
    the fallback loud. Partitioner plumbing (``Sharding`` & co.) is
    excluded: it says nothing about kernels and churns with GSPMD
    internals."""
    census: Dict[str, Dict] = {}
    for line in hlo_text.splitlines():
        if "custom-call" not in line and "custom_call" not in line:
            continue
        m = _CC_TARGET_RE.search(line) or re.search(
            r"stablehlo\.custom_call @([\w.\-]+)", line
        )
        if m is None:
            continue
        target = m.group(1)
        if target in _BENIGN_CUSTOM_CALLS:
            continue
        head, sep, tail = line.partition("custom-call(")
        operands = _CC_SHAPE_RE.findall(tail.split(")", 1)[0]) if sep \
            else []
        results = _CC_SHAPE_RE.findall(head) if sep else []
        res = results[0] if len(results) == 1 else \
            "(" + ", ".join(results) + ")"
        sig = f"({', '.join(operands)}) -> {res}"
        entry = census.setdefault(target, {"count": 0, "sites": []})
        entry["count"] += 1
        if sig not in entry["sites"]:
            entry["sites"].append(sig)
    for entry in census.values():
        entry["sites"].sort()
    return census


def check_custom_calls_against_contract(
    program: StepProgram,
    contract: Dict,
    census: Optional[Dict[str, Dict]] = None,
) -> List[Violation]:
    """Diff the program's custom-call census against the contract's
    recorded ``custom_calls`` section.

    Fails on: a contracted kernel target missing from the program (the
    silent-fallback case — the kernel stopped lowering and nobody
    noticed); a target the contract has never seen (an un-contracted
    kernel entered the step); count or operand/result-shape drift in an
    existing target. Contracts written before SC007 have no
    ``custom_calls`` section and skip the rule — regenerate with
    ``--fix-contracts`` to arm it."""
    want = contract.get("custom_calls")
    if want is None:
        return []
    if contract.get("config_hash") and program.config_hash and \
            contract["config_hash"] != program.config_hash:
        return []  # SC001 already reports the hash mismatch
    if census is None:
        census = custom_call_census(program.hlo)
    out: List[Violation] = []
    for target in sorted(want):
        if target not in census:
            out.append(
                program.violation(
                    "SC007",
                    f"contracted kernel {target} vanished from the "
                    f"lowered step ({want[target]['count']} call(s) in "
                    "the contract): the program silently fell back to "
                    "the reference path — check the dispatcher flags "
                    "and kernel availability, or --fix-contracts if "
                    "the removal is deliberate.",
                    snippet=target,
                )
            )
    for target in sorted(census):
        got = census[target]
        ref = want.get(target)
        if ref is None:
            out.append(
                program.violation(
                    "SC007",
                    f"new custom-call kernel {target}: {got['count']} "
                    "call(s) not in the contract — contract every "
                    "kernel the step runs (review, then "
                    "--fix-contracts).",
                    snippet=target,
                )
            )
            continue
        if got["count"] != ref["count"] or \
                got["sites"] != ref.get("sites", []):
            out.append(
                program.violation(
                    "SC007",
                    f"kernel {target} drifted from the contract: "
                    f"count {ref['count']} -> {got['count']}, sites "
                    f"{ref.get('sites', [])} -> {got['sites']}.",
                    snippet=target,
                )
            )
    return out


# ---------------------------------------------------------------------------
# one-call entry: run all SC rules on a program
# ---------------------------------------------------------------------------


def check_program(
    program: StepProgram,
    contract: Optional[Dict] = None,
    byte_tolerance: float = DEFAULT_BYTE_TOLERANCE,
    replicated_threshold: int = DEFAULT_REPLICATED_BYTES,
    census: Optional[Dict[str, Dict[str, int]]] = None,
) -> List[Violation]:
    """SC002–SC005 always; SC001/SC006 only when a contract is
    supplied (there is nothing to diff against otherwise)."""
    out: List[Violation] = []
    if contract is not None and program.hlo:
        out.extend(
            check_census_against_contract(
                program, contract, byte_tolerance, census=census
            )
        )
        out.extend(
            check_overlap_against_contract(
                program, contract, byte_tolerance
            )
        )
        out.extend(check_custom_calls_against_contract(program, contract))
        out.extend(check_pp_schedule_against_contract(program, contract))
    if program.stablehlo:
        out.extend(check_replicated_large(program, replicated_threshold))
        out.extend(check_replicated_moments(program, replicated_threshold))
        out.extend(check_dense_vocab(program))
        out.extend(check_output_sharding_drift(program))
    out.extend(check_host_transfer(program))
    out.sort(key=lambda v: (v.rule, v.line))
    return out


# ---------------------------------------------------------------------------
# contracts on disk
# ---------------------------------------------------------------------------


def contract_path(contracts_dir: str, mesh_spec: str) -> str:
    return os.path.join(contracts_dir, f"{mesh_spec}.json")


def load_contract(contracts_dir: str, mesh_spec: str) -> Optional[Dict]:
    try:
        with open(contract_path(contracts_dir, mesh_spec),
                  encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return None
    if not isinstance(data, dict) or "census" not in data:
        raise ValueError(
            f"{contract_path(contracts_dir, mesh_spec)}: not a shardcheck "
            "contract file"
        )
    return data


def write_contract(
    contracts_dir: str,
    mesh_spec: str,
    program: StepProgram,
    extra: Optional[Dict] = None,
) -> Dict:
    os.makedirs(contracts_dir, exist_ok=True)
    census = collective_census(program.hlo, program.coords())
    data = {
        "comment": (
            "shardcheck SC001 contract: the collective census of the "
            "lowered step program for this mesh. Regenerate with: "
            "python -m dlrover_tpu.lint --hlo <spec> --fix-contracts"
        ),
        "version": 1,
        "mesh_spec": mesh_spec,
        "axis_sizes": {
            a: s for a, s in program.axis_sizes.items() if s > 1
        },
        "world": program.world,
        "config_hash": program.config_hash,
        "census": {k: census[k] for k in sorted(census)},
        # SC007: the kernel inventory. Empty on CPU-lowered contracts
        # (no Pallas custom-calls off-TPU) — still armed: a kernel
        # APPEARING un-contracted fails just like one vanishing.
        "custom_calls": custom_call_census(program.hlo),
    }
    if program.n_slices > 1:
        # arms the per-cell dcn_bytes diff (the slow-link veto) and
        # records what the census unit means for this contract
        data["n_slices"] = program.n_slices
        data["dcn_bytes_total"] = census_dcn_bytes(census)
        # arms SC006: the exposed/overlapped split of those DCN bytes.
        # Recorded for EVERY multislice contract — a flat or fused-hier
        # program banks ratio 0.0 with its exposure baseline, so even
        # without the overlap schedule a change that inflates the
        # stalled bytes fails the contract.
        report = overlap_report(program.hlo, program.coords())
        data["overlap"] = {
            k: report[k]
            for k in (
                "dcn_exposed_bytes", "dcn_overlapped_bytes",
                "overlap_ratio",
            )
        }
    # arms SC008: the pipeline-schedule fingerprint (bubble fraction
    # of the declared geometry + static stage-handoff pattern).
    # Recorded for every pp > 1 contract.
    pp_report = pp_schedule_report(program)
    if pp_report is not None:
        data["pp_schedule"] = pp_report
    if extra:
        data.update(extra)
    path = contract_path(contracts_dir, mesh_spec)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


# ---------------------------------------------------------------------------
# SC rule catalog (for --list-rules and the docs)
# ---------------------------------------------------------------------------

SC_RULES: List[Tuple[str, str, str]] = [
    ("SC001", "collective-census",
     "Collectives per mesh axis (and, on multislice assignments, per "
     "ici/dcn link class) diffed against a checked-in contract."),
    ("SC002", "replicated-large-tensor",
     "A big sharding-constrained tensor left fully replicated across "
     "the data axes; under zero-1, also an optimizer moment still "
     "replicated across dp."),
    ("SC003", "dense-vocab-materialization",
     "A float dot_general result carrying both seq and full-vocab dims "
     "(dense logits; chunked-CE regression gate)."),
    ("SC004", "output-sharding-drift",
     "A donated state leaf whose output sharding is unpinned or differs "
     "from its input sharding."),
    ("SC005", "host-transfer-in-jit",
     "Host callback / infeed / outfeed inside the jitted step."),
    ("SC006", "exposed-dcn-bytes",
     "Trip-weighted exposed vs. overlapped DCN bytes diffed against "
     "the contract's recorded split — vetoes a change that "
     "re-serializes slice-boundary transfers the schedule used to "
     "hide behind compute."),
    ("SC007", "custom-call-census",
     "Every non-benign custom-call (Pallas/Mosaic kernel) in the "
     "lowered step, with operand/result shapes, diffed against the "
     "contract — a contracted kernel vanishing is a silent fallback "
     "to the reference path; a new one is un-reviewed."),
    ("SC008", "pp-schedule-bubble",
     "Pipeline-schedule fingerprint (analytic steady-state bubble "
     "fraction of the declared geometry + static collective-permute|pp "
     "stage-handoff count) diffed against the contract — vetoes a "
     "change that re-serializes the interleaved 1F1B schedule."),
]
