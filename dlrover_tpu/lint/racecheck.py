"""racecheck: whole-repo static concurrency analysis (RC001–RC003).

graftlint checks the AST per file and shardcheck checks the lowered IR;
this is the third machine-checked invariant layer — the master's
*thread/lock structure*. 28 modules hold ``threading`` locks today and
every shipped concurrency bug so far (the JG006 origin bugs, the AIMD
phase-lock, the shard-state writer drains) was found dynamically, after
the fact. racecheck makes the lock discipline a checked artifact:

- **lock identity** is ``(module, class, attribute)`` — every
  assignment whose value constructs ``threading.Lock`` / ``RLock`` /
  ``Condition`` (directly, through a wrapper call like
  ``maybe_track(threading.Lock(), ...)``, or in a list comprehension of
  stripes) names a lock. Identity is type-level, not instance-level:
  two instances of one class share the id, which is exactly the
  granularity a lock-ORDER discipline is stated at.
- **RC001 lock-order-cycle**: "acquires B while holding A" edges come
  from ``with``-statement nesting plus two same-module call-graph hops
  (the JG002 technique: ``self.f()`` resolves within the class, bare
  ``f()`` to module functions). Any cycle in the global acquisition
  graph is a potential deadlock. The acyclic graph is checked in as
  ``lint/lock_order.json`` and diffed: a NEW edge — even an acyclic
  one — fails until ``--fix-lock-order`` re-records it, so the edge
  shows up in review as a one-line JSON diff and a cycle-closing edge
  is vetoed before it ships. The same file arms the runtime
  :class:`~dlrover_tpu.lint.lock_tracker.LockTracker`.
- **RC002 guarded-by inference**: an attribute written under lock L at
  two or more sites but written lock-free elsewhere (outside
  ``__init__``, which runs before the object is published) is a
  finding — the whole-repo upgrade of JG006's thread-target heuristic.
  Sites inside thread-target functions are JG006's and are skipped
  here, so one defect never double-reports (graftlint.md, "division of
  labor").
- **RC003 blocking-call-under-lock**: ``sleep``, thread ``join``, file
  or socket IO, subprocess and RPC sends lexically inside a
  ``with <lock>:`` block of a hot-path master module (gate, servicer,
  SpeedMonitor stripes, task-manager heap, rendezvous, the loopback
  wire). A blocked holder of a hot lock stalls every RPC handler
  behind it — the exact shape the RequestGate exists to prevent.

Suppression reuses the graftlint comment syntax (``# graftlint:
disable=RC002 <why>``), and the baseline machinery is shared with
:mod:`dlrover_tpu.lint.engine` (fingerprints on rule + path + line
text), in ``lint/racecheck_baseline.json``. CLI:
``python -m dlrover_tpu.lint --race`` (exit 0 clean / 1 findings or
graph drift / 2 usage).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.lint.engine import (
    Severity,
    SourceFile,
    Violation,
    iter_py_files,
)

#: checked-in acquisition graph (regenerate with --fix-lock-order)
DEFAULT_LOCK_ORDER = os.path.join(
    os.path.dirname(__file__), "lock_order.json"
)
#: grandfathered racecheck findings (regenerate with --fix-race-baseline)
DEFAULT_RACE_BASELINE = os.path.join(
    os.path.dirname(__file__), "racecheck_baseline.json"
)

LOCK_MAKERS = {"Lock", "RLock", "Condition"}

#: RC003 applies where a blocked lock holder stalls RPC handlers: the
#: master's request path and the harness wire that stands in for it
HOT_PATH_SUFFIXES = (
    "rpc/transport.py",
    "master/servicer.py",
    "master/monitor/speed_monitor.py",
    "master/monitor/hang_watchdog.py",
    "master/shard/task_manager.py",
    "master/shard/dataset_manager.py",
    "master/rendezvous/kv_store.py",
    "master/rendezvous/sync_service.py",
    "master/node/job_context.py",
    "fleet/loopback.py",
)

RC_RULES = (
    ("RC001", "lock-order-cycle",
     "cycle in the global lock-acquisition graph (potential deadlock)"),
    ("RC002", "unguarded-attr-write",
     "attribute guarded by a lock at 2+ sites but written lock-free "
     "elsewhere"),
    ("RC003", "blocking-call-under-lock",
     "sleep/join/IO/RPC while holding a hot-path master lock"),
)


# ---------------------------------------------------------------------------
# the repo lock model
# ---------------------------------------------------------------------------


def _module_name(rel_path: str) -> str:
    """dlrover_tpu/master/shard/task_manager.py -> master.shard.task_manager
    (the leading package segment is dropped: ids must survive a repo
    rename and read short in reports)."""
    p = rel_path.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x]
    if parts and parts[0] in ("dlrover_tpu", "."):
        parts = parts[1:]
    return ".".join(parts)


def _makes_lock(value: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when this expression constructs one,
    looking THROUGH wrapper calls (``maybe_track(threading.Lock())``)
    and comprehensions (striped lock lists)."""
    from dlrover_tpu.lint.rules import dotted_name

    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func).rsplit(".", 1)[-1]
            if callee in LOCK_MAKERS:
                return callee
    return None


@dataclasses.dataclass
class LockDef:
    lock_id: str  # module.Class.attr | module.name
    kind: str  # Lock | RLock | Condition
    path: str
    line: int
    striped: bool = False  # a list/dict of locks (subscripted use)


@dataclasses.dataclass
class FuncInfo:
    """One function/method with its lock-relevant facts."""

    module: str
    cls: str  # "" for module-level functions
    name: str
    node: ast.AST
    src: SourceFile
    #: lock ids this function acquires directly (any `with` in the body)
    acquires: Set[str] = dataclasses.field(default_factory=set)
    #: (callee_class_or_empty, callee_name) same-module calls
    calls: Set[Tuple[str, str]] = dataclasses.field(default_factory=set)


class RepoModel:
    """Everything RC rules need, built in one pass over the files."""

    def __init__(self):
        self.locks: Dict[str, LockDef] = {}
        self.funcs: Dict[Tuple[str, str, str], FuncInfo] = {}
        self.sources: Dict[str, SourceFile] = {}
        self.errors: List[str] = []
        #: method name -> [(module, class)] across the whole tree:
        #: ``recv.method()`` on a non-self receiver resolves only when
        #: exactly one class defines the method (unique-name
        #: resolution — under-approximates, never invents an edge)
        self.method_index: Dict[str, List[Tuple[str, str]]] = {}
        #: (module, class) -> same-module base-class names (virtual
        #: dispatch: ``self.m()`` in a base can run a subclass override)
        self.class_bases: Dict[Tuple[str, str], Set[str]] = {}

    # -- construction ---------------------------------------------------

    @classmethod
    def build(cls, paths: Sequence[str]) -> "RepoModel":
        model = cls()
        for full, display in iter_py_files(paths):
            try:
                with open(full, encoding="utf-8") as f:
                    text = f.read()
                src = SourceFile(full, text, rel_path=display)
            except (OSError, SyntaxError, ValueError) as e:
                model.errors.append(f"{display}: unparsable: {e}")
                continue
            model.sources[src.rel_path] = src
            model._scan_file(src)
        model._resolve_acquires()
        return model

    def _scan_file(self, src: SourceFile):
        from dlrover_tpu.lint.rules import dotted_name

        module = _module_name(src.rel_path)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                self.class_bases[(module, node.name)] = {
                    dotted_name(b).rsplit(".", 1)[-1] for b in node.bases
                }
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls_name = self._enclosing_class(node)
                if self._enclosing_func(node) is not None:
                    continue  # nested defs are not call targets here
                info = FuncInfo(module, cls_name, node.name, node, src)
                self._scan_func(info)
                self.funcs[(module, cls_name, node.name)] = info
                if cls_name:
                    self.method_index.setdefault(node.name, []).append(
                        (module, cls_name)
                    )
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                self._scan_lock_assign(src, module, node)

    @staticmethod
    def _enclosing_class(node: ast.AST, through_funcs: bool = False) -> str:
        """Nearest ClassDef name. ``through_funcs`` looks past enclosing
        functions (the ``self._lock = ...`` inside ``__init__`` case);
        without it a def inside a function reads as module-level."""
        from dlrover_tpu.lint.rules import ancestors

        for a in ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a.name
            if not through_funcs and isinstance(
                a, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                return ""
        return ""

    @staticmethod
    def _enclosing_func(node: ast.AST):
        from dlrover_tpu.lint.rules import enclosing_function

        return enclosing_function(node)

    def _scan_lock_assign(self, src: SourceFile, module: str, node):
        value = node.value if node.value is not None else None
        if value is None:
            return
        kind = _makes_lock(value)
        if kind is None:
            return
        striped = isinstance(value, (ast.ListComp, ast.List, ast.DictComp))
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            lock_id = None
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                cls_name = self._enclosing_class(node, through_funcs=True)
                if cls_name:
                    lock_id = f"{module}.{cls_name}.{t.attr}"
            elif isinstance(t, ast.Name):
                if self._enclosing_func(node) is not None:
                    continue  # a local lock: no stable identity
                cls_name = self._enclosing_class(node)
                owner = f"{module}.{cls_name}" if cls_name else module
                lock_id = f"{owner}.{t.id}"
            if lock_id and lock_id not in self.locks:
                self.locks[lock_id] = LockDef(
                    lock_id, kind, src.rel_path,
                    getattr(node, "lineno", 1), striped,
                )

    @staticmethod
    def _call_target(info: FuncInfo, node: ast.Call) -> Optional[Tuple[str, str]]:
        """(resolution, name): ``(cls, m)`` for ``self.m()``, ``("", f)``
        for bare ``f()``, ``("*", m)`` for a method on any other
        receiver (subscripts included) — resolved later by unique
        method name across the tree."""
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                return (info.cls, node.func.attr)
            return ("*", node.func.attr)
        if isinstance(node.func, ast.Name):
            return ("", node.func.id)
        return None

    def _scan_func(self, info: FuncInfo):
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                call = self._call_target(info, node)
                if call is not None:
                    info.calls.add(call)

    def _resolve_acquires(self):
        for info in self.funcs.values():
            for node in ast.walk(info.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lid = self.resolve_lock(info, item.context_expr)
                        if lid:
                            info.acquires.add(lid)

    # -- lock-expression resolution --------------------------------------

    def resolve_lock(self, info: FuncInfo, expr: ast.AST) -> Optional[str]:
        """Lock id for a ``with``-item expression, or None when it is
        not a known lock (locals, non-lock context managers)."""
        # strip subscripts: self._locks[i] -> self._locks (striped)
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and info.cls
        ):
            # the declaring class owns the id: walk same-module bases so
            # a subclass's `with self._lock:` maps to the inherited lock
            cls = info.cls
            seen = set()
            while cls and cls not in seen:
                seen.add(cls)
                lid = f"{info.module}.{cls}.{expr.attr}"
                if lid in self.locks:
                    return lid
                bases = self.class_bases.get((info.module, cls), set())
                cls = next(iter(sorted(bases)), "")
            return None
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            # ClassName._class_lock style (singleton guards)
            lid = f"{info.module}.{expr.value.id}.{expr.attr}"
            return lid if lid in self.locks else None
        if isinstance(expr, ast.Name):
            lid = f"{info.module}.{expr.id}"
            return lid if lid in self.locks else None
        return None

    def _subclasses(self, module: str, cls: str) -> Set[str]:
        """``cls`` plus its same-module (transitive) subclasses."""
        out = {cls}
        changed = True
        while changed:
            changed = False
            for (m, c), bases in self.class_bases.items():
                if m == module and c not in out and bases & out:
                    out.add(c)
                    changed = True
        return out

    def callees(self, info: FuncInfo, call: Tuple[str, str]) -> List[FuncInfo]:
        """Possible targets of one call: the module function for bare
        names, the virtual-dispatch set (class + same-module
        subclasses defining the method) for ``self.m()``, the
        unique-name owner for any other receiver."""
        cls_name, name = call
        if cls_name == "*":
            owners = self.method_index.get(name, [])
            if len(owners) != 1:
                return []  # ambiguous or unknown: no edge invented
            module, cls = owners[0]
            g = self.funcs.get((module, cls, name))
            return [g] if g else []
        if cls_name:
            out = []
            for c in self._subclasses(info.module, cls_name):
                g = self.funcs.get((info.module, c, name))
                if g is not None:
                    out.append(g)
            return out
        g = self.funcs.get((info.module, "", name))
        return [g] if g else []

    def callee(self, info: FuncInfo, call: Tuple[str, str]):
        targets = self.callees(info, call)
        return targets[0] if len(targets) == 1 else None

    def reachable_acquires(self, info: FuncInfo, hops: int = 2) -> Set[str]:
        """Locks acquired by ``info`` or by resolvable callees within
        ``hops`` call-graph hops (the JG002 technique)."""
        out: Set[str] = set(info.acquires)
        frontier = [info]
        for _ in range(hops):
            nxt = []
            for f in frontier:
                for call in f.calls:
                    for g in self.callees(f, call):
                        if g is not info:
                            out |= g.acquires
                            nxt.append(g)
            frontier = nxt
        return out


# ---------------------------------------------------------------------------
# RC001 — lock-order cycles + the checked-in graph
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Edge:
    held: str
    acquired: str
    path: str
    line: int
    via: str  # "nested" | "call:<func>"

    def key(self) -> Tuple[str, str]:
        return (self.held, self.acquired)


def extract_edges(model: RepoModel) -> List[Edge]:
    """Every "acquires ``acquired`` while holding ``held``" edge in the
    repo: ``with`` nesting first, then calls made inside a ``with``
    block resolved two same-module hops deep."""
    from dlrover_tpu.lint.rules import ancestors

    edges: Dict[Tuple[str, str, str], Edge] = {}

    def add(held, acquired, src, node, via):
        if held == acquired:
            # same-id re-entry: legal for RLock stripes and striped
            # lists (different instances); a true self-deadlock on one
            # Lock instance is the runtime tracker's to catch
            return
        e = Edge(held, acquired, src.rel_path,
                 getattr(node, "lineno", 1), via)
        edges.setdefault((held, acquired, via), e)

    for info in model.funcs.values():
        for node in ast.walk(info.node):
            held = []
            for a in ancestors(node):
                if a is info.node:
                    break
                if isinstance(a, ast.With):
                    for item in a.items:
                        lid = model.resolve_lock(info, item.context_expr)
                        if lid:
                            held.append(lid)
            if not held:
                continue
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = model.resolve_lock(info, item.context_expr)
                    if lid:
                        for h in held:
                            add(h, lid, info.src, node, "nested")
            elif isinstance(node, ast.Call):
                call = RepoModel._call_target(info, node)
                if call is None:
                    continue
                for g in model.callees(info, call):
                    for lid in model.reachable_acquires(g, hops=1):
                        for h in held:
                            add(h, lid, info.src, node, f"call:{call[1]}")
    return sorted(edges.values(), key=lambda e: (e.held, e.acquired, e.via))


def find_cycles(edges: Iterable[Edge]) -> List[List[str]]:
    """Elementary cycles in the acquisition graph (DFS with a path
    stack; the graph is tiny). Each cycle is the lock-id path with the
    start repeated at the end."""
    graph: Dict[str, Set[str]] = {}
    for e in edges:
        graph.setdefault(e.held, set()).add(e.acquired)
    cycles: List[List[str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str], on_path: Set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path + [start]
                # canonical form: rotate so the smallest id leads
                body = cyc[:-1]
                i = body.index(min(body))
                canon = tuple(body[i:] + body[:i])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(cyc)
            elif nxt not in on_path and nxt > start:
                # nodes < start were exhausted as starts already
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def load_lock_order(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return None
    if not isinstance(data, dict) or "edges" not in data:
        raise ValueError(f"{path}: not a racecheck lock-order file")
    return data


def write_lock_order(
    path: str, model: RepoModel, edges: Sequence[Edge]
) -> Dict:
    data = {
        "comment": (
            "racecheck RC001 acquisition graph: every 'acquires B while "
            "holding A' edge in the tree, by (module, class, attribute) "
            "lock identity. CI diffs this file, so a new edge — even an "
            "acyclic one — lands as a reviewable one-line diff, and the "
            "runtime LockTracker raises on any acquisition that "
            "contradicts it. Regenerate with: python -m dlrover_tpu.lint "
            "--race --fix-lock-order dlrover_tpu/"
        ),
        "version": 1,
        "locks": {
            lid: {"kind": d.kind, "path": d.path, "line": d.line,
                  "striped": d.striped}
            for lid, d in sorted(model.locks.items())
        },
        "edges": [
            {"held": e.held, "acquired": e.acquired, "via": e.via,
             "site": f"{e.path}:{e.line}"}
            for e in edges
        ],
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


# ---------------------------------------------------------------------------
# the three rules
# ---------------------------------------------------------------------------


def _violation(
    src: SourceFile, rule: str, node_or_line, message: str
) -> Optional[Violation]:
    line = (
        node_or_line
        if isinstance(node_or_line, int)
        else getattr(node_or_line, "lineno", 1)
    )
    if src.suppressed(rule, line):
        return None
    return Violation(
        rule=rule,
        path=src.rel_path,
        line=line,
        col=0,
        message=message,
        snippet=src.snippet_at(line),
        severity=Severity.ERROR,
    )


def check_rc001(
    model: RepoModel,
    edges: Sequence[Edge],
    checked_in: Optional[Dict],
) -> Tuple[List[Violation], List[str]]:
    """(violations, graph-drift messages). Cycles are violations at a
    participating edge's site; drift (edges added/removed vs the
    checked-in graph) is reported separately — it fails the run but is
    fixed by --fix-lock-order, not by a suppression."""
    violations: List[Violation] = []
    by_key: Dict[Tuple[str, str], Edge] = {}
    for e in edges:
        by_key.setdefault(e.key(), e)
    for cyc in find_cycles(edges):
        first = by_key.get((cyc[0], cyc[1]))
        src = model.sources.get(first.path) if first else None
        msg = (
            "lock-order cycle (potential deadlock): "
            + " -> ".join(cyc)
            + ". Two threads taking these locks in program order can "
            "each hold one and wait on the other. Restructure so every "
            "path acquires them in one global order (or drop to a "
            "single lock)."
        )
        if src is not None:
            v = _violation(src, "RC001", first.line, msg)
            if v is not None:
                violations.append(v)
        else:
            violations.append(Violation(
                "RC001", first.path if first else "<graph>",
                first.line if first else 1, 0, msg, "",
            ))
    drift: List[str] = []
    if checked_in is None:
        drift.append(
            "no checked-in lock_order.json — the RC001 diff gate has "
            "nothing to diff against; generate it with "
            "--race --fix-lock-order"
        )
    else:
        want = {(d["held"], d["acquired"]) for d in checked_in["edges"]}
        got = {e.key() for e in edges}
        for held, acquired in sorted(got - want):
            e = by_key[(held, acquired)]
            drift.append(
                f"{e.path}:{e.line}: RC001 new acquisition edge "
                f"{held} -> {acquired} (via {e.via}) is not in the "
                "checked-in lock_order.json — if the order is "
                "intentional and acyclic, record it with "
                "--fix-lock-order so the diff is reviewed"
            )
        for held, acquired in sorted(want - got):
            drift.append(
                f"lock_order.json: stale edge {held} -> {acquired} no "
                "longer exists in the tree — run --fix-lock-order to "
                "shrink the graph"
            )
    return violations, drift


def _lexically_locked(model: RepoModel, info: FuncInfo, node) -> bool:
    """Is ``node`` inside a ``with <lock>:`` block of ``info``? Resolved
    lock ids count, and so do lock-ish names JG006-style (a lock passed
    in as an argument still guards)."""
    from dlrover_tpu.lint.rules import ancestors, dotted_name

    for a in ancestors(node):
        if a is info.node:
            break
        if isinstance(a, ast.With):
            for item in a.items:
                d = dotted_name(item.context_expr)
                if (
                    model.resolve_lock(info, item.context_expr)
                    or "lock" in d.lower()
                    or "cond" in d.lower()
                ):
                    return True
    return False


def lock_context_only(model: RepoModel) -> Set[Tuple[str, str, str]]:
    """Functions that only ever run with a lock held: every resolved
    call site is lexically inside a locked region, or inside another
    lock-context-only function (fixed point — the ``get_task`` →
    ``_refill_locked`` → ``_create_tasks_from_shards`` chain). Writes
    in them are guarded *via the caller*, which a purely lexical rule
    would misreport."""
    # call sites: target key -> [(caller key, lexically locked)]
    callsites: Dict[Tuple[str, str, str], List[Tuple[Tuple, bool]]] = {}
    for key, info in model.funcs.items():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            call = RepoModel._call_target(info, node)
            if call is None:
                continue
            locked = _lexically_locked(model, info, node)
            for g in model.callees(info, call):
                gkey = (g.module, g.cls, g.name)
                if gkey != key:
                    callsites.setdefault(gkey, []).append((key, locked))
    only: Set[Tuple[str, str, str]] = set()
    for _ in range(3):  # bounded fixed point (call chains are shallow)
        nxt = {
            key
            for key, sites in callsites.items()
            if sites
            and all(locked or caller in only for caller, locked in sites)
        }
        if nxt == only:
            break
        only = nxt
    return only


def check_rc002(model: RepoModel) -> List[Violation]:
    """Guarded-by inference per (module, class, attribute): 2+ write
    sites under a lock and any lock-free write site elsewhere (outside
    ``__init__``/``__new__``, outside thread-target functions — JG006's
    beat — and outside functions only ever called with a lock held)
    flags the lock-free sites."""
    from dlrover_tpu.lint.rules import UnguardedSharedMutationRule

    guarded_via_caller = lock_context_only(model)
    # write sites: (module, cls, attr) -> list of (guarded, src, node)
    sites: Dict[Tuple[str, str, str], List] = {}
    jg006 = UnguardedSharedMutationRule()
    thread_fns: Set[int] = set()
    for src in model.sources.values():
        thread_fns |= {id(fn) for fn in jg006._thread_targets(src)}
    for key, info in model.funcs.items():
        if info.name in ("__init__", "__new__") or not info.cls:
            continue
        in_thread_target = id(info.node) in thread_fns
        for node in ast.walk(info.node):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                guarded = (
                    _lexically_locked(model, info, node)
                    or key in guarded_via_caller
                )
                sites.setdefault(
                    (info.module, info.cls, t.attr), []
                ).append((guarded, info.src, node, in_thread_target))
    out: List[Violation] = []
    for (module, cls_name, attr), entries in sorted(
        sites.items(), key=lambda kv: str(kv[0])
    ):
        n_guarded = sum(1 for g, *_ in entries if g)
        if n_guarded < 2:
            continue
        for guarded, src, node, in_thread_target in entries:
            if guarded or in_thread_target:
                continue  # thread-target sites are JG006's report
            v = _violation(
                src, "RC002", node,
                f"self.{attr} is written under a lock at {n_guarded} "
                f"site(s) in {cls_name} but lock-free here: either this "
                "write races the guarded ones, or the attribute is not "
                "actually shared — guard it, or suppress with why the "
                "lock-free write is safe (single-threaded phase, "
                "pre-publication, etc.).",
            )
            if v is not None:
                out.append(v)
    return out


#: RC003's blocking-call set: calls that park the thread while every
#: other handler queues behind the held lock
RC003_CALLEES = {
    "time.sleep", "sleep", "os.system", "os.replace", "open",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "socket.create_connection", "urlopen",
}
RC003_METHODS = {"join", "sleep", "recv", "send", "sendall", "connect",
                 "fsync", "flush"}
#: RPC-send methods on client-ish receivers (a lock held across a
#: network round trip is the worst case)
RC003_RPC_METHODS = {"get", "report"}
RC003_RPC_RECEIVERS = ("client", "stub", "channel")


def check_rc003(model: RepoModel) -> List[Violation]:
    from dlrover_tpu.lint.rules import ancestors, dotted_name

    out: List[Violation] = []
    for info in model.funcs.values():
        if not info.src.rel_path.replace(os.sep, "/").endswith(
            HOT_PATH_SUFFIXES
        ):
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            held = None
            for a in ancestors(node):
                if a is info.node:
                    break
                if isinstance(a, ast.With):
                    for item in a.items:
                        lid = model.resolve_lock(info, item.context_expr)
                        if lid:
                            held = lid
            if held is None:
                continue
            d = dotted_name(node.func)
            hit = None
            if d in RC003_CALLEES:
                hit = d
            elif isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                recv = dotted_name(node.func.value).rsplit(".", 1)[-1]
                if attr in RC003_METHODS:
                    hit = f".{attr}()"
                elif attr in RC003_RPC_METHODS and any(
                    r in recv.lower() for r in RC003_RPC_RECEIVERS
                ):
                    hit = f"{recv}.{attr}() [RPC]"
            if hit is None:
                continue
            v = _violation(
                info.src, "RC003", node,
                f"blocking call {hit} while holding hot-path lock "
                f"{held}: every RPC handler needing that lock parks "
                "behind this call. Move the blocking work outside the "
                "critical section (snapshot under the lock, block "
                "after), or suppress with why the hold is bounded.",
            )
            if v is not None:
                out.append(v)
    return out


# ---------------------------------------------------------------------------
# one-call entry (CLI and the tier-1 test share it)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RaceResult:
    violations: List[Violation]
    fresh: List[Violation]
    stale_fingerprints: List[str]
    drift: List[str]
    errors: List[str]
    edges: List[Edge]
    model: RepoModel

    @property
    def failed(self) -> bool:
        return bool(self.fresh or self.drift or self.errors)


def run(
    paths: Sequence[str],
    lock_order_path: Optional[str] = None,
    baseline_path: Optional[str] = None,
    fix_lock_order: bool = False,
    fix_baseline: bool = False,
) -> RaceResult:
    from dlrover_tpu.lint import engine

    lock_order_path = lock_order_path or DEFAULT_LOCK_ORDER
    baseline_path = baseline_path or DEFAULT_RACE_BASELINE
    model = RepoModel.build(paths)
    edges = extract_edges(model)
    # a cyclic graph is never a recordable artifact: --fix-lock-order
    # must not seed the runtime tracker with a deadlock, and
    # --fix-race-baseline must not grandfather one — refuse BEFORE any
    # write, so an ignored exit-1 fix run cannot bless the cycle
    cyclic = bool(find_cycles(edges))
    if fix_lock_order and not cyclic:
        write_lock_order(lock_order_path, model, edges)
    checked_in = load_lock_order(lock_order_path)
    v1, drift = check_rc001(model, edges, checked_in)
    violations = sorted(
        v1 + check_rc002(model) + check_rc003(model),
        key=lambda v: (v.path, v.line, v.rule),
    )
    if fix_baseline:
        if not cyclic:
            engine.write_baseline(
                baseline_path,
                # RC001 never enters the baseline even cycle-free:
                # order problems are fixed or recorded in the graph,
                # not grandfathered
                [v for v in violations if v.rule != "RC001"],
                regen_hint="--race --fix-race-baseline",
            )
        return RaceResult(
            violations, [], [], drift, model.errors, edges, model
        )
    baseline = engine.load_baseline(baseline_path)
    fresh, stale = engine.apply_baseline(violations, baseline)
    return RaceResult(
        violations, fresh, stale, drift, model.errors, edges, model
    )


def report(result: RaceResult, out=None) -> None:
    import sys

    out = out or sys.stdout
    for v in result.fresh:
        print(v.format(), file=out)
    for d in result.drift:
        print(d, file=out)
    for e in result.errors:
        print(f"ERROR {e}", file=out)
    if result.stale_fingerprints:
        print(
            f"note: {len(result.stale_fingerprints)} racecheck baseline "
            "entr"
            f"{'y is' if len(result.stale_fingerprints) == 1 else 'ies are'}"
            " stale — run --race --fix-race-baseline to shrink it",
            file=out,
        )
    n_base = len(result.violations) - len(result.fresh)
    print(
        f"racecheck: {len(result.fresh)} new, {n_base} baselined, "
        f"{len(result.drift)} graph drift(s), {len(result.errors)} "
        f"errors over {len(result.model.locks)} lock(s), "
        f"{len(result.edges)} edge(s)",
        file=out,
    )
