"""graftlint rule engine: file walking, suppressions, baseline, reporting.

Design (mirrors how large linters age well, scaled down to stdlib-only):

- a **Rule** is an object with an ``id`` (``JGnnn``) and a
  ``check(source) -> Iterable[Violation]``; rules never do I/O;
- **suppression** is per-line (``# graftlint: disable=JG003`` on the
  violating line or the line above) or per-file
  (``# graftlint: disable-file=JG003`` anywhere in the file), always
  naming the rule — blanket ``disable=all`` exists but is for fixture
  files, not production code;
- the **baseline** grandfathers pre-existing violations so the linter
  can gate CI from day one without a big-bang cleanup: violations are
  fingerprinted by ``(rule, relative path, stripped source line)`` —
  NOT the line number, so unrelated edits above a grandfathered site
  don't un-baseline it — with a count per fingerprint (two identical
  offending lines in one file need two baseline slots). New violations
  are everything beyond the baselined count.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: baseline shipped with the package: grandfathered violations of the
#: pre-graftlint codebase (``--fix-baseline`` rewrites it)
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

SUPPRESS_TOKEN = "graftlint: disable="
SUPPRESS_FILE_TOKEN = "graftlint: disable-file="


class Severity:
    ERROR = "error"  # fails the gate
    WARNING = "warning"  # reported, never fails (heuristic rules start here)


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # posix-normalized, relative to the lint invocation root
    line: int  # 1-indexed
    col: int
    message: str
    snippet: str  # stripped source line (fingerprint component)
    severity: str = Severity.ERROR

    def fingerprint(self) -> str:
        """Stable identity for baselining: rule + file + offending line
        TEXT. Line numbers drift with every edit above the site; the
        text of the offending line only changes when someone touches
        the site itself — exactly when re-review is wanted."""
        key = f"{self.rule}|{self.path}|{self.snippet}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.message}\n    {self.snippet}"
        )


class SourceFile:
    """One parsed python file plus the per-file context rules share."""

    def __init__(self, path: str, text: str, rel_path: Optional[str] = None):
        self.path = path
        self.rel_path = (rel_path or path).replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # parent links: rules walk up (e.g. "is this assignment inside a
        # `with lock:` block"); ast itself only links downward
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._graftlint_parent = node  # type: ignore[attr-defined]
        self._file_suppressions: Optional[set] = None

    # -- suppression -------------------------------------------------------

    def _line_suppressions(self, lineno: int) -> set:
        """Rule ids disabled on source line ``lineno`` (1-indexed)."""
        if not 1 <= lineno <= len(self.lines):
            return set()
        line = self.lines[lineno - 1]
        idx = line.find(SUPPRESS_TOKEN)
        if idx < 0:
            return set()
        # an empty spec ("disable=" with the rule id forgotten) is a
        # no-op suppression, not a crash
        parts = line[idx + len(SUPPRESS_TOKEN):].split()
        if not parts:
            return set()
        return {r.strip() for r in parts[0].split(",") if r.strip()}

    def file_suppressions(self) -> set:
        if self._file_suppressions is None:
            out = set()
            for line in self.lines:
                idx = line.find(SUPPRESS_FILE_TOKEN)
                if idx < 0:
                    continue
                parts = line[idx + len(SUPPRESS_FILE_TOKEN):].split()
                if not parts:
                    continue
                out.update(
                    r.strip() for r in parts[0].split(",") if r.strip()
                )
            self._file_suppressions = out
        return self._file_suppressions

    def suppressed(self, rule: str, lineno: int) -> bool:
        """Suppressed on the line itself, the line above (comment-above
        style), or file-wide."""
        for rules in (
            self._line_suppressions(lineno),
            self._line_suppressions(lineno - 1),
            self.file_suppressions(),
        ):
            if rule in rules or "all" in rules:
                return True
        return False

    # -- helpers rules lean on ---------------------------------------------

    def snippet_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def violation(
        self,
        rule: str,
        node: ast.AST,
        message: str,
        severity: str = Severity.ERROR,
    ) -> Violation:
        line = getattr(node, "lineno", 1)
        return Violation(
            rule=rule,
            path=self.rel_path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.snippet_at(line),
            severity=severity,
        )


# ---------------------------------------------------------------------------
# file walking + linting
# ---------------------------------------------------------------------------


def iter_py_files(paths: Sequence[str]) -> Iterable[Tuple[str, str]]:
    """(abs path, display path) for every .py under ``paths``; hidden
    dirs and __pycache__ skipped. Display paths stay relative when the
    input was, so fingerprints are machine-independent."""
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p, os.path.normpath(p)
            continue
        for root, dirnames, files in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if not d.startswith(".") and d != "__pycache__"
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    full = os.path.join(root, f)
                    yield full, os.path.normpath(full)


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence] = None,
) -> Tuple[List[Violation], List[str]]:
    """Run ``rules`` (default: the full catalog) over every python file
    under ``paths``. Returns (violations, unparsable-file messages) —
    a syntax error in one file must not hide violations in the rest."""
    if rules is None:
        from dlrover_tpu.lint.rules import ALL_RULES

        rules = ALL_RULES
    violations: List[Violation] = []
    errors: List[str] = []
    for full, display in iter_py_files(paths):
        try:
            with open(full, encoding="utf-8") as f:
                text = f.read()
            src = SourceFile(full, text, rel_path=display)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{display}: unparsable: {e}")
            continue
        for rule in rules:
            try:
                found = list(rule.check(src))
            except Exception as e:  # a broken rule must not kill the run
                errors.append(f"{display}: rule {rule.id} crashed: {e}")
                continue
            for v in found:
                if not src.suppressed(v.rule, v.line):
                    violations.append(v)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return violations, errors


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> {rule, path, snippet, count}. Missing file = empty
    baseline (a fresh checkout of a clean repo needs no file)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    if not isinstance(data, dict) or "violations" not in data:
        raise ValueError(f"baseline {path}: not a graftlint baseline file")
    return dict(data["violations"])


def write_baseline(
    path: str,
    violations: Sequence[Violation],
    regen_hint: str = "--fix-baseline",
) -> dict:
    entries: Dict[str, dict] = {}
    for v in violations:
        fp = v.fingerprint()
        e = entries.setdefault(
            fp,
            {
                "rule": v.rule,
                "path": v.path,
                "snippet": v.snippet,
                "count": 0,
            },
        )
        e["count"] += 1
    data = {
        "comment": (
            "grandfathered violations. Entries key on "
            "(rule, path, line TEXT) so line drift never un-baselines a "
            "site. Regenerate with: python -m dlrover_tpu.lint "
            f"{regen_hint} dlrover_tpu/"
        ),
        "version": 1,
        "violations": {k: entries[k] for k in sorted(entries)},
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def apply_baseline(
    violations: Sequence[Violation], baseline: Dict[str, dict]
) -> Tuple[List[Violation], List[str]]:
    """(new violations, stale baseline fingerprints). The first
    ``count`` occurrences of each baselined fingerprint are forgiven;
    anything beyond is new. Stale fingerprints (baselined but no longer
    occurring) are reported so ``--fix-baseline`` runs shrink the file
    as debt is paid down."""
    remaining = {fp: int(e.get("count", 1)) for fp, e in baseline.items()}
    fresh: List[Violation] = []
    for v in violations:
        fp = v.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            fresh.append(v)
    stale = [fp for fp, n in remaining.items() if n > 0]
    return fresh, stale


# ---------------------------------------------------------------------------
# one-call entry (CLI and the tier-1 test share it)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    violations: List[Violation]  # everything found (post-suppression)
    fresh: List[Violation]  # not covered by the baseline
    stale_fingerprints: List[str]
    errors: List[str]

    @property
    def failed(self) -> bool:
        return bool(
            [v for v in self.fresh if v.severity == Severity.ERROR]
            or self.errors
        )


def run(
    paths: Sequence[str],
    baseline_path: Optional[str] = None,
    fix_baseline: bool = False,
    rules: Optional[Sequence] = None,
) -> LintResult:
    baseline_path = baseline_path or DEFAULT_BASELINE
    violations, errors = lint_paths(paths, rules=rules)
    if fix_baseline:
        write_baseline(baseline_path, violations)
        return LintResult(violations, [], [], errors)
    baseline = load_baseline(baseline_path)
    fresh, stale = apply_baseline(violations, baseline)
    return LintResult(violations, fresh, stale, errors)


def report(result: LintResult, out=None) -> None:
    out = out or sys.stdout
    for v in result.fresh:
        print(v.format(), file=out)
    for e in result.errors:
        print(f"ERROR {e}", file=out)
    if result.stale_fingerprints:
        print(
            f"note: {len(result.stale_fingerprints)} baseline entr"
            f"{'y is' if len(result.stale_fingerprints) == 1 else 'ies are'}"
            " stale (violation fixed — run --fix-baseline to shrink the "
            "baseline)",
            file=out,
        )
    n_base = len(result.violations) - len(result.fresh)
    print(
        f"graftlint: {len(result.fresh)} new, {n_base} baselined, "
        f"{len(result.errors)} errors",
        file=out,
    )
