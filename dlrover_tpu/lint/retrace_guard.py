"""Runtime companion to graftlint: catch silent XLA recompiles.

The one elasticity invariant static analysis cannot see: the PR 2 adam
bug — XLA returned some optimizer moments re-sharded, so step N+1's
input signature differed from step N's and ``jax.jit`` silently
recompiled the full fwd+bwd+adamw program. Nothing crashed; the job
just burned tens of compile-seconds of chip time, repeatedly, with no
signal beyond a slow wall clock. The same species: a data pipeline
whose batch shape drifts (re-tracing every step), an eval fn re-wrapped
in ``jax.jit`` inside a loop (recompiling an identical program).

:class:`RetraceGuard` listens to ``jax_log_compiles`` — every compile
logs ``Compiling <fn> with global shapes and types [...]. Argument
mapping: (...)`` through ``jax._src.interpreters.pxla``, and that
message IS the (mesh signature, avatar signature) pair: global shapes/
dtypes plus the per-argument sharding mapping. The guard counts
compiles per signature and per function name and raises
:class:`RetraceError`

- when one exact signature compiles more than ``max_recompiles_per_
  signature`` extra times (an identical program rebuilt — cache-
  defeating churn), or
- when one function accumulates more than ``max_signatures_per_fn``
  distinct signatures (signature drift — the input keeps changing
  shape/sharding under the same step).

A *warm* remesh (``ElasticTrainer.lower_step`` AOT cache hit) emits no
compile log at all, so the guard stays silent across it — which is
exactly the property the warm-compile tests pin down.

Wired into :class:`ElasticTrainer` behind ``DLROVER_TPU_RETRACE_GUARD``
(see :func:`maybe_install`); usable standalone::

    with RetraceGuard(max_signatures_per_fn=2):
        step(state, batch)   # raises on the 3rd distinct signature

The raise happens *in place* — inside the jit call that triggered the
over-budget compile — so the stack trace points at the drifting call
site, not at some later check. (Python logging propagates exceptions
raised by a handler's ``emit`` up through the logging call.) Compiles
from background threads (speculative neighbor compiles) are counted
but never raise there; they surface at the next ``check()``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

#: jax logs "Compiling <fn> with global shapes and types ..." here
_COMPILE_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_PREFIX = "Compiling "

__all__ = ["RetraceError", "RetraceGuard", "maybe_install", "installed"]


class RetraceError(RuntimeError):
    """A jitted function recompiled beyond the guard's budget."""


#: jax's EAGER op dispatch jit-compiles tiny per-primitive programs
#: (convert_element_type, broadcast_in_dim, rng internals, ...) whose
#: shapes naturally drift during setup — param init alone compiles one
#: broadcast per distinct param shape. Counting those would false-trip
#: the drift budget before the first train step, so they are exempt by
#: default; the step/eval/loss functions the guard exists for are
#: ordinary user ``def``s and never collide with these names.
DEFAULT_IGNORE_FNS = frozenset({
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "expand_dims", "concatenate", "iota", "copy", "slice",
    "add", "subtract", "multiply", "divide", "true_divide", "power",
    "maximum", "minimum", "_where", "fn", "threefry_2x32",
    "_threefry_seed", "_threefry_split", "_uniform", "_normal",
    "_randint", "_gamma", "ones", "zeros", "full",
})


class _CompileLogHandler(logging.Handler):
    def __init__(self, guard: "RetraceGuard"):
        super().__init__(level=logging.DEBUG)
        self._guard = guard

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if msg.startswith(_COMPILE_PREFIX):
            self._guard._on_compile(msg)


class RetraceGuard:
    """Counts XLA compiles per (function, signature); raises on churn.

    ``max_recompiles_per_signature``: how many *repeat* compiles of one
    exact signature are tolerated (default 2 — a remesh away and back
    legitimately rebuilds the eval fn; a third identical compile is
    churn). ``max_signatures_per_fn``: distinct signatures one function
    may compile (default 8 — a live world plus a handful of speculated
    neighbors; shape-drifting inputs blow past it immediately).
    """

    def __init__(
        self,
        max_recompiles_per_signature: int = 2,
        max_signatures_per_fn: int = 8,
        raise_in_place: bool = True,
        ignore_fns: frozenset = DEFAULT_IGNORE_FNS,
    ):
        self.max_recompiles_per_signature = max_recompiles_per_signature
        self.max_signatures_per_fn = max_signatures_per_fn
        self.raise_in_place = raise_in_place
        self.ignore_fns = ignore_fns
        self._lock = threading.Lock()
        self._sig_counts: Dict[str, int] = {}
        self._fn_sigs: Dict[str, set] = {}
        self._pending: List[str] = []
        self._handler: Optional[_CompileLogHandler] = None
        self._prev_log_compiles: Optional[bool] = None
        self._active = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RetraceGuard":
        if self._active:
            return self
        import jax

        self._prev_log_compiles = bool(
            getattr(jax.config, "jax_log_compiles", False)
        )
        jax.config.update("jax_log_compiles", True)
        self._handler = _CompileLogHandler(self)
        logging.getLogger(_COMPILE_LOGGER).addHandler(self._handler)
        self._active = True
        return self

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        if self._handler is not None:
            logging.getLogger(_COMPILE_LOGGER).removeHandler(self._handler)
            self._handler = None
        try:
            import jax

            jax.config.update(
                "jax_log_compiles", bool(self._prev_log_compiles)
            )
        except Exception:
            pass

    def __enter__(self) -> "RetraceGuard":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
        if exc_type is None:
            self.check()

    # -- accounting --------------------------------------------------------

    @staticmethod
    def _fn_of(sig: str) -> str:
        rest = sig[len(_COMPILE_PREFIX):]
        return rest.split(" ", 1)[0] or "<unknown>"

    def _on_compile(self, sig: str) -> None:
        fn = self._fn_of(sig)
        if fn in self.ignore_fns:
            return
        with self._lock:
            n = self._sig_counts.get(sig, 0) + 1
            self._sig_counts[sig] = n
            sigs = self._fn_sigs.setdefault(fn, set())
            sigs.add(sig)
            problem = None
            if n > 1 + self.max_recompiles_per_signature:
                problem = (
                    f"jitted '{fn}' recompiled an ALREADY-SEEN signature "
                    f"(compile #{n} of the same program): cache-defeating "
                    "churn — look for jit re-wrapping in a loop, or "
                    "outputs resharded relative to inputs (pin "
                    "out_shardings). Signature: " + sig[:400]
                )
            elif len(sigs) > self.max_signatures_per_fn:
                problem = (
                    f"jitted '{fn}' compiled {len(sigs)} distinct "
                    f"signatures (> {self.max_signatures_per_fn}): input "
                    "shape/sharding is drifting call-to-call — every "
                    "step pays a full XLA compile. Latest signature: "
                    + sig[:400]
                )
            raising = (
                problem is not None
                and self.raise_in_place
                and threading.current_thread() is threading.main_thread()
            )
            if problem and not raising:
                # background (speculative-compile) threads swallow
                # exceptions by design, and raise_in_place=False defers
                # by contract: queue for the next check(). A violation
                # raised in place is NOT also queued — the caller saw
                # it; a later clean check() must not re-raise it.
                self._pending.append(problem)
        if problem:
            logger.error("retrace guard: %s", problem)
            if raising:
                raise RetraceError(problem)

    # -- inspection --------------------------------------------------------

    def check(self) -> None:
        """Raise any violation recorded since the last check (covers
        ``raise_in_place=False`` and background-thread compiles)."""
        with self._lock:
            pending, self._pending = self._pending, []
        if pending:
            raise RetraceError("; ".join(pending))

    @property
    def compile_count(self) -> int:
        with self._lock:
            return sum(self._sig_counts.values())

    def signatures_of(self, fn: str) -> int:
        with self._lock:
            return len(self._fn_sigs.get(fn, ()))


# ---------------------------------------------------------------------------
# trainer wiring (DLROVER_TPU_RETRACE_GUARD)
# ---------------------------------------------------------------------------

_installed: Optional[RetraceGuard] = None
_install_lock = threading.Lock()


def maybe_install() -> Optional[RetraceGuard]:
    """Process-wide singleton guard when ``DLROVER_TPU_RETRACE_GUARD``
    is on: 1 = defaults, N>=2 = at most N distinct signatures per
    function. Idempotent — every ElasticTrainer calls this; the first
    one wins. Returns the active guard or None when disabled."""
    n = int(flags.RETRACE_GUARD.get() or 0)
    if n <= 0:
        return None
    global _installed
    with _install_lock:
        if _installed is None:
            kwargs = {} if n <= 1 else {"max_signatures_per_fn": n}
            _installed = RetraceGuard(**kwargs).start()
            logger.info(
                "retrace guard active (max %d signatures/fn, %d repeat "
                "compiles/signature)",
                _installed.max_signatures_per_fn,
                _installed.max_recompiles_per_signature,
            )
        return _installed


def installed() -> Optional[RetraceGuard]:
    return _installed


def uninstall() -> None:
    """Tear down the singleton (tests)."""
    global _installed
    with _install_lock:
        if _installed is not None:
            _installed.stop()
            _installed = None
