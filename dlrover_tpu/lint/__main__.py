"""graftlint CLI.

    python -m dlrover_tpu.lint [options] paths...

Exit codes: 0 clean (against the baseline), 1 new violations or
unparsable files, 2 usage error. ``--fix-baseline`` rewrites the
baseline to exactly the current violation set (use after deliberate
grandfathering, never to silence a new violation you should fix).
"""

from __future__ import annotations

import argparse
import sys

from dlrover_tpu.lint import engine
from dlrover_tpu.lint.rules import ALL_RULES, rule_catalog


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.lint",
        description="graftlint: machine-checked elasticity invariants",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument(
        "--baseline",
        default=engine.DEFAULT_BASELINE,
        help="baseline file of grandfathered violations "
        "(default: the checked-in dlrover_tpu/lint/baseline.json)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every violation, ignoring the baseline",
    )
    p.add_argument(
        "--fix-baseline",
        action="store_true",
        help="rewrite the baseline to the current violation set",
    )
    p.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="JGnnn",
        help="run only these rule ids (repeatable)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = p.parse_args(argv)

    if args.list_rules:
        for rid, name, doc in rule_catalog():
            print(f"{rid}  {name:28s} {doc}")
        return 0
    if not args.paths:
        p.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2

    rules = ALL_RULES
    if args.rule:
        if args.fix_baseline:
            # a scoped --fix-baseline would rewrite the baseline with
            # ONLY the selected rules' violations, silently erasing
            # every other rule's grandfathered entries
            print(
                "error: --rule cannot be combined with --fix-baseline "
                "(the baseline must cover the full rule catalog)",
                file=sys.stderr,
            )
            return 2
        wanted = set(args.rule)
        unknown = wanted - {r.id for r in ALL_RULES}
        if unknown:
            print(f"error: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in ALL_RULES if r.id in wanted]

    if args.fix_baseline:
        result = engine.run(
            args.paths, baseline_path=args.baseline, fix_baseline=True,
            rules=rules,
        )
        print(
            f"graftlint: baseline {args.baseline} rewritten with "
            f"{len(result.violations)} violation(s)"
        )
        for e in result.errors:
            print(f"ERROR {e}", file=sys.stderr)
        return 1 if result.errors else 0

    if args.no_baseline:
        violations, errors = engine.lint_paths(args.paths, rules=rules)
        result = engine.LintResult(violations, violations, [], errors)
    else:
        result = engine.run(args.paths, baseline_path=args.baseline,
                            rules=rules)
    engine.report(result)
    return 1 if result.failed else 0


if __name__ == "__main__":
    sys.exit(main())
