"""graftlint + shardcheck + racecheck + wirecheck + memcheck +
statecheck CLI.

    python -m dlrover_tpu.lint [options] paths...       # AST rules
    python -m dlrover_tpu.lint --hlo dp4 [--hlo ...]    # IR rules
    python -m dlrover_tpu.lint --race [paths...]        # concurrency
    python -m dlrover_tpu.lint --wire [paths...]        # wire schema
    python -m dlrover_tpu.lint --mem dp4 [--mem ...]    # memory model
    python -m dlrover_tpu.lint --state [paths...]       # state inventory

Exit codes: 0 clean (against the baseline / contracts / lock-order
graph / wire schema + corpus / state inventory), 1 new violations,
unparsable files, missing contracts, or lock-graph/schema/inventory
drift, 2 usage error.
``--fix-baseline`` rewrites the AST baseline; ``--fix-contracts``
regenerates the SC001 collective-census contracts (``--hlo``) or the
MC001 memory contracts (``--mem``) for the given mesh specs;
``--fix-lock-order`` / ``--fix-race-baseline`` re-record the
RC001 acquisition graph and the racecheck baseline;
``--fix-wire-schema`` records a wire/durable schema change (give the
compat rationale via ``--wire-note``) and ``--fix-wire-corpus``
regenerates the golden serialized corpus; ``--fix-state-inventory``
regenerates the ST001 state inventory, preserving its hand-triaged
whitelist (all: use after deliberate grandfathering or a reviewed
change, never to silence a new violation you should fix).

The ``--hlo`` and ``--mem`` paths lower the pinned contract model (see
lint/contract_model.py) on virtual CPU devices — no TPU, no live
training process — and run the SC rules over the lowered StableHLO +
optimized HLO text (``--hlo``) or the MC rules over the per-device
memory model of the compiled step (``--mem``). The ``--race`` path is
a whole-repo analysis (cross-file lock identity), so it takes the
package root, not single files (see lint/racecheck.py).
"""

from __future__ import annotations

import argparse
import sys

from dlrover_tpu.lint import engine, shardcheck
from dlrover_tpu.lint.rules import ALL_RULES, rule_catalog


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.lint",
        description="graftlint: machine-checked elasticity invariants",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument(
        "--baseline",
        default=engine.DEFAULT_BASELINE,
        help="baseline file of grandfathered violations "
        "(default: the checked-in dlrover_tpu/lint/baseline.json)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every violation, ignoring the baseline",
    )
    p.add_argument(
        "--fix-baseline",
        action="store_true",
        help="rewrite the baseline to the current violation set",
    )
    p.add_argument(
        "--rule",
        action="append",
        default=None,
        metavar="JGnnn",
        help="run only these rule ids (repeatable)",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    p.add_argument(
        "--hlo",
        action="append",
        default=None,
        metavar="MESHSPEC",
        help="IR mode: lower the contract model for this mesh spec "
        "(e.g. dp4, dp2xfsdp2, sp2xdp2, a zero-1 variant like "
        "dp4+zero1, a multislice hierarchical variant like "
        "dp4+2slice / dp4+2slice+zero1, or an overlap-scheduled one "
        "like dp4+2slice+overlap; repeatable) and run the SC rules "
        "over the lowered program",
    )
    p.add_argument(
        "--contracts",
        default=shardcheck.DEFAULT_CONTRACTS_DIR,
        help="SC001 contract directory (default: the checked-in "
        "dlrover_tpu/lint/contracts)",
    )
    p.add_argument(
        "--fix-contracts",
        action="store_true",
        help="regenerate the contracts for the given --hlo mesh specs",
    )
    p.add_argument(
        "--byte-tolerance",
        type=float,
        default=shardcheck.DEFAULT_BYTE_TOLERANCE,
        help="SC001: allowed fractional byte growth per collective cell "
        f"(default {shardcheck.DEFAULT_BYTE_TOLERANCE})",
    )
    p.add_argument(
        "--mem",
        action="append",
        default=None,
        metavar="MESHSPEC",
        help="memory mode: lower the contract model for this mesh spec "
        "(same grammar as --hlo; repeatable) and run the MC rules over "
        "its static per-device memory model (lint/memcheck.py)",
    )
    p.add_argument(
        "--device-class",
        default="",
        help="MC002: per-device HBM budget class for --mem "
        "(v5e | v5p | cpu-host; default: no budget check)",
    )
    p.add_argument(
        "--budget-gb",
        type=float,
        default=0.0,
        help="MC002: explicit per-device HBM budget in GB for --mem "
        "(overrides --device-class)",
    )
    p.add_argument(
        "--race",
        action="store_true",
        help="concurrency mode: whole-repo lock-order + guarded-by "
        "analysis (RC rules) against the checked-in lock_order.json "
        "and racecheck baseline",
    )
    p.add_argument(
        "--lock-order",
        default=None,
        help="RC001 acquisition-graph file (default: the checked-in "
        "dlrover_tpu/lint/lock_order.json)",
    )
    p.add_argument(
        "--race-baseline",
        default=None,
        help="racecheck baseline file (default: the checked-in "
        "dlrover_tpu/lint/racecheck_baseline.json)",
    )
    p.add_argument(
        "--fix-lock-order",
        action="store_true",
        help="re-record the RC001 acquisition graph from the current "
        "tree (use for a reviewed, intentional new edge)",
    )
    p.add_argument(
        "--fix-race-baseline",
        action="store_true",
        help="rewrite the racecheck baseline to the current finding set",
    )
    p.add_argument(
        "--wire",
        action="store_true",
        help="wire mode: schema registry diff against the checked-in "
        "lint/wire_schema.json, golden-corpus replay, and the WC skew "
        "rules over the AST (docs/design/wirecheck.md)",
    )
    p.add_argument(
        "--wire-schema",
        default=None,
        help="wire schema file (default: the checked-in "
        "dlrover_tpu/lint/wire_schema.json)",
    )
    p.add_argument(
        "--wire-corpus",
        default=None,
        help="golden corpus directory (default: the checked-in "
        "dlrover_tpu/lint/wire_corpus)",
    )
    p.add_argument(
        "--fix-wire-schema",
        action="store_true",
        help="record the current wire/durable schema (with a history "
        "entry; pair with --wire-note explaining why the change is "
        "skew-compatible)",
    )
    p.add_argument(
        "--fix-wire-corpus",
        action="store_true",
        help="regenerate the golden serialized corpus (legacy pins are "
        "frozen and never rewritten)",
    )
    p.add_argument(
        "--wire-note",
        default="",
        help="compat note recorded in the schema history by "
        "--fix-wire-schema",
    )
    p.add_argument(
        "--state",
        action="store_true",
        help="state mode: mutable-state inventory diff against the "
        "checked-in lint/state_inventory.json, tenant-isolation rules "
        "(ST001-ST004) and the baseline-liveness gate ST005 "
        "(docs/design/statecheck.md)",
    )
    p.add_argument(
        "--state-inventory",
        default=None,
        help="state inventory file (default: the checked-in "
        "dlrover_tpu/lint/state_inventory.json)",
    )
    p.add_argument(
        "--fix-state-inventory",
        action="store_true",
        help="regenerate the state section of the inventory from the "
        "current tree (the whitelist is hand-maintained and preserved)",
    )
    args = p.parse_args(argv)

    if args.list_rules:
        from dlrover_tpu.lint import memcheck, racecheck, statecheck, \
            wirecheck

        for rid, name, doc in rule_catalog():
            print(f"{rid}  {name:28s} {doc}")
        for rid, name, doc in shardcheck.SC_RULES:
            print(f"{rid}  {name:28s} {doc}")
        for rid, name, doc in racecheck.RC_RULES:
            print(f"{rid}  {name:28s} {doc}")
        for rid, name, doc in wirecheck.WC_RULES:
            print(f"{rid}  {name:28s} {doc}")
        for rid, name, doc in memcheck.MC_RULES:
            print(f"{rid}  {name:28s} {doc}")
        for rid, name, doc in statecheck.ST_RULES:
            print(f"{rid}  {name:28s} {doc}")
        return 0
    if args.state:
        if args.hlo or args.mem or args.race or args.wire \
                or args.fix_baseline or args.no_baseline or args.rule:
            print(
                "error: --state (state mode) cannot be combined with "
                "--hlo, --mem, --race, --wire, --fix-baseline, "
                "--no-baseline or --rule — run them as separate "
                "invocations",
                file=sys.stderr,
            )
            return 2
        return _run_state(args)
    if args.fix_state_inventory:
        print(
            "error: --fix-state-inventory needs --state",
            file=sys.stderr,
        )
        return 2
    if args.wire:
        if args.hlo or args.mem or args.race or args.fix_baseline \
                or args.no_baseline or args.rule:
            print(
                "error: --wire (schema mode) cannot be combined with "
                "--hlo, --mem, --race, --fix-baseline, --no-baseline "
                "or --rule — run them as separate invocations",
                file=sys.stderr,
            )
            return 2
        return _run_wire(args)
    if args.fix_wire_schema or args.fix_wire_corpus:
        print(
            "error: --fix-wire-schema / --fix-wire-corpus need --wire",
            file=sys.stderr,
        )
        return 2
    if args.race:
        if args.hlo or args.mem or args.fix_baseline or args.no_baseline \
                or args.rule:
            print(
                "error: --race (concurrency mode) cannot be combined "
                "with --hlo, --mem, --fix-baseline, --no-baseline or "
                "--rule — run them as separate invocations",
                file=sys.stderr,
            )
            return 2
        return _run_race(args)
    if args.fix_lock_order or args.fix_race_baseline:
        print(
            "error: --fix-lock-order / --fix-race-baseline need --race",
            file=sys.stderr,
        )
        return 2
    if args.hlo or args.mem:
        if args.hlo and args.mem:
            print(
                "error: --hlo (IR mode) and --mem (memory mode) are "
                "separate invocations (each owns --fix-contracts)",
                file=sys.stderr,
            )
            return 2
        if args.paths or args.fix_baseline or args.no_baseline or args.rule:
            mode = "--hlo (IR mode)" if args.hlo else "--mem (memory mode)"
            print(
                f"error: {mode} cannot be combined with paths, "
                "--fix-baseline, --no-baseline or --rule (AST mode) — "
                "run them as separate invocations",
                file=sys.stderr,
            )
            return 2
        return _run_hlo(args) if args.hlo else _run_mem(args)
    if args.fix_contracts:
        print("error: --fix-contracts needs --hlo or --mem MESHSPEC",
              file=sys.stderr)
        return 2
    if not args.paths:
        p.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2

    rules = ALL_RULES
    if args.rule:
        if args.fix_baseline:
            # a scoped --fix-baseline would rewrite the baseline with
            # ONLY the selected rules' violations, silently erasing
            # every other rule's grandfathered entries
            print(
                "error: --rule cannot be combined with --fix-baseline "
                "(the baseline must cover the full rule catalog)",
                file=sys.stderr,
            )
            return 2
        wanted = set(args.rule)
        unknown = wanted - {r.id for r in ALL_RULES}
        if unknown:
            print(f"error: unknown rule(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = [r for r in ALL_RULES if r.id in wanted]

    if args.fix_baseline:
        result = engine.run(
            args.paths, baseline_path=args.baseline, fix_baseline=True,
            rules=rules,
        )
        print(
            f"graftlint: baseline {args.baseline} rewritten with "
            f"{len(result.violations)} violation(s)"
        )
        for e in result.errors:
            print(f"ERROR {e}", file=sys.stderr)
        return 1 if result.errors else 0

    if args.no_baseline:
        violations, errors = engine.lint_paths(args.paths, rules=rules)
        result = engine.LintResult(violations, violations, [], errors)
    else:
        result = engine.run(args.paths, baseline_path=args.baseline,
                            rules=rules)
    engine.report(result)
    return 1 if result.failed else 0


def _run_state(args) -> int:
    """State mode: mutable-state inventory diff + tenant-isolation
    rules + baseline liveness."""
    from dlrover_tpu.lint import statecheck

    paths = args.paths or ["dlrover_tpu"]
    result = statecheck.run(
        paths,
        inventory_path=args.state_inventory,
        fix_inventory=args.fix_state_inventory,
    )
    if args.fix_state_inventory:
        n = len(result.scanner.state)
        print(
            f"statecheck: inventory "
            f"{args.state_inventory or statecheck.DEFAULT_INVENTORY} "
            f"rewritten ({n} state entr{'y' if n == 1 else 'ies'}; "
            "whitelist preserved)"
        )
    statecheck.report(result)
    return 1 if result.failed else 0


def _run_wire(args) -> int:
    """Wire mode: schema diff + golden-corpus replay + WC AST rules."""
    from dlrover_tpu.lint import wirecheck

    result = wirecheck.run(
        paths=args.paths or None,
        schema_path=args.wire_schema or wirecheck.DEFAULT_SCHEMA,
        corpus_dir=args.wire_corpus or wirecheck.DEFAULT_CORPUS_DIR,
        fix_schema=args.fix_wire_schema,
        fix_corpus=args.fix_wire_corpus,
        note=args.wire_note,
    )
    if args.fix_wire_schema:
        print(
            "wirecheck: schema "
            f"{args.wire_schema or wirecheck.DEFAULT_SCHEMA} recorded"
        )
    if args.fix_wire_corpus:
        print(
            "wirecheck: corpus "
            f"{args.wire_corpus or wirecheck.DEFAULT_CORPUS_DIR} "
            "regenerated"
        )
    wirecheck.report(result)
    return 1 if result.failed else 0


def _run_race(args) -> int:
    """Concurrency mode: whole-repo RC rules + lock-order graph diff."""
    from dlrover_tpu.lint import racecheck

    paths = args.paths or ["dlrover_tpu"]
    result = racecheck.run(
        paths,
        lock_order_path=args.lock_order,
        baseline_path=args.race_baseline,
        fix_lock_order=args.fix_lock_order,
        fix_baseline=args.fix_race_baseline,
    )
    cycles = [v for v in result.violations if v.rule == "RC001"]
    if args.fix_lock_order:
        if cycles:
            # nothing was written: a cyclic graph must never seed the
            # tracker or pass the diff gate
            print(
                "racecheck: lock order NOT rewritten — the current "
                "tree has a lock-order cycle; fix it first:",
                file=sys.stderr,
            )
        else:
            print(
                f"racecheck: lock order "
                f"{args.lock_order or racecheck.DEFAULT_LOCK_ORDER} "
                f"rewritten ({len(result.edges)} edge(s) over "
                f"{len(result.model.locks)} lock(s))"
            )
    if args.fix_race_baseline:
        if cycles:
            print(
                "racecheck: baseline NOT rewritten — a deadlock cycle "
                "is never baselinable; fix it first:",
                file=sys.stderr,
            )
        else:
            print(
                f"racecheck: baseline "
                f"{args.race_baseline or racecheck.DEFAULT_RACE_BASELINE} "
                f"rewritten with "
                f"{len([v for v in result.violations if v.rule != 'RC001'])}"
                " finding(s)"
            )
        for e in result.errors:
            print(f"ERROR {e}", file=sys.stderr)
        for v in cycles:
            print(v.format(), file=sys.stderr)
        return 1 if result.errors or cycles else 0
    if args.fix_lock_order and cycles:
        for v in cycles:
            print(v.format(), file=sys.stderr)
        return 1
    racecheck.report(result)
    return 1 if result.failed else 0


def _run_hlo(args) -> int:
    """IR mode: one contract-model lowering per mesh spec."""
    from dlrover_tpu.lint import contract_model

    specs = []
    worlds = []
    for raw in args.hlo:
        try:
            wd = shardcheck.WorldDescriptor.parse(raw)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        specs.append(wd.spec)  # canonicalized
        w = 1
        for s in wd.axis_sizes().values():
            w *= s
        worlds.append(w)

    # every spec shares one jax process: size the virtual CPU device
    # pool to the largest world before anything touches jax
    contract_model.ensure_cpu_devices(max(worlds))

    failed = False
    for spec in specs:
        try:
            program, _ = contract_model.build_program(spec)
        except Exception as e:
            print(f"{spec}: lowering failed: {e}", file=sys.stderr)
            failed = True
            continue
        if args.fix_contracts:
            import jax

            data = shardcheck.write_contract(
                args.contracts, spec, program,
                extra={
                    "jax_version": jax.__version__,
                    "seq_len": contract_model.SEQ_LEN,
                    "vocab": contract_model.VOCAB,
                    "zero1": program.zero1,
                },
            )
            note = ""
            if "overlap" in data:
                note = (
                    f", dcn overlap_ratio="
                    f"{data['overlap']['overlap_ratio']:.4f}"
                )
            print(
                f"shardcheck: contract {spec} rewritten "
                f"({len(data['census'])} collective cell(s), "
                f"{len(data['custom_calls'])} kernel target(s), "
                f"world={program.world}{note})"
            )
            continue
        try:
            contract = shardcheck.load_contract(args.contracts, spec)
        except ValueError as e:
            print(f"{spec}: {e}", file=sys.stderr)
            failed = True
            continue
        if contract is None:
            print(
                f"{spec}: no contract at "
                f"{shardcheck.contract_path(args.contracts, spec)} — "
                "generate one with --fix-contracts",
                file=sys.stderr,
            )
            failed = True
            continue
        census = shardcheck.collective_census(
            program.hlo, program.coords()
        )
        violations = shardcheck.check_program(
            program, contract, byte_tolerance=args.byte_tolerance,
            census=census,
        )
        for v in violations:
            print(v.format())
        better = shardcheck.census_improvements(census, contract)
        if better:
            print(
                f"note: {spec} communicates less than its contract "
                f"({len(better)} cell(s) improved — run --fix-contracts "
                "to bank it):"
            )
            for line in better:
                print(f"  {line}")
        status = "FAIL" if violations else "ok"
        overlap_note = ""
        if program.n_slices > 1:
            rep = shardcheck.overlap_report(
                program.hlo, program.coords()
            )
            overlap_note = (
                f", dcn exposed={rep['dcn_exposed_bytes']}B "
                f"overlapped={rep['dcn_overlapped_bytes']}B "
                f"ratio={rep['overlap_ratio']:.4f}"
            )
        kernels = shardcheck.custom_call_census(program.hlo)
        print(
            f"shardcheck: {spec} {status} ({len(violations)} violation(s),"
            f" {sum(c['count'] for c in census.values())} collectives over"
            f" {len(census)} cell(s),"
            f" {len(kernels)} kernel target(s){overlap_note})"
        )
        failed = failed or bool(violations)
    return 1 if failed else 0


def _run_mem(args) -> int:
    """Memory mode: one contract-model build per mesh spec, MC rules
    over its static per-device memory model."""
    from dlrover_tpu.lint import contract_model, memcheck

    specs = []
    worlds = []
    for raw in args.mem:
        try:
            wd = shardcheck.WorldDescriptor.parse(raw)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        specs.append(wd.spec)  # canonicalized
        w = 1
        for s in wd.axis_sizes().values():
            w *= s
        worlds.append(w)

    contract_model.ensure_cpu_devices(max(worlds))

    failed = False
    for spec in specs:
        try:
            payload = contract_model.build_memcheck(spec)
        except Exception as e:
            print(f"{spec}: lowering failed: {e}", file=sys.stderr)
            failed = True
            continue
        if args.fix_contracts:
            import jax

            data = memcheck.write_mem_contract(
                args.contracts, spec,
                payload["components"], payload["peak_bytes"],
                measured=payload["measured"],
                extra={
                    "config_hash": payload["config_hash"],
                    "world": payload["world"],
                    "axis_sizes": {
                        a: s for a, s in payload["axis_sizes"].items()
                        if s > 1
                    },
                    "jax_version": jax.__version__,
                },
            )
            print(
                f"memcheck: contract {spec} rewritten "
                f"(peak {data['peak_bytes']} bytes/device, "
                f"world={payload['world']})"
            )
            continue
        try:
            contract = memcheck.load_mem_contract(args.contracts, spec)
        except ValueError as e:
            print(f"{spec}: {e}", file=sys.stderr)
            failed = True
            continue
        if contract is None:
            print(
                f"{spec}: no contract at "
                f"{memcheck.mem_contract_path(args.contracts, spec)} — "
                "generate one with --fix-contracts",
                file=sys.stderr,
            )
            failed = True
            continue
        if (
            contract.get("config_hash")
            and contract["config_hash"] != payload["config_hash"]
        ):
            # unlike the lower-time hook, the CLI program is PINNED:
            # a hash mismatch here means the contract is stale, and
            # staying quiet would un-arm MC001 in CI
            print(
                f"{spec}: contract is for config "
                f"{contract['config_hash']} but the pinned program is "
                f"{payload['config_hash']} — regenerate with "
                "--fix-contracts",
                file=sys.stderr,
            )
            failed = True
            continue
        violations = memcheck.check_components(
            payload["components"], payload["peak_bytes"], contract,
            byte_tolerance=args.byte_tolerance, label=f"mem:{spec}",
        )
        violations.extend(memcheck.check_budget(
            payload["peak_bytes"],
            device_class=args.device_class, budget_gb=args.budget_gb,
            label=f"mem:{spec}",
        ))
        for v in violations:
            print(v.format())
        better = memcheck.component_improvements(
            payload["components"], payload["peak_bytes"], contract,
            byte_tolerance=args.byte_tolerance,
        )
        if better:
            print(
                f"note: {spec} uses less memory than its contract "
                f"({len(better)} component(s) improved — run "
                "--fix-contracts to bank it):"
            )
            for line in better:
                print(f"  {line}")
        status = "FAIL" if violations else "ok"
        delta = payload.get("argument_delta_frac")
        delta_note = (
            f", arguments explained to {delta:.2%}"
            if delta is not None else ""
        )
        print(
            f"memcheck: {spec} {status} ({len(violations)} violation(s),"
            f" peak {payload['peak_bytes']} bytes/device"
            f"{delta_note})"
        )
        failed = failed or bool(violations)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
