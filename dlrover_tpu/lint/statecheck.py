"""statecheck: static mutable-state inventory + tenant-isolation lint.

graftlint checks per-file AST rules, shardcheck the lowered IR, racecheck
the lock structure, wirecheck the RPC schema, memcheck the device-memory
contracts — this is the sixth machine-checked invariant layer: the
master's *process-mutable state surface*. The multi-job refactor
(``master/job_container.py``) moved every piece of per-job state behind
an explicit container; statecheck is what keeps it there:

- the **state inventory** (``lint/state_inventory.json``) enumerates
  every piece of process-mutable state in ``dlrover_tpu/master/``,
  ``common/`` and ``rpc/`` — module-level mutable bindings, mutable
  class attributes, singleton patterns, ``global``-rebound module
  names, and the JobContainer's own per-job slots — each classified
  ``per_job`` (lives behind the container), ``process_global``
  (whitelisted, with a reason), or ``violation`` (neither). The file
  is checked in and two-sided-diffed like wirecheck's schema: state
  that exists but is not inventoried fails (ST001), and inventory
  entries whose code is gone fail as drift, so the file never rots.
- **ST002** fails on any scanned state that is neither a per-job slot
  nor whitelisted — the "new module-level cache" regression gate.
- **ST003** fails on bare singleton patterns (``_instance`` class
  slots, ``singleton()``/``reset_singleton()`` classmethods): per-job
  state must be a JobContainer slot, not a process singleton.
- **ST004** walks the servicer's handler dispatch tables and flags any
  ambient-accessor call (``get_job_context``, ``get_master_config``,
  ``default_container``, ``singleton``...) reachable from an RPC
  handler within two call-graph hops (racecheck's resolution rules):
  handlers operate on state *injected at composition time*, so one
  process can serve two jobs without the handlers cross-reading.
- **ST005** is the baseline-liveness gate: every entry in
  ``lint/baseline.json`` and ``lint/racecheck_baseline.json`` must
  still resolve to a real file containing the recorded line text —
  entries referencing symbols retired by later PRs fail until the
  baseline is regenerated.

Suppression reuses the graftlint comment syntax (``# graftlint:
disable=ST002 <why>``). There is deliberately NO baseline file:
state either has a classification or the build fails. CLI:
``python -m dlrover_tpu.lint --state`` (exit 0 clean / 1 findings or
inventory drift / 2 usage); ``--fix-state-inventory`` regenerates the
``state`` section, preserving the hand-triaged whitelist.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.lint.engine import (
    DEFAULT_BASELINE,
    Severity,
    SourceFile,
    Violation,
    iter_py_files,
)
from dlrover_tpu.lint.racecheck import (
    DEFAULT_RACE_BASELINE,
    FuncInfo,
    RepoModel,
    _module_name,
)

#: checked-in inventory (regenerate with --fix-state-inventory)
DEFAULT_INVENTORY = os.path.join(
    os.path.dirname(__file__), "state_inventory.json"
)

#: the master's tenant-state scope: everything an RPC handler can reach
SCOPE_PREFIXES = ("master/", "common/", "rpc/")

#: constructors whose result is process-mutable state when bound at
#: module or class level
MUTABLE_CALLS = {
    "dict", "list", "set", "defaultdict", "OrderedDict", "deque",
    "Counter", "count",
}
#: lock constructors: concurrency structure is racecheck's artifact,
#: not state inventory
LOCK_CALLS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
              "maybe_track", "local"}

#: legacy ambient accessors: composition roots may call them; RPC
#: handler call graphs may not (ST004)
AMBIENT_ACCESSORS = {
    "get_job_context",
    "get_master_config",
    "default_container",
    "singleton",
    "singleton_instance",
}

#: method names that mark a class as a bare singleton (ST003)
SINGLETON_METHODS = {"singleton", "singleton_instance", "reset_singleton"}
#: class-attribute names that mark a singleton slot
SINGLETON_ATTRS = {"_instance", "_singleton", "_INSTANCE"}

ST_RULES = (
    ("ST001", "untracked-state",
     "process-mutable state not recorded in lint/state_inventory.json"),
    ("ST002", "state-violation",
     "mutable state that is neither a per-job container slot nor a "
     "whitelisted process-global"),
    ("ST003", "bare-singleton",
     "singleton pattern outside the JobContainer registry"),
    ("ST004", "ambient-access-in-handler",
     "RPC handler call graph reaches a process-ambient state accessor"),
    ("ST005", "dead-baseline-entry",
     "baseline entry no longer resolves to a real source line"),
)


# ---------------------------------------------------------------------------
# the state scan
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StateDef:
    state_id: str  # module.qualname (racecheck id convention)
    kind: str  # module_mutable | class_attr_mutable | singleton |
    #          # module_global_rebind | per_job_slot
    path: str
    line: int
    classification: str = ""  # per_job | process_global | violation


def _in_scope(rel_path: str) -> bool:
    """Package files are scoped to master/common/rpc; files outside the
    package (test fixtures under tmp dirs) are always in scope so the
    seeded-regression tests can exercise the rules directly."""
    p = rel_path.replace(os.sep, "/")
    if "dlrover_tpu/" in p:
        sub = p.split("dlrover_tpu/", 1)[-1]
        return sub.startswith(SCOPE_PREFIXES)
    return True


def _mutable_value(value: Optional[ast.AST]) -> bool:
    """Is this expression a process-mutable container/builder?"""
    if value is None:
        return False
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        from dlrover_tpu.lint.rules import dotted_name

        callee = dotted_name(value.func).rsplit(".", 1)[-1]
        if callee in LOCK_CALLS:
            return False
        return callee in MUTABLE_CALLS
    return False


#: kind precedence when one name is detected twice (a module dict that
#: is also ``global``-rebound keeps the mutable kind)
_KIND_RANK = {
    "singleton": 0,
    "per_job_slot": 1,
    "module_mutable": 2,
    "class_attr_mutable": 3,
    "module_global_rebind": 4,
}


class StateScanner:
    """One pass over the sources; collects every StateDef."""

    def __init__(self):
        self.state: Dict[str, StateDef] = {}
        self.sources: Dict[str, SourceFile] = {}
        self.errors: List[str] = []

    def _add(self, state_id: str, kind: str, src: SourceFile, node):
        line = getattr(node, "lineno", 1)
        old = self.state.get(state_id)
        if old is not None and _KIND_RANK[old.kind] <= _KIND_RANK[kind]:
            return
        self.state[state_id] = StateDef(
            state_id, kind, src.rel_path.replace(os.sep, "/"), line
        )

    def scan_file(self, src: SourceFile):
        module = _module_name(src.rel_path)
        self.sources[src.rel_path] = src
        # module-level mutable bindings
        for node in src.tree.body:
            self._scan_binding(src, module, "", node)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                self._scan_class(src, module, node)
            elif isinstance(node, ast.Global):
                for name in node.names:
                    self._add(
                        f"{module}.{name}", "module_global_rebind", src, node
                    )

    def _scan_binding(self, src: SourceFile, module: str, cls: str, node):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            return
        value = node.value
        if not _mutable_value(value):
            return
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id.startswith("__") and t.id.endswith("__"):
                continue  # __all__ and friends
            owner = f"{module}.{cls}" if cls else module
            kind = "class_attr_mutable" if cls else "module_mutable"
            self._add(f"{owner}.{t.id}", kind, src, node)

    def _scan_class(self, src: SourceFile, module: str, cls: ast.ClassDef):
        singleton_site = None
        for node in cls.body:
            self._scan_binding(src, module, cls.name, node)
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in SINGLETON_ATTRS:
                        singleton_site = singleton_site or node
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in SINGLETON_METHODS
            ):
                singleton_site = singleton_site or node
        if singleton_site is not None:
            self._add(
                f"{module}.{cls.name}", "singleton", src, singleton_site
            )
        if cls.name == "JobContainer":
            self._scan_container(src, module, cls)

    def _scan_container(self, src: SourceFile, module: str,
                        cls: ast.ClassDef):
        """Every ``self.X = ...`` in JobContainer.__init__ is a per-job
        slot: removing one from the container changes the inventory and
        fails the two-sided diff, same as adding ambient state."""
        for node in cls.body:
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "__init__"
            ):
                for stmt in ast.walk(node):
                    if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                        continue
                    targets = (
                        stmt.targets
                        if isinstance(stmt, ast.Assign)
                        else [stmt.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            self._add(
                                f"{module}.{cls.name}.{t.attr}",
                                "per_job_slot", src, stmt,
                            )


def scan_state(paths: Sequence[str]) -> StateScanner:
    scanner = StateScanner()
    for full, display in iter_py_files(paths):
        if not _in_scope(display):
            continue
        try:
            with open(full, encoding="utf-8") as f:
                text = f.read()
            src = SourceFile(full, text, rel_path=display)
        except (OSError, SyntaxError, ValueError) as e:
            scanner.errors.append(f"{display}: unparsable: {e}")
            continue
        scanner.scan_file(src)
    return scanner


def classify(scanner: StateScanner, whitelist: Dict[str, str]) -> None:
    for sd in scanner.state.values():
        if sd.kind == "per_job_slot":
            sd.classification = "per_job"
        elif sd.state_id in whitelist:
            sd.classification = "process_global"
        else:
            sd.classification = "violation"


# ---------------------------------------------------------------------------
# the checked-in inventory
# ---------------------------------------------------------------------------


def load_inventory(path: str) -> Optional[Dict]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return None
    if not isinstance(data, dict) or "state" not in data:
        raise ValueError(f"{path}: not a statecheck inventory file")
    return data


def write_inventory(
    path: str, scanner: StateScanner, whitelist: Dict[str, str]
) -> Dict:
    data = {
        "comment": (
            "statecheck state inventory: every piece of process-mutable "
            "state in master/, common/ and rpc/, classified per_job "
            "(JobContainer slot), process_global (whitelisted below, "
            "with a reason), or violation. CI two-sided-diffs this "
            "file. Regenerate the state section with: python -m "
            "dlrover_tpu.lint --state --fix-state-inventory dlrover_tpu/ "
            "(the whitelist is hand-maintained and preserved)."
        ),
        "version": 1,
        "whitelist": {k: whitelist[k] for k in sorted(whitelist)},
        "state": {
            sd.state_id: {
                "kind": sd.kind,
                "path": sd.path,
                "classification": sd.classification,
            }
            for sd in sorted(
                scanner.state.values(), key=lambda s: s.state_id
            )
        },
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _violation(
    src: Optional[SourceFile], rule: str, path: str, line: int, message: str
) -> Optional[Violation]:
    if src is not None and src.suppressed(rule, line):
        return None
    snippet = src.snippet_at(line) if src is not None else ""
    return Violation(
        rule=rule, path=path, line=line, col=0, message=message,
        snippet=snippet, severity=Severity.ERROR,
    )


def check_inventory(
    scanner: StateScanner, checked_in: Optional[Dict]
) -> Tuple[List[Violation], List[str]]:
    """ST001 + ST002: the two-sided diff plus the classification gate."""
    violations: List[Violation] = []
    drift: List[str] = []
    recorded = (checked_in or {}).get("state", {})
    if checked_in is None:
        drift.append(
            "no checked-in state_inventory.json — generate it with "
            "--state --fix-state-inventory and triage every entry"
        )
    for sd in sorted(scanner.state.values(), key=lambda s: s.state_id):
        src = scanner.sources.get(sd.path)
        entry = recorded.get(sd.state_id)
        if checked_in is not None and entry is None:
            v = _violation(
                src, "ST001", sd.path, sd.line,
                f"process-mutable state {sd.state_id} ({sd.kind}) is not "
                "in lint/state_inventory.json — every piece of mutable "
                "master state must be inventoried and classified. Run "
                "--state --fix-state-inventory, then either move the "
                "state into the JobContainer or whitelist it with a "
                "reason.",
            )
            if v is not None:
                violations.append(v)
        elif entry is not None and (
            entry.get("kind") != sd.kind
            or entry.get("classification") != sd.classification
        ):
            drift.append(
                f"state_inventory.json: {sd.state_id} drifted "
                f"(recorded {entry.get('kind')}/"
                f"{entry.get('classification')}, scanned {sd.kind}/"
                f"{sd.classification}) — run --fix-state-inventory"
            )
        if sd.classification == "violation":
            v = _violation(
                src, "ST002", sd.path, sd.line,
                f"{sd.state_id} ({sd.kind}) is process-mutable state "
                "outside the per-job container and not whitelisted: a "
                "second job in this process would share it. Move it "
                "onto JobContainer (or an instance the container owns), "
                "or add a whitelist entry to lint/state_inventory.json "
                "with the reason it is legitimately process-global.",
            )
            if v is not None:
                violations.append(v)
    for state_id in sorted(set(recorded) - set(scanner.state)):
        drift.append(
            f"state_inventory.json: stale entry {state_id} no longer "
            "exists in the tree — run --fix-state-inventory to shrink "
            "the inventory"
        )
    return violations, drift


def check_st003(
    scanner: StateScanner, whitelist: Dict[str, str]
) -> List[Violation]:
    out: List[Violation] = []
    for sd in sorted(scanner.state.values(), key=lambda s: s.state_id):
        if sd.kind != "singleton" or sd.state_id in whitelist:
            continue
        src = scanner.sources.get(sd.path)
        v = _violation(
            src, "ST003", sd.path, sd.line,
            f"{sd.state_id} is a bare singleton (instance slot / "
            "singleton classmethods): per-job state must live on the "
            "JobContainer so two jobs in one process stay isolated. "
            "Make the class an injected container slot, or whitelist "
            "it with the reason it is process-scoped.",
        )
        if v is not None:
            out.append(v)
    return out


# -- ST004: handler call graphs ---------------------------------------------


def _handler_funcs(model: RepoModel) -> List[FuncInfo]:
    """Seed set: every method wired into a ``self._get_handlers`` /
    ``self._report_handlers`` dispatch dict, plus the ``get``/``report``
    entry points of the class owning the dict."""
    out: List[FuncInfo] = []
    seen: Set[Tuple[str, str, str]] = set()
    for (module, cls, name), info in sorted(model.funcs.items()):
        if name != "__init__" or not cls:
            continue
        handler_names: Set[str] = set()
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Assign):
                continue
            is_table = any(
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and t.attr in ("_get_handlers", "_report_handlers")
                for t in node.targets
            )
            if not is_table or not isinstance(node.value, ast.Dict):
                continue
            for v in node.value.values:
                if (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                ):
                    handler_names.add(v.attr)
        if not handler_names:
            continue
        handler_names |= {"get", "report"}
        for h in sorted(handler_names):
            key = (module, cls, h)
            g = model.funcs.get(key)
            if g is not None and key not in seen:
                seen.add(key)
                out.append(g)
    return out


def _accessor_calls(info: FuncInfo) -> Iterable[Tuple[ast.Call, str]]:
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name in AMBIENT_ACCESSORS:
            yield node, name


def check_st004(model: RepoModel, hops: int = 2) -> List[Violation]:
    out: List[Violation] = []
    seen_sites: Set[Tuple[str, int]] = set()
    handlers = _handler_funcs(model)
    for h in handlers:
        visited: Set[Tuple[str, str, str]] = set()
        frontier: List[Tuple[FuncInfo, List[str]]] = [(h, [h.name])]
        for depth in range(hops + 1):
            nxt: List[Tuple[FuncInfo, List[str]]] = []
            for info, chain in frontier:
                key = (info.module, info.cls, info.name)
                if key in visited:
                    continue
                visited.add(key)
                if info.name in AMBIENT_ACCESSORS and depth > 0:
                    continue  # flagged at the call site already
                for call_node, name in _accessor_calls(info):
                    site = (
                        info.src.rel_path,
                        getattr(call_node, "lineno", 1),
                    )
                    if site in seen_sites:
                        continue
                    seen_sites.add(site)
                    v = _violation(
                        info.src, "ST004", info.src.rel_path,
                        getattr(call_node, "lineno", 1),
                        f"{name}() reached from RPC handler "
                        f"{' -> '.join(chain)}: handlers must use state "
                        "injected at composition time (the servicer's "
                        "job_context/config parameters), never the "
                        "process-ambient accessor — a second job in "
                        "this process would cross-read. Thread the "
                        "dependency through the constructor.",
                    )
                    if v is not None:
                        out.append(v)
                if depth < hops:
                    for call in sorted(info.calls):
                        for g in model.callees(info, call):
                            gkey = (g.module, g.cls, g.name)
                            if gkey not in visited:
                                nxt.append((g, chain + [g.name]))
            frontier = nxt
    out.sort(key=lambda v: (v.path, v.line))
    return out


# -- ST005: baseline liveness -----------------------------------------------


def check_st005(
    baseline_paths: Optional[Sequence[str]] = None,
    root: str = ".",
) -> List[str]:
    """Every grandfathered finding must still point at a live source
    line: (path exists) and (snippet appears among the file's stripped
    lines). Dead entries mean a PR retired the symbol without
    regenerating the baseline — the file rots into noise."""
    if baseline_paths is None:
        baseline_paths = (DEFAULT_BASELINE, DEFAULT_RACE_BASELINE)
    problems: List[str] = []
    for bpath in baseline_paths:
        try:
            with open(bpath, encoding="utf-8") as f:
                data = json.load(f)
        except FileNotFoundError:
            continue
        except ValueError as e:
            problems.append(f"{bpath}: unreadable baseline: {e}")
            continue
        stripped_cache: Dict[str, Optional[Set[str]]] = {}
        for fp, entry in sorted(data.get("violations", {}).items()):
            rel = entry.get("path", "")
            snippet = entry.get("snippet", "")
            target = os.path.join(root, rel)
            if rel not in stripped_cache:
                try:
                    with open(target, encoding="utf-8") as f:
                        stripped_cache[rel] = {
                            ln.strip() for ln in f.read().splitlines()
                        }
                except OSError:
                    stripped_cache[rel] = None
            lines = stripped_cache[rel]
            if lines is None:
                problems.append(
                    f"{os.path.basename(bpath)}: ST005 entry {fp} "
                    f"({entry.get('rule')}) points at missing file "
                    f"{rel} — regenerate the baseline"
                )
            elif snippet and snippet not in lines:
                problems.append(
                    f"{os.path.basename(bpath)}: ST005 entry {fp} "
                    f"({entry.get('rule')}, {rel}) no longer matches "
                    "any source line — the site was fixed or retired; "
                    "regenerate the baseline to shrink it"
                )
    return problems


# ---------------------------------------------------------------------------
# one-call entry (CLI and the tier-1 test share it)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StateResult:
    violations: List[Violation]
    drift: List[str]
    dead_baseline: List[str]
    errors: List[str]
    scanner: StateScanner

    @property
    def failed(self) -> bool:
        return bool(
            self.violations or self.drift or self.dead_baseline
            or self.errors
        )


def run(
    paths: Sequence[str],
    inventory_path: Optional[str] = None,
    fix_inventory: bool = False,
    check_baselines: bool = True,
    baseline_paths: Optional[Sequence[str]] = None,
) -> StateResult:
    inventory_path = inventory_path or DEFAULT_INVENTORY
    checked_in = load_inventory(inventory_path)
    whitelist = dict((checked_in or {}).get("whitelist", {}))
    scanner = scan_state(paths)
    classify(scanner, whitelist)
    model = RepoModel.build(paths)
    errors = list(scanner.errors)
    if fix_inventory:
        write_inventory(inventory_path, scanner, whitelist)
        checked_in = load_inventory(inventory_path)
    violations, drift = check_inventory(scanner, checked_in)
    violations += check_st003(scanner, whitelist)
    violations += check_st004(model)
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    dead = (
        check_st005(baseline_paths=baseline_paths)
        if check_baselines
        else []
    )
    return StateResult(violations, drift, dead, errors, scanner)


def report(result: StateResult, out=None) -> None:
    import sys

    out = out or sys.stdout
    for v in result.violations:
        print(v.format(), file=out)
    for d in result.drift:
        print(d, file=out)
    for d in result.dead_baseline:
        print(d, file=out)
    for e in result.errors:
        print(f"ERROR {e}", file=out)
    n = result.scanner.state
    by_class: Dict[str, int] = {}
    for sd in n.values():
        by_class[sd.classification] = by_class.get(sd.classification, 0) + 1
    print(
        f"statecheck: {len(result.violations)} finding(s), "
        f"{len(result.drift)} inventory drift(s), "
        f"{len(result.dead_baseline)} dead baseline entr"
        f"{'y' if len(result.dead_baseline) == 1 else 'ies'}, "
        f"{len(result.errors)} errors over {len(n)} state entr"
        f"{'y' if len(n) == 1 else 'ies'} "
        f"({by_class.get('per_job', 0)} per_job, "
        f"{by_class.get('process_global', 0)} process_global, "
        f"{by_class.get('violation', 0)} violation)",
        file=out,
    )
