"""graftlint: the repo's elasticity invariants, checked by machine.

PR 1 and PR 2 each lost days to the same bug species — a ``loss_fn``
closing over a mesh made remesh impossible, a per-batch ``float()``
host-synced ``evaluate()``, a ``set()`` of slices crashed the shm
restore path, a SIGTERM handler had to be re-armed after SIG_IGN.
These are invariant classes, not one-off bugs, and with 40+ threaded
modules and ~50 raw ``os.environ`` call sites convention does not
scale. graftlint encodes each class as an AST rule (stdlib ``ast``
only, no new deps) and runs as a tier-1 test and a CI gate, the way
Orbax bakes checkpoint-layout invariants into its API instead of its
docs.

Usage::

    python -m dlrover_tpu.lint dlrover_tpu/            # check
    python -m dlrover_tpu.lint --fix-baseline dlrover_tpu/
    # graftlint: disable=JG002  -- per-line suppression (with a reason)

The rule catalog lives in :mod:`dlrover_tpu.lint.rules`; each rule's
docstring names the shipped bug it encodes. The runtime companion
:mod:`dlrover_tpu.lint.retrace_guard` catches the one invariant static
analysis cannot see — silent XLA recompiles of an already-compiled
step signature.

Sibling layers sharing this package: :mod:`~dlrover_tpu.lint.
shardcheck` (the lowered IR), :mod:`~dlrover_tpu.lint.racecheck` (the
lock structure, with :mod:`~dlrover_tpu.lint.lock_tracker` at
runtime), and :mod:`~dlrover_tpu.lint.wirecheck` (the wire & durable
protocol, with :mod:`~dlrover_tpu.lint.skew_shim` at runtime).
"""

from dlrover_tpu.lint.engine import (  # noqa: F401
    LintResult,
    Severity,
    SourceFile,
    Violation,
    lint_paths,
    load_baseline,
    run,
    write_baseline,
)
from dlrover_tpu.lint.rules import ALL_RULES, rule_catalog  # noqa: F401
