"""The shardcheck contract model: one fixed tiny program per mesh.

SC001 diffs the step program's collective census against a checked-in
contract, which only means something if every generation of the
contract lowers the *same* program. This module pins that program: a
tiny llama (vocab 256, dim 64, 2 layers) with an explicitly small CE
chunk (64 < vocab — the default 2048 clips to the full tiny vocab,
which would make the chunked path materialize seq×vocab tensors and
trip its own SC003 gate), a fixed sequence length and global batch,
lowered through the exact ``ElasticTrainer`` machinery production uses
(``step_ir`` → ``lower_step`` avatars). Everything runs on CPU host
devices — contract generation and CI checking never touch a TPU.

Imports jax lazily: :mod:`dlrover_tpu.lint` must stay importable in
the dep-free graftlint environment, and the ``--hlo`` CLI needs to
force the CPU platform *before* jax initializes.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

from dlrover_tpu.lint import shardcheck

#: the pinned contract-program knobs — changing any of these re-keys
#: every contract (config_hash mismatch), which is exactly the signal
#: to regenerate with --fix-contracts
SEQ_LEN = 16
GLOBAL_BATCH = 8
MICRO_BATCH = 2
CE_CHUNK = 64
VOCAB = 256

#: global batch of the ``+overlap`` contract variants ONLY: the overlap
#: schedule pipelines the DCN leg across gradient-accumulation
#: microbatches, so its contract program must actually accumulate —
#: and the peeled scan must survive to the optimized HLO (the overlap
#: dimension reads loop structure). dp4 × micro 2 → accum 3 → a
#: trip-count-2 scan, which XLA keeps as a real while (a trip-count-1
#: loop is inlined away and the schedule evidence with it). Scoped to
#: overlap specs so every pre-existing contract keeps its config_hash.
OVERLAP_GLOBAL_BATCH = 24

#: pipeline-contract geometry (pp > 1 specs ONLY — non-pp contracts
#: keep the 2-layer config and their config_hash): 4 layers over
#: pp=2 x 2 virtual stages (one layer per chunk), 4 microbatches, so
#: the interleaved 1F1B model bubble is (p-1)/(m*v) = 1/8 — the
#: paper's (p-1)/(p*m) with v = p. The SC008 contract pins exactly
#: this geometry.
PP_LAYERS = 4
PP_MICROBATCHES = 4
PP_VIRTUAL_STAGES = 2
PP_SCHEDULE = "1f1b"


def ensure_cpu_devices(n: int) -> None:
    """Force the CPU platform with ≥ ``n`` virtual host devices. Must
    run before jax initializes its backend (mirrors tests/conftest.py,
    including the jax.config override that beats any sitecustomize
    meddling with JAX_PLATFORMS)."""
    # jax platform wiring, not DLROVER_TPU_* knobs: these two env vars
    # must be written before jax initializes, same as tests/conftest.py
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # graftlint: disable=JG003
    xla_flags = os.environ.get("XLA_FLAGS", "")  # graftlint: disable=JG003
    if "--xla_force_host_platform_device_count" not in xla_flags:
        # graftlint: disable=JG003
        os.environ["XLA_FLAGS"] = (
            xla_flags + f" --xla_force_host_platform_device_count={max(n, 8)}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    have = len(jax.devices())
    if have < n:
        raise RuntimeError(
            f"mesh needs {n} devices but jax initialized with {have} "
            "(jax imported before the device-count flag could be set? "
            "run the CLI in a fresh process)"
        )


def build_contract_trainer(
    axis_sizes: Dict[str, int], zero1: bool = False, n_slices: int = 1,
    overlap: bool = False,
):
    """(trainer, state, batch) for the pinned contract model on the
    mesh ``axis_sizes`` describes, placed on CPU host devices.
    ``zero1`` builds the weight-update-sharded variant of the step via
    the TrainConfig knob; ``n_slices > 1`` builds the mesh slice-major
    (virtual slices on CPU) and hands the trainer the slice count, so
    the hierarchical-collectives strategy and the per-link census see
    the multislice topology. Callers that must not let exported
    ``DLROVER_TPU_ZERO1`` / ``DLROVER_TPU_HIER_COLLECTIVES`` overrides
    leak in wrap the build in ``flags.*.scoped(None)``
    (``build_program`` does)."""
    import jax
    import numpy as np

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel import build_mesh, named_shardings
    from dlrover_tpu.parallel.mesh import MeshConfig
    from dlrover_tpu.train.trainer import ElasticTrainer, TrainConfig

    world = 1
    for s in axis_sizes.values():
        world *= s
    pp = axis_sizes.get("pp", 1)
    if pp > 1:
        # the pipeline variant of the pinned program: same tiny dims,
        # 4 layers so pp=2 x v=2 holds one layer per chunk, explicit
        # interleaved-1F1B schedule knobs — the SC008 geometry
        cfg = llama.LlamaConfig.tiny(
            vocab_size=VOCAB, ce_chunk_size=CE_CHUNK,
            n_layers=PP_LAYERS, pp_schedule=PP_SCHEDULE,
            pp_microbatches=PP_MICROBATCHES,
            pp_virtual_stages=PP_VIRTUAL_STAGES,
        )
    else:
        cfg = llama.LlamaConfig.tiny(
            vocab_size=VOCAB, ce_chunk_size=CE_CHUNK
        )
    mc = MeshConfig(
        dp=axis_sizes.get("dp", 1),
        pp=pp,
        fsdp=axis_sizes.get("fsdp", 1),
        ep=axis_sizes.get("ep", 1),
        sp=axis_sizes.get("sp", 1),
        tp=axis_sizes.get("tp", 1),
    ).resolve(world)
    mesh = build_mesh(
        mc, devices=jax.devices()[:world], n_slices=n_slices
    )
    specs = llama.param_specs(cfg, pp=mc.pp)
    # pp steps feed the schedule's own microbatching: one accum row
    # carrying the whole global batch (accum=1), so the loss call sees
    # GLOBAL_BATCH rows to split into PP_MICROBATCHES microbatches
    micro = (
        GLOBAL_BATCH // mc.data_parallel_size if pp > 1 else MICRO_BATCH
    )
    tc = TrainConfig(
        global_batch_size=(
            OVERLAP_GLOBAL_BATCH if overlap else GLOBAL_BATCH
        ),
        micro_batch_size=micro,
        warmup_steps=0,
        total_steps=100,
        zero1=zero1,
        overlap_collectives=overlap,
    )
    trainer = ElasticTrainer(
        None, specs, mesh, mc, tc,
        loss_factory=lambda m: (
            lambda p, t: llama.loss_fn(p, t, cfg, m)
        ),
        n_slices=n_slices,
    )
    trainer.shardcheck_hints = {
        "seq_len": SEQ_LEN, "vocab": cfg.vocab_size,
    }
    if pp > 1:
        # arms the SC008 pipeline-schedule contract dimension
        trainer.shardcheck_hints["pp_schedule"] = {
            "schedule": cfg.pp_schedule,
            "microbatches": cfg.pp_microbatches or mc.pp,
            "virtual_stages": cfg.pp_virtual_stages,
        }
    params = jax.device_put(
        llama.init_params(cfg, jax.random.key(0)),
        named_shardings(mesh, specs),
    )
    state = trainer.init_state(params)
    accum, per = trainer.step_batch_shape
    batch = np.zeros((accum, per, SEQ_LEN), np.int32)
    trainer.record_avatars(state, batch)
    return trainer, state, batch


def _pinned_flags():
    """The contract-program flag pins, as one ExitStack: the SPEC
    decides the variant; exported DLROVER_TPU_ZERO1 /
    DLROVER_TPU_HIER_COLLECTIVES / DLROVER_TPU_OVERLAP_* would
    otherwise override the knobs at init_state/lower time and build
    (or ``--fix-contracts``: RECORD) the wrong program. The CE path
    choice is part of the contracted program too, so the kernel
    dispatch flags pin to their defaults (fused falls back to chunked
    off-TPU — the recorded program is the PR 1 one)."""
    import contextlib

    from dlrover_tpu.common import flags

    stack = contextlib.ExitStack()
    for flag in (
        flags.ZERO1,
        flags.HIER_COLLECTIVES,
        flags.OVERLAP_COLLECTIVES,
        flags.OVERLAP_BUCKET_MB,
        flags.CHUNKED_CE,
        flags.FUSED_CE,
    ):
        stack.enter_context(flag.scoped(None))
    return stack


def build_program(
    spec: str, pinned: bool = True
) -> Tuple["shardcheck.StepProgram", object]:
    """Lower the contract model for ``spec`` (e.g. ``"dp2xfsdp2"``,
    the zero-1 variant ``"dp4+zero1"``, or a multislice hierarchical
    variant like ``"dp4+2slice"``) and return
    ``(StepProgram, trainer)``."""
    from dlrover_tpu.common.world import WorldDescriptor

    wd = WorldDescriptor.parse(spec)
    axis_sizes = wd.axis_sizes()
    world = 1
    for s in axis_sizes.values():
        world *= s
    ensure_cpu_devices(world)
    with _pinned_flags():
        trainer, _, _ = build_contract_trainer(
            axis_sizes, zero1=wd.zero1, n_slices=wd.n_slices,
            overlap=wd.overlap,
        )
        program = trainer.step_ir(pinned=pinned)
    program.label = "hlo:" + wd.spec
    return program, trainer


def build_memcheck(spec: str) -> Dict:
    """Lower the contract model for ``spec`` under the same flag pins
    as :func:`build_program` and return the trainer's memcheck payload
    (lint/memcheck.py): the per-device component breakdown, analytic
    peak and guarded measured bytes of the pinned program — the MC001
    contract substrate."""
    from dlrover_tpu.common.world import WorldDescriptor

    wd = WorldDescriptor.parse(spec)
    axis_sizes = wd.axis_sizes()
    world = 1
    for s in axis_sizes.values():
        world *= s
    ensure_cpu_devices(world)
    with _pinned_flags():
        trainer, _, _ = build_contract_trainer(
            axis_sizes, zero1=wd.zero1, n_slices=wd.n_slices,
            overlap=wd.overlap,
        )
        return trainer.memcheck_payload()
