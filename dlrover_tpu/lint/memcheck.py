"""memcheck: static per-device memory contracts (MC rules) — the fifth
invariant layer.

shardcheck proves the lowered step moves the right *bytes over links*;
memcheck proves it fits in the right *bytes of HBM*. The one resize
failure mode no earlier layer could catch before it happens is a
grow/shrink into an OOM world: the goodput planner scores candidates
from *measured* headroom, which only exists for worlds that have
already run. This module makes "this world fits" a static, checked-in
contract, the same shape SC001 gave collective bytes:

- **measured side**: ``compiled.memory_analysis()`` on the warm-compile
  avatar build (argument / output / temp / generated-code / alias
  bytes) — the per-device arena XLA actually plans, obtainable for any
  admissible world on CPU with no TPU attached;
- **analytic side**: a per-leaf model over the state/batch avatars'
  ``(shape, dtype, PartitionSpec)`` — each leaf's global bytes divided
  by the product of the mesh axes its spec shards over, bucketed into
  the five components ``params / moments / grads_accum / activations /
  temp``. The analytic side makes the measured number *explainable*
  (which component grew, and why), and scales to worlds that were
  never compiled at all — that scaling law is the planner's
  :class:`HeadroomOracle`.

Rules:

MC001  memory-contract: per-device peak bytes and the per-component
       breakdown diffed against a checked-in per-(mesh-spec,
       config-hash) contract (``lint/contracts/mem-<spec>.json``) with
       a byte tolerance; growth past tolerance names the component.
MC002  headroom-budget: predicted per-device peak vs. a per-device-
       class HBM budget (``v5e`` / ``v5p`` / ``cpu-host`` — the
       ROADMAP item 5 vocabulary) minus a headroom fraction. The same
       check, applied to a candidate ``WorldDescriptor`` through the
       oracle, is the planner's ``oom_veto``.

Everything here is arithmetic over plain shapes and dicts — no jax
import, no device use — so the module stays importable in the dep-free
lint environment and master-side in the planner process. Compiling a
program to GET the measured bytes (CLI ``--mem``, trainer hook) is the
caller's job, and every ``memory_analysis()`` read goes through the
guarded :func:`read_memory_analysis` (backends return ``None`` or
partial objects; older jaxlib CPU has no generated-code bytes — degrade
with one warning, never ``AttributeError``).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.world import WorldDescriptor
from dlrover_tpu.lint.engine import Severity, Violation

#: contracts live next to the SC001 ones (``--fix-contracts`` rewrites);
#: ``mem-`` prefix keeps the two families from colliding on a spec name
DEFAULT_CONTRACTS_DIR = os.path.join(os.path.dirname(__file__), "contracts")

#: MC001 default: per-component (and peak) byte growth beyond this
#: fraction of the contract fails lint
DEFAULT_BYTE_TOLERANCE = 0.10

#: MC001: growth below this many bytes never fails, whatever the
#: fraction — keeps KB-sized components (scalars, step counters) from
#: flapping the gate on dtype-width noise
MIN_GROWTH_BYTES = 64 << 10

#: MC002 default headroom: a candidate must fit in budget * (1 - this)
DEFAULT_HEADROOM_FRAC = 0.10

#: per-device-class HBM capacities, bytes (ROADMAP item 5 vocabulary).
#: cpu-host is deliberately small: it bounds the CPU-lowered CI builds
#: and gives the fleet harness an OOM-able class without a TPU.
DEVICE_HBM_BYTES: Dict[str, int] = {
    "v5e": 16 * 10**9,
    "v5p": 95 * 10**9,
    "cpu-host": 4 * 10**9,
}

#: the component vocabulary, in reporting order
COMPONENTS = ("params", "moments", "grads_accum", "activations", "temp")

#: numpy dtype name -> bytes (plain names: avatars hand us strings so
#: this module never imports numpy/jax)
_DTYPE_BYTES = {
    "bool": 1, "int8": 1, "uint8": 1, "float8_e4m3fn": 1,
    "float8_e5m2": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8, "complex64": 8,
    "complex128": 16,
}


class MemcheckError(RuntimeError):
    """Raised by the strict lower-time hook (``DLROVER_TPU_MEMCHECK=2``)
    when the compiled step program violates an MC rule."""

    def __init__(self, violations: Sequence[Violation]):
        self.violations = list(violations)
        super().__init__(
            f"{len(self.violations)} memcheck violation(s):\n"
            + "\n".join(v.format() for v in self.violations)
        )


def _violation(rule: str, label: str, message: str) -> Violation:
    return Violation(
        rule=rule,
        path=label or "memcheck",
        line=0,
        col=0,
        message=message,
        snippet="",
        severity=Severity.ERROR,
    )


# ---------------------------------------------------------------------------
# satellite 1: the ONE guarded reader over memory_analysis()
# ---------------------------------------------------------------------------

#: (attr on the backend object, key we publish) — `*_bytes` names so the
#: dict is self-describing in contracts / bench detail
_MEMORY_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)

#: warn-once registry: one line per (label, field) per process, then
#: silent degradation — a CI log should say a backend is partial once,
#: not once per lowering
_WARNED: set = set()


def _warn_once(key: str, message: str) -> None:
    if key in _WARNED:
        return
    _WARNED.add(key)
    logger.warning("memcheck: %s", message)


def read_memory_analysis(compiled, label: str = "step") -> Dict[str, int]:
    """The sanctioned reader over ``compiled.memory_analysis()``.

    Backends are allowed to return ``None``, raise, or hand back an
    object missing fields (older jaxlib CPU reports no generated-code
    bytes); every call site that used to spell the five ``getattr``\\ s
    itself goes through here instead. Missing pieces degrade to absent
    keys with one warning per (label, field); an empty dict means
    nothing was measurable. When at least the argument/temp side is
    present a ``peak_bytes`` estimate is added: arguments + outputs +
    temp + generated code − aliased bytes (donated inputs whose buffer
    the output reuses would otherwise be counted twice).
    """
    try:
        ma = compiled.memory_analysis()
    except Exception as exc:  # backend quirk, never a caller crash
        _warn_once(f"{label}:call",
                   f"memory_analysis() unavailable ({label}): {exc}")
        return {}
    if ma is None:
        _warn_once(f"{label}:none",
                   f"memory_analysis() returned None ({label})")
        return {}
    out: Dict[str, int] = {}
    for attr, key in _MEMORY_FIELDS:
        value = getattr(ma, attr, None)
        if value is None:
            _warn_once(f"{label}:{attr}",
                       f"memory_analysis().{attr} missing ({label}); "
                       "degrading")
            continue
        try:
            out[key] = int(value)
        except (TypeError, ValueError):
            _warn_once(f"{label}:{attr}",
                       f"memory_analysis().{attr} non-numeric ({label}); "
                       "degrading")
    if out:
        out["peak_bytes"] = measured_peak_bytes(out)
    return out


def measured_peak_bytes(measured: Dict[str, int]) -> int:
    """Per-device peak from the measured fields (missing fields count
    zero — the estimate degrades monotonically with the backend)."""
    return max(
        0,
        measured.get("argument_bytes", 0)
        + measured.get("output_bytes", 0)
        + measured.get("temp_bytes", 0)
        + measured.get("generated_code_bytes", 0)
        - measured.get("alias_bytes", 0),
    )


# ---------------------------------------------------------------------------
# the analytic per-leaf model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LeafAvatar:
    """One avatar leaf, reduced to what the memory model needs — plain
    strings and ints so trainers can flatten jax pytrees into these and
    this module never touches jax itself.

    ``sharded_axes`` is the flattened mesh-axis content of the leaf's
    ``PartitionSpec`` (``P(("fsdp", "tp"), None)`` -> ``("fsdp",
    "tp")``): the axes this leaf's bytes divide across.
    """

    path: str
    shape: Tuple[int, ...]
    dtype: str
    sharded_axes: Tuple[str, ...] = ()

    def global_bytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n * dtype_bytes(self.dtype)

    def per_device_bytes(self, axis_sizes: Dict[str, int]) -> float:
        div = 1
        for axis in self.sharded_axes:
            div *= max(1, int(axis_sizes.get(axis, 1)))
        return self.global_bytes() / div


def dtype_bytes(name: str) -> int:
    name = str(name)
    if name in _DTYPE_BYTES:
        return _DTYPE_BYTES[name]
    # "float8_e4m3fnuz"-style strangers: trailing digit run before any
    # suffix is the bit width
    digits = "".join(c for c in name if c.isdigit())
    if digits:
        return max(1, int(digits[:3]) // 8 or 1)
    return 4


def classify_leaf(path: str) -> str:
    """Component bucket for a state-avatar leaf, by pytree path. The
    train state is ``{"params": ..., "opt": ..., step, lr_scale}``;
    anything that is not a parameter is optimizer-side state."""
    p = path.lower()
    if "params" in p:
        return "params"
    return "moments"


def analytic_components(
    state_leaves: Sequence[LeafAvatar],
    batch_leaves: Sequence[LeafAvatar],
    axis_sizes: Dict[str, int],
    measured: Optional[Dict[str, int]] = None,
) -> Dict[str, int]:
    """The explainable per-device breakdown, bytes per component.

    - ``params`` / ``moments``: state leaves at their avatar sharding;
    - ``grads_accum``: the gradient (accumulator) buffer — shaped and
      sharded exactly like the params, so it *is* the params' per-device
      bytes again;
    - ``activations``: the batch leaves at their avatar sharding (the
      live input tensors; intermediate activations land in temp);
    - ``temp``: the measured temp arena plus generated code, with the
      modeled grad accumulator (which XLA plans inside that arena)
      taken back out, clamped at zero — the honest "scratch the model
      cannot explain" remainder. Zero when nothing was measured.

    With all five summed the analytic peak tracks the measured one up
    to the donation residue (outputs − aliased bytes): arguments are
    params + moments + activations, and grads + temp reassemble the
    measured arena — that near-identity is the parity bench asserts.
    """
    params = 0.0
    moments = 0.0
    for leaf in state_leaves:
        if classify_leaf(leaf.path) == "params":
            params += leaf.per_device_bytes(axis_sizes)
        else:
            moments += leaf.per_device_bytes(axis_sizes)
    grads = params
    acts = sum(l.per_device_bytes(axis_sizes) for l in batch_leaves)
    temp = 0.0
    if measured and (measured.get("temp_bytes")
                     or measured.get("generated_code_bytes")):
        temp = max(
            0.0,
            measured.get("temp_bytes", 0)
            + measured.get("generated_code_bytes", 0)
            - grads,
        )
    return {
        "params": int(params),
        "moments": int(moments),
        "grads_accum": int(grads),
        "activations": int(acts),
        "temp": int(temp),
    }


def analytic_peak_bytes(components: Dict[str, int]) -> int:
    return int(sum(components.get(c, 0) for c in COMPONENTS))


def explain_delta_frac(
    components: Dict[str, int], measured: Dict[str, int]
) -> Optional[float]:
    """How far the analytic state+batch model sits from the measured
    argument bytes — the cross-check that makes the quoted number
    explainable. ``None`` when the backend measured nothing."""
    arg = measured.get("argument_bytes")
    if not arg:
        return None
    modeled = (
        components.get("params", 0)
        + components.get("moments", 0)
        + components.get("activations", 0)
    )
    return abs(modeled - arg) / arg


# ---------------------------------------------------------------------------
# MC001: the contract diff
# ---------------------------------------------------------------------------


def mem_contract_path(contracts_dir: str, mesh_spec: str) -> str:
    return os.path.join(contracts_dir, f"mem-{mesh_spec}.json")


def load_mem_contract(
    contracts_dir: str, mesh_spec: str
) -> Optional[Dict]:
    try:
        with open(mem_contract_path(contracts_dir, mesh_spec),
                  encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return None
    if not isinstance(data, dict) or "components" not in data:
        raise ValueError(
            f"{mem_contract_path(contracts_dir, mesh_spec)}: not a "
            "memcheck contract file"
        )
    return data


def write_mem_contract(
    contracts_dir: str,
    mesh_spec: str,
    components: Dict[str, int],
    peak_bytes: int,
    measured: Optional[Dict[str, int]] = None,
    extra: Optional[Dict] = None,
) -> Dict:
    os.makedirs(contracts_dir, exist_ok=True)
    data = {
        "comment": (
            "memcheck MC001 contract: the static per-device memory "
            "model of the lowered step program for this mesh. "
            "Regenerate with: python -m dlrover_tpu.lint --mem <spec> "
            "--fix-contracts"
        ),
        "version": 1,
        "mesh_spec": mesh_spec,
        "components": {c: int(components.get(c, 0)) for c in COMPONENTS},
        "peak_bytes": int(peak_bytes),
    }
    if measured:
        data["measured"] = {k: int(v) for k, v in sorted(measured.items())}
    if extra:
        data.update(extra)
    path = mem_contract_path(contracts_dir, mesh_spec)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return data


def check_components(
    components: Dict[str, int],
    peak_bytes: int,
    contract: Dict,
    byte_tolerance: float = DEFAULT_BYTE_TOLERANCE,
    label: str = "step",
) -> List[Violation]:
    """MC001: diff the built breakdown against the contract. Growth past
    tolerance (and past :data:`MIN_GROWTH_BYTES`) fails, NAMING the
    component that grew — the whole point of carrying a breakdown
    instead of one peak number."""
    out: List[Violation] = []
    contracted = contract.get("components", {})
    for comp in COMPONENTS:
        old = int(contracted.get(comp, 0))
        new = int(components.get(comp, 0))
        grown = new - old
        if grown <= MIN_GROWTH_BYTES:
            continue
        if old > 0 and new <= old * (1.0 + byte_tolerance):
            continue
        pct = (grown / old * 100.0) if old else math.inf
        out.append(_violation(
            "MC001",
            label,
            f"memory component '{comp}' grew past tolerance: "
            f"{old} -> {new} bytes per device "
            f"(+{grown}, {'+inf' if old == 0 else f'{pct:+.1f}'}%"
            f", tolerance {byte_tolerance:.0%}). Review the change or "
            "regenerate with --fix-contracts.",
        ))
    old_peak = int(contract.get("peak_bytes", 0))
    if (old_peak > 0
            and peak_bytes - old_peak > MIN_GROWTH_BYTES
            and peak_bytes > old_peak * (1.0 + byte_tolerance)):
        worst = max(
            COMPONENTS,
            key=lambda c: components.get(c, 0) - contracted.get(c, 0),
        )
        out.append(_violation(
            "MC001",
            label,
            f"per-device peak grew past tolerance: {old_peak} -> "
            f"{peak_bytes} bytes (largest component delta: '{worst}').",
        ))
    return out


def component_improvements(
    components: Dict[str, int],
    peak_bytes: int,
    contract: Dict,
    byte_tolerance: float = DEFAULT_BYTE_TOLERANCE,
) -> List[str]:
    """Shrinks worth re-banking (the mirror of MC001: an improvement
    left uncommitted is tolerance headroom a future regression can
    silently spend)."""
    notes: List[str] = []
    contracted = contract.get("components", {})
    for comp in COMPONENTS:
        old = int(contracted.get(comp, 0))
        new = int(components.get(comp, 0))
        if old - new > MIN_GROWTH_BYTES and new < old * (1.0 - byte_tolerance):
            notes.append(
                f"component '{comp}' shrank {old} -> {new} bytes; "
                "re-bank with --fix-contracts"
            )
    old_peak = int(contract.get("peak_bytes", 0))
    if (old_peak - peak_bytes > MIN_GROWTH_BYTES
            and peak_bytes < old_peak * (1.0 - byte_tolerance)):
        notes.append(
            f"peak shrank {old_peak} -> {peak_bytes} bytes; re-bank "
            "with --fix-contracts"
        )
    return notes


# ---------------------------------------------------------------------------
# MC002: the headroom budget + the planner's oracle
# ---------------------------------------------------------------------------


def budget_bytes(
    device_class: str = "", budget_gb: float = 0.0
) -> float:
    """Resolve the per-device HBM budget: an explicit GB override wins,
    else the device-class table, else 0 (= budget checking off)."""
    if budget_gb and budget_gb > 0:
        return float(budget_gb) * 1e9
    return float(DEVICE_HBM_BYTES.get(device_class, 0))


def check_budget(
    peak_bytes: float,
    device_class: str = "",
    budget_gb: float = 0.0,
    headroom_frac: float = DEFAULT_HEADROOM_FRAC,
    label: str = "step",
) -> List[Violation]:
    """MC002: predicted per-device peak vs. the device-class budget
    minus headroom. No budget configured -> nothing to check."""
    budget = budget_bytes(device_class, budget_gb)
    if budget <= 0:
        return []
    usable = budget * (1.0 - headroom_frac)
    if peak_bytes <= usable:
        return []
    return [_violation(
        "MC002",
        label,
        f"predicted per-device peak {int(peak_bytes)} bytes exceeds "
        f"the {device_class or 'configured'} budget "
        f"({int(budget)} bytes - {headroom_frac:.0%} headroom = "
        f"{int(usable)} usable).",
    )]


def component_divisor(
    component: str,
    wd: WorldDescriptor,
    assume_zero1: Optional[bool] = None,
) -> int:
    """How many ways ``component`` divides across the devices of a
    world — the scaling law that turns one compiled breakdown into a
    prediction for EVERY admissible world:

    - params and the grad accumulator shard over the model axes
      (fsdp, tp);
    - optimizer moments additionally shard over dp under ZeRO-1 — the
      term that makes a *shrink* pack more state per device;
    - activations shard over the sequence/model axes (sp, tp); the
      per-device microbatch is held fixed across dp changes by the
      grad-accumulation invariant, so dp does not appear;
    - temp is per-device scratch: divisor 1.

    ``assume_zero1`` overrides the descriptor's own flag: planner-level
    node candidates are bare dp worlds, but they will run the *current
    program family* — the caller knows whether that family is ZeRO-1.
    """
    axes = wd.axis_sizes()
    fsdp = max(1, axes.get("fsdp", 1))
    tp = max(1, axes.get("tp", 1))
    sp = max(1, axes.get("sp", 1))
    dp = max(1, axes.get("dp", 1))
    zero1 = wd.zero1 if assume_zero1 is None else bool(assume_zero1)
    if component in ("params", "grads_accum"):
        return fsdp * tp
    if component == "moments":
        return fsdp * tp * (dp if zero1 else 1)
    if component == "activations":
        return sp * tp
    return 1


@dataclasses.dataclass
class HeadroomOracle:
    """The static headroom oracle: per-component GLOBAL byte totals plus
    the scaling law of :func:`component_divisor`, so any candidate
    ``WorldDescriptor`` — never-visited worlds, layout flips, the lot —
    prices out in five divisions. jax-free by construction: it runs
    master-side inside the planner and device-side inside the
    speculation filter.

    ``totals[c] / component_divisor(c, wd)`` is the predicted per-device
    bytes of component ``c`` at world ``wd`` (components with divisor 1,
    i.e. temp, store per-device bytes directly).
    """

    totals: Dict[str, float]
    base: WorldDescriptor
    device_class: str = ""
    budget_gb: float = 0.0
    headroom_frac: float = DEFAULT_HEADROOM_FRAC
    #: model candidates as running the current program family's ZeRO-1
    #: setting even when the bare candidate descriptor doesn't carry it
    assume_zero1: Optional[bool] = None

    @classmethod
    def from_components(
        cls,
        components: Dict[str, float],
        base: WorldDescriptor,
        **kwargs,
    ) -> "HeadroomOracle":
        """Lift a per-device breakdown measured AT ``base`` back to
        global totals (multiply by the base world's divisors)."""
        assume = kwargs.get("assume_zero1")
        totals = {
            c: float(components.get(c, 0))
            * component_divisor(c, base, assume)
            for c in COMPONENTS
        }
        return cls(totals=totals, base=base, **kwargs)

    @classmethod
    def from_contract(cls, contract: Dict, **kwargs) -> "HeadroomOracle":
        base = WorldDescriptor.parse(contract["mesh_spec"])
        return cls.from_components(
            contract.get("components", {}), base, **kwargs
        )

    def predict(
        self, wd: WorldDescriptor, assume_zero1: Optional[bool] = None
    ) -> Dict[str, float]:
        assume = self.assume_zero1 if assume_zero1 is None else assume_zero1
        out = {
            c: self.totals.get(c, 0.0) / component_divisor(c, wd, assume)
            for c in COMPONENTS
        }
        out["peak_bytes"] = sum(out[c] for c in COMPONENTS)
        return out

    def budget_bytes(self) -> float:
        return budget_bytes(self.device_class, self.budget_gb)

    def fits(
        self, wd: WorldDescriptor, assume_zero1: Optional[bool] = None
    ) -> Dict:
        """Price a candidate. ``{"fits": bool, "peak_bytes": ...,
        "budget_bytes": ..., "usable_bytes": ...}`` — a zero budget
        means the oracle is unarmed and everything fits."""
        pred = self.predict(wd, assume_zero1)
        budget = self.budget_bytes()
        usable = budget * (1.0 - self.headroom_frac)
        return {
            "fits": budget <= 0 or pred["peak_bytes"] <= usable,
            "peak_bytes": int(pred["peak_bytes"]),
            "budget_bytes": int(budget),
            "usable_bytes": int(usable),
        }


# ---------------------------------------------------------------------------
# MC rule catalog (for --list-rules and the docs)
# ---------------------------------------------------------------------------

MC_RULES: List[Tuple[str, str, str]] = [
    ("MC001", "memory-contract",
     "Per-device peak bytes and the params/moments/grads_accum/"
     "activations/temp breakdown of the lowered step diffed against a "
     "checked-in per-(mesh, config-hash) contract; growth past the "
     "byte tolerance names the component that grew."),
    ("MC002", "headroom-budget",
     "Predicted per-device peak vs. the per-device-class HBM budget "
     "(v5e/v5p/cpu-host) minus headroom; the same check through the "
     "HeadroomOracle is the planner's oom_veto on candidate worlds."),
]
