"""gRPC transport for the control plane.

The reference exposes one gRPC service with two generic RPCs ``get`` and
``report`` carrying pickled payloads (``dlrover/proto/elastic_training.proto:18-31``,
``master/servicer.py:106-153``). We keep the two-generic-RPC shape — it makes
the protocol evolvable without proto regeneration — but payloads are the safe
JSON serde from :mod:`dlrover_tpu.common.serde`, and the methods are declared
as raw-bytes unary RPCs so no generated stubs are needed.

Fleet-scale hardening (ROADMAP item 5, docs/design/fleet_harness.md):

- the server runs every request through a :class:`RequestGate` — a
  bounded admission counter that *sheds* excess load with an explicit
  :class:`~dlrover_tpu.common.messages.OverloadedResponse` instead of
  letting the executor's unbounded queue hide saturation behind
  unbounded latency.  Reports shed first (they are periodic and
  resendable); gets shed at a higher watermark (a shed ``get_task``
  stalls training, a shed heartbeat costs nothing).
- the client retries through the unified policy in
  :mod:`dlrover_tpu.rpc.policy`: jittered exponential backoff with a
  budget, and an error taxonomy distinguishing unavailable vs deadline
  vs application errors.  ``Overloaded`` replies either retry after the
  server's hint (default) or raise :class:`OverloadedError` for
  periodic reporters that honor backpressure by widening their
  interval.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, List, Optional

import grpc

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.serde import (
    UnknownMessageError,
    deserialize,
    serialize,
)
from dlrover_tpu.rpc import policy as rpc_policy
from dlrover_tpu.rpc.policy import OverloadedError

SERVICE = "dlrover_tpu.Master"
GET = f"/{SERVICE}/get"
REPORT = f"/{SERVICE}/report"
#: the cheap node-id header: lets the admission gate record WHICH node
#: it shed before paying any deserialization (shed-aware liveness)
NODE_ID_HEADER = "dlrover-node-id"

_identity = lambda b: b  # noqa: E731


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, get_fn: Callable, report_fn: Callable):
        self._get_fn = get_fn
        self._report_fn = report_fn

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == GET:
            return grpc.unary_unary_rpc_method_handler(
                self._get_fn,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        if method == REPORT:
            return grpc.unary_unary_rpc_method_handler(
                self._report_fn,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        return None


class RequestGate:
    """Bounded admission for the servicer, shared by the real gRPC
    server and the fleet harness's in-process loopback.

    ``depth`` is the number of requests currently *inside* the
    servicer.  Admission above ``report_cap`` (or ``get_cap`` for
    gets) is refused — the caller returns an ``OverloadedResponse``
    built from :meth:`overload_reply`, a reply that costs microseconds,
    so saturation turns into explicit, bounded-latency sheds instead of
    an invisible executor queue.  Counters are cumulative and exported
    on the master ``/metrics``."""

    def __init__(self, report_cap: int = 16, get_cap: Optional[int] = None):
        self.report_cap = max(1, int(report_cap))
        # gets shed later: a shed get stalls the caller's actual work
        self.get_cap = (
            max(self.report_cap, int(get_cap))
            if get_cap is not None
            else self.report_cap * 2
        )
        # the liveness ceiling advertised on Overloaded replies: how far
        # a client may widen its report cadence before the heartbeat
        # evictor would declare it dead. The master that owns this gate
        # sets it from its heartbeat timeout (a safe fraction, so a
        # widened-but-honoring worker always lands >=2 reports per
        # timeout window). 0 = don't advertise.
        self.liveness_ceiling_s = 0.0
        # clock for the shed-recency ledger (injectable: the fleet
        # harness stamps sheds in virtual time)
        self.clock = time.time
        from dlrover_tpu.lint.lock_tracker import maybe_track

        self._lock = maybe_track(
            threading.Lock(), "rpc.transport.RequestGate._lock"
        )
        self._inflight = 0
        self._inflight_reports = 0
        self._peak = 0
        self._served: Dict[str, int] = {"get": 0, "report": 0}
        self._rejected: Dict[str, int] = {"get": 0, "report": 0}
        # shed-aware liveness: node_id -> last shed timestamp. The
        # node id arrives as a cheap header (gRPC metadata / loopback
        # arg) so it is known BEFORE deserialization — the whole point
        # of shedding is not paying the parse, and the heartbeat
        # evictor still must not evict workers the master itself
        # silenced. Bounded; pruned oldest-first past the cap.
        self._shed_nodes: Dict[int, float] = {}
        self._shed_cap = 8192

    def try_enter(self, kind: str, node_id: int = -1) -> bool:
        with self._lock:
            if kind == "get":
                # gets compete for the TOTAL budget (they shed last,
                # at the higher watermark)
                admitted = self._inflight < self.get_cap
            else:
                # reports compete only with OTHER reports: a get-heavy
                # episode (a 1k-node re-rendezvous polling the world)
                # must never starve heartbeats/failure reports into
                # 100% shed — that would walk healthy workers into
                # eviction while their failure reports are shed too
                admitted = self._inflight_reports < self.report_cap
            if not admitted:
                self._rejected[kind] = self._rejected.get(kind, 0) + 1
                if node_id >= 0:
                    self._shed_nodes[node_id] = self.clock()
                    if len(self._shed_nodes) > self._shed_cap:
                        oldest = min(
                            self._shed_nodes, key=self._shed_nodes.get
                        )
                        del self._shed_nodes[oldest]
                return False
            self._inflight += 1
            if kind != "get":
                self._inflight_reports += 1
            if self._inflight > self._peak:
                self._peak = self._inflight
            self._served[kind] = self._served.get(kind, 0) + 1
            return True

    def recently_shed(
        self, node_id: int, window_s: float, now: Optional[float] = None
    ) -> bool:
        """Did the gate shed a request from this node within the
        window? The heartbeat evictor treats such a node as alive: it
        was talking, the master refused to listen."""
        with self._lock:
            ts = self._shed_nodes.get(int(node_id))
        if ts is None:
            return False
        now = self.clock() if now is None else now
        return now - ts <= window_s

    def leave(self, kind: str = "report"):
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            if kind != "get":
                self._inflight_reports = max(0, self._inflight_reports - 1)

    @property
    def depth(self) -> int:
        with self._lock:
            return self._inflight

    @staticmethod
    def _retry_hint_s(depth: int) -> float:
        """Shed-reply backoff hint: grows with depth so a deeper
        overload pushes the fleet further out."""
        return min(10.0, max(0.5, 0.05 * depth))

    def overload_reply(self, kind: str = "report"):
        from dlrover_tpu.common import messages as msg

        with self._lock:
            depth = self._inflight
        return msg.OverloadedResponse(
            retry_after_s=self._retry_hint_s(depth),
            queue_depth=depth,
            reason=f"{kind} admission cap reached",
            max_interval_s=self.liveness_ceiling_s,
        )

    def stats(self) -> Dict:
        with self._lock:
            return {
                "inflight": self._inflight,
                "peak_inflight": self._peak,
                "report_cap": self.report_cap,
                "get_cap": self.get_cap,
                "served": dict(self._served),
                "rejected": dict(self._rejected),
            }

    def prometheus_lines(self) -> List[str]:
        s = self.stats()
        lines = [
            "# TYPE dlrover_tpu_master_rpc_inflight gauge",
            f"dlrover_tpu_master_rpc_inflight {s['inflight']}",
            f"dlrover_tpu_master_rpc_inflight_peak {s['peak_inflight']}",
            "# TYPE dlrover_tpu_master_rpc_total counter",
        ]
        for kind in sorted(s["served"]):
            lines.append(
                f'dlrover_tpu_master_rpc_total{{method="{kind}",'
                f'outcome="served"}} {s["served"][kind]}'
            )
        for kind in sorted(s["rejected"]):
            lines.append(
                f'dlrover_tpu_master_rpc_total{{method="{kind}",'
                f'outcome="rejected"}} {s["rejected"][kind]}'
            )
        return lines


class RpcServer:
    """Wraps a servicer object exposing ``get(msg)`` / ``report(msg)``."""

    def __init__(
        self,
        servicer,
        port: int = 0,
        max_workers: int = 32,
        gate: Optional[RequestGate] = None,
    ):
        from dlrover_tpu.common import flags

        self._servicer = servicer
        if gate is None:
            # admission caps BELOW the thread count: in-handler depth
            # can never exceed max_workers, so a cap at or above it
            # would never reject — the gate would silently vanish and
            # overload would hide in the executor queue again. Shed
            # replies also need free threads to stay fast.
            cap = int(flags.RPC_INFLIGHT_CAP.get()) or max(
                8, max_workers // 2
            )
            ceiling = max(1, max_workers - 8)
            if cap > ceiling:
                logger.warning(
                    "RPC admission cap %d >= server threads %d would "
                    "disable shedding; clamping to %d",
                    cap, max_workers, ceiling,
                )
                cap = ceiling
            gate = RequestGate(report_cap=cap, get_cap=min(
                max_workers - 2, cap * 2
            ))
        self.gate = gate
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ],
        )
        self._server.add_generic_rpc_handlers(
            [_Handler(self._handle_get, self._handle_report)]
        )
        self.port = self._server.add_insecure_port(f"0.0.0.0:{port}")

    @staticmethod
    def _peer_node_id(context) -> int:
        """The cheap node-id header (gRPC metadata): read BEFORE the
        payload deserializes so a shed still records WHO it silenced.
        -1 = absent (pre-header client) — shed-blind for that caller,
        exactly the old behavior."""
        try:
            for key, value in context.invocation_metadata() or ():
                if key == NODE_ID_HEADER:
                    return int(value)
        except (TypeError, ValueError, AttributeError):
            pass
        return -1

    def _handle_get(self, request: bytes, context) -> bytes:
        if not self.gate.try_enter("get", self._peer_node_id(context)):
            return serialize(self.gate.overload_reply("get"))
        try:
            msg = deserialize(request)
            resp = self._servicer.get(msg, context)
            return serialize(resp) if resp is not None else b""
        except UnknownMessageError as e:
            # a newer client's request on an older master: degrade to
            # the same typed SimpleResponse the servicer's unknown-
            # handler path returns (wirecheck WC003) — the client's
            # feature-detection fallbacks (e.g. lease_shards ->
            # get_task) key on exactly this reply, an INTERNAL abort
            # would read as a master outage and burn the retry budget
            return serialize(_skew_reply(e))
        except Exception:
            logger.exception("error handling get RPC")
            context.abort(grpc.StatusCode.INTERNAL, "get failed")
        finally:
            self.gate.leave("get")

    def _handle_report(self, request: bytes, context) -> bytes:
        if not self.gate.try_enter("report", self._peer_node_id(context)):
            return serialize(self.gate.overload_reply("report"))
        try:
            msg = deserialize(request)
            resp = self._servicer.report(msg, context)
            return serialize(resp) if resp is not None else b""
        except UnknownMessageError as e:
            return serialize(_skew_reply(e))
        except Exception:
            logger.exception("error handling report RPC")
            context.abort(grpc.StatusCode.INTERNAL, "report failed")
        finally:
            self.gate.leave("report")

    def start(self):
        self._server.start()

    def stop(self, grace: Optional[float] = None):
        self._server.stop(grace)


class RpcClient:
    """Client side of the two generic RPCs, with the unified retry
    policy (jittered exponential backoff, budget-bounded, error
    taxonomy — :mod:`dlrover_tpu.rpc.policy`)."""

    def __init__(
        self,
        addr: str,
        timeout: float = 30.0,
        policy: rpc_policy.BackoffPolicy = rpc_policy.DEFAULT_RPC,
        rng: Optional[random.Random] = None,
        node_id: int = -1,
    ):
        self.addr = addr
        self._timeout = timeout
        self._policy = policy
        self._rng = rng
        # the cheap node-id header rides every call's metadata so the
        # server's admission gate knows who it shed without touching
        # the payload (-1 = anonymous caller, e.g. master-to-master)
        self._metadata = (
            ((NODE_ID_HEADER, str(int(node_id))),) if node_id >= 0 else None
        )
        self._lock = threading.Lock()
        self._channel = None
        self._get = None
        self._report = None
        self._connect()

    def _connect(self):
        self._channel = grpc.insecure_channel(
            self.addr,
            options=[
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.enable_retries", 1),
                # a master relaunch is a DESIGNED-FOR event: gRPC's
                # default reconnect backoff grows toward 120s, so a
                # channel that watched the old master die can keep
                # replaying "connection refused" long after the new
                # master is serving — defeating the RELAUNCH_TOLERANT
                # retry budget at the application layer. Bound the
                # re-dial so a relaunched address is probed within
                # seconds (found by the SIGKILL-the-master e2e: the
                # agent's succeeded report burned all its retries
                # inside the channel's backoff window while the master
                # was up and reachable).
                ("grpc.initial_reconnect_backoff_ms", 500),
                ("grpc.min_reconnect_backoff_ms", 500),
                ("grpc.max_reconnect_backoff_ms", 3000),
            ],
        )
        self._get = self._channel.unary_unary(
            GET, request_serializer=_identity, response_deserializer=_identity
        )
        self._report = self._channel.unary_unary(
            REPORT, request_serializer=_identity, response_deserializer=_identity
        )

    def available(self, timeout: float = 5.0) -> bool:
        try:
            grpc.channel_ready_future(self._channel).result(timeout=timeout)
            return True
        except Exception:
            return False

    def _reconnect(self):
        """Tear down and re-dial the channel. A long-lived channel that
        watched its master die can wedge in a state no reconnect
        backoff escapes (observed in the SIGKILL-the-master e2e:
        subchannel fds kept failing with 'FD Shutdown' for 60+ s while
        a FRESH channel from a new process connected instantly). The
        relaunch-tolerance story therefore includes rebuilding the
        channel after consecutive unavailable failures — the client
        half of master-relaunch survival."""
        with self._lock:
            try:
                self._channel.close()
            except Exception:
                pass
            self._connect()

    def _stub(self, kind: str):
        with self._lock:
            return self._get if kind == "get" else self._report

    def _call(
        self,
        kind: str,
        msg: Any,
        retries: int,
        timeout: Optional[float],
        on_overload: str = "retry",
        policy: Optional[rpc_policy.BackoffPolicy] = None,
    ):
        """One logical call. ``retries`` bounds attempts (compat with
        the old signature); delays come from the policy's jittered,
        budget-bounded schedule. ``on_overload``: "retry" sleeps at
        least the server's hint and tries again; "raise" surfaces
        :class:`OverloadedError` immediately — periodic reporters
        honor it by widening their cadence, not by retrying. The stub
        re-resolves every attempt so a mid-call channel rebuild takes
        effect immediately."""
        timeout = timeout or self._timeout
        pol = dataclasses.replace(
            policy or self._policy, max_attempts=max(1, retries)
        )
        delays = pol.delays(self._rng)
        payload = serialize(msg)
        err: Optional[BaseException] = None
        unavailable_streak = 0
        while True:
            hint = 0.0
            try:
                try:
                    resp = deserialize(
                        self._stub(kind)(
                            payload, timeout=timeout, metadata=self._metadata
                        )
                    )
                except UnknownMessageError as e:
                    # version skew INSIDE the retry loop: map to the
                    # typed taxonomy error (named _t, actionable) and
                    # never retry — the peer is healthy, replaying the
                    # call replays the identical decode failure. This
                    # closes the documented OverloadedResponse hazard
                    # class: a raw ValueError used to escape here and
                    # surface at whatever site touched the response
                    raise rpc_policy.UnknownMessageTypeError(
                        e.type_name, peer=self.addr
                    ) from e
                if _is_overloaded(resp):
                    err = OverloadedError(
                        resp.retry_after_s,
                        resp.queue_depth,
                        getattr(resp, "max_interval_s", 0.0),
                    )
                    if on_overload == "raise":
                        raise err
                    hint = resp.retry_after_s
                else:
                    return resp
            except OverloadedError:
                raise
            except grpc.RpcError as e:
                if rpc_policy.classify(e) not in rpc_policy.RETRYABLE:
                    raise
                err = e
                if rpc_policy.classify(e) == "unavailable":
                    unavailable_streak += 1
                    if unavailable_streak >= 2:
                        logger.warning(
                            "master %s unavailable %d attempts in a "
                            "row; rebuilding the channel",
                            self.addr, unavailable_streak,
                        )
                        self._reconnect()
            delay = next(delays, None)
            if delay is None:
                raise err
            time.sleep(max(delay, hint))

    def get(
        self,
        msg: Any,
        retries: int = 3,
        timeout: Optional[float] = None,
        on_overload: str = "retry",
        policy: Optional[rpc_policy.BackoffPolicy] = None,
    ):
        return self._call(
            "get", msg, retries, timeout, on_overload, policy
        )

    def report(
        self,
        msg: Any,
        retries: int = 3,
        timeout: Optional[float] = None,
        on_overload: str = "retry",
        policy: Optional[rpc_policy.BackoffPolicy] = None,
    ):
        return self._call(
            "report", msg, retries, timeout, on_overload, policy
        )

    def close(self):
        if self._channel:
            self._channel.close()


def _is_overloaded(resp: Any) -> bool:
    from dlrover_tpu.common import messages as msg

    return isinstance(resp, msg.OverloadedResponse)


def _skew_reply(e: UnknownMessageError):
    """The server half of unknown-message degradation: a typed
    SimpleResponse naming the unknown ``_t``, identical in shape to the
    servicer's no-handler reply so clients have ONE skew signature to
    feature-detect on."""
    from dlrover_tpu.common import messages as msg

    logger.warning(
        "request carried unknown message type %r (version skew); "
        "answering SimpleResponse", e.type_name,
    )
    return msg.SimpleResponse(
        success=False,
        reason=f"unknown message type {e.type_name!r} (version skew)",
    )
