"""gRPC transport for the control plane.

The reference exposes one gRPC service with two generic RPCs ``get`` and
``report`` carrying pickled payloads (``dlrover/proto/elastic_training.proto:18-31``,
``master/servicer.py:106-153``). We keep the two-generic-RPC shape — it makes
the protocol evolvable without proto regeneration — but payloads are the safe
JSON serde from :mod:`dlrover_tpu.common.serde`, and the methods are declared
as raw-bytes unary RPCs so no generated stubs are needed.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from typing import Any, Callable, Optional

import grpc

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.serde import deserialize, serialize

SERVICE = "dlrover_tpu.Master"
GET = f"/{SERVICE}/get"
REPORT = f"/{SERVICE}/report"

_identity = lambda b: b  # noqa: E731


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, get_fn: Callable, report_fn: Callable):
        self._get_fn = get_fn
        self._report_fn = report_fn

    def service(self, handler_call_details):
        method = handler_call_details.method
        if method == GET:
            return grpc.unary_unary_rpc_method_handler(
                self._get_fn,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        if method == REPORT:
            return grpc.unary_unary_rpc_method_handler(
                self._report_fn,
                request_deserializer=_identity,
                response_serializer=_identity,
            )
        return None


class RpcServer:
    """Wraps a servicer object exposing ``get(msg)`` / ``report(msg)``."""

    def __init__(self, servicer, port: int = 0, max_workers: int = 32):
        self._servicer = servicer
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ],
        )
        self._server.add_generic_rpc_handlers(
            [_Handler(self._handle_get, self._handle_report)]
        )
        self.port = self._server.add_insecure_port(f"0.0.0.0:{port}")

    def _handle_get(self, request: bytes, context) -> bytes:
        try:
            msg = deserialize(request)
            resp = self._servicer.get(msg, context)
            return serialize(resp) if resp is not None else b""
        except Exception:
            logger.exception("error handling get RPC")
            context.abort(grpc.StatusCode.INTERNAL, "get failed")

    def _handle_report(self, request: bytes, context) -> bytes:
        try:
            msg = deserialize(request)
            resp = self._servicer.report(msg, context)
            return serialize(resp) if resp is not None else b""
        except Exception:
            logger.exception("error handling report RPC")
            context.abort(grpc.StatusCode.INTERNAL, "report failed")

    def start(self):
        self._server.start()

    def stop(self, grace: Optional[float] = None):
        self._server.stop(grace)


class RpcClient:
    """Client side of the two generic RPCs, with retry."""

    def __init__(self, addr: str, timeout: float = 30.0):
        self.addr = addr
        self._timeout = timeout
        self._lock = threading.Lock()
        self._channel = None
        self._get = None
        self._report = None
        self._connect()

    def _connect(self):
        self._channel = grpc.insecure_channel(
            self.addr,
            options=[
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.enable_retries", 1),
            ],
        )
        self._get = self._channel.unary_unary(
            GET, request_serializer=_identity, response_deserializer=_identity
        )
        self._report = self._channel.unary_unary(
            REPORT, request_serializer=_identity, response_deserializer=_identity
        )

    def available(self, timeout: float = 5.0) -> bool:
        try:
            grpc.channel_ready_future(self._channel).result(timeout=timeout)
            return True
        except Exception:
            return False

    def _call(self, stub, msg: Any, retries: int, timeout: Optional[float]):
        timeout = timeout or self._timeout
        err = None
        for i in range(retries):
            try:
                return deserialize(stub(serialize(msg), timeout=timeout))
            except grpc.RpcError as e:
                err = e
                if e.code() in (
                    grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                ):
                    time.sleep(min(2**i, 8))
                    continue
                raise
        raise err

    def get(self, msg: Any, retries: int = 3, timeout: Optional[float] = None):
        return self._call(self._get, msg, retries, timeout)

    def report(self, msg: Any, retries: int = 3, timeout: Optional[float] = None):
        return self._call(self._report, msg, retries, timeout)

    def close(self):
        if self._channel:
            self._channel.close()
