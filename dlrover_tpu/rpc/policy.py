"""Unified retry / timeout / backoff policy for the control plane.

Before this module every RPC call site invented its own loop:
``transport.RpcClient`` slept ``min(2**i, 8)`` with no jitter,
``MasterClient.barrier()`` busy-polled at a fixed 0.2 s, and the
rendezvous handler polled ``get_comm_world`` at a fixed 0.3 s.  At 1k
nodes fixed intervals synchronize: every waiter that entered a barrier
in the same rendezvous round polls in the same phase, so the master
absorbs the whole fleet as a square wave instead of a flat rate.  This
module is the one place the retry/backoff vocabulary is defined:

- :func:`classify` — error taxonomy.  ``unavailable`` (master down /
  connection refused / mid-relaunch) and ``deadline`` (server slow or
  link black-holed) are retryable transport conditions; ``overloaded``
  is the server's *explicit* shed signal (``OverloadedResponse``)
  which callers honor by widening their own interval rather than
  hammering the retry path; ``application`` errors propagate — the
  server saw the request and rejected it, retrying is wrong.
- :class:`BackoffPolicy` / :class:`Backoff` — jittered exponential
  backoff with a total-sleep budget, deterministic under a seeded rng
  (the fleet harness replays schedules).
- :class:`AdaptiveInterval` — AIMD report cadence: widen
  multiplicatively on ``Overloaded``, decay back toward the base on
  success.  Shared by the agent's folded status reporter and the
  simulated fleet workers so both honor backpressure identically.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Iterator, Optional

# -- error taxonomy ---------------------------------------------------------

UNAVAILABLE = "unavailable"
DEADLINE = "deadline"
OVERLOADED = "overloaded"
APPLICATION = "application"


class OverloadedError(Exception):
    """The server shed this request (explicit backpressure).

    Carries the server's ``retry_after_s`` hint; periodic reporters
    honor it by widening their interval instead of retrying."""

    def __init__(
        self,
        retry_after_s: float = 0.0,
        queue_depth: int = 0,
        max_interval_s: float = 0.0,
    ):
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.queue_depth = int(queue_depth)
        #: server-advertised liveness ceiling: widening past this gets
        #: the client evicted by the heartbeat monitor (0 = unknown)
        self.max_interval_s = max(0.0, float(max_interval_s))
        super().__init__(
            f"server overloaded (queue_depth={queue_depth}, "
            f"retry_after={self.retry_after_s:.2f}s)"
        )


class UnknownMessageTypeError(Exception):
    """The peer answered with a message type this binary cannot decode
    (``serde.UnknownMessageError`` mapped into the taxonomy by
    ``RpcClient._call``).

    This is version skew, not a transport blip: retrying replays the
    same decode failure, so it classifies ``application`` (never
    retried) and the message is actionable — it names the unknown
    ``_t`` and the rollout rule. Before this class existed the raw
    ``ValueError`` escaped the retry loop and surfaced at whatever call
    site happened to touch the response first (the documented
    OverloadedResponse hazard: a pre-gate client saw shed load as an
    AttributeError/ValueError instead of backpressure)."""

    def __init__(self, type_name: str, peer: str = ""):
        self.type_name = str(type_name)
        self.peer = str(peer)
        where = f" from {self.peer}" if self.peer else ""
        super().__init__(
            f"peer{where} sent unknown message type {self.type_name!r} — "
            "version skew between this binary and the peer; align "
            "versions, and upgrade masters LAST so old clients keep "
            "receiving only message types they know"
        )


class RetryBudgetExceeded(Exception):
    """Retries exhausted; ``last_error`` holds the final failure."""

    def __init__(self, msg: str, last_error: Optional[BaseException] = None):
        super().__init__(msg)
        self.last_error = last_error


def classify(exc: BaseException) -> str:
    """Map an exception to the taxonomy. gRPC status codes are read
    duck-typed (``exc.code()``) so non-gRPC transports — the fleet
    harness's in-process loopback — classify identically."""
    if isinstance(exc, OverloadedError):
        return OVERLOADED
    if isinstance(exc, UnknownMessageTypeError):
        # version skew: the peer is healthy and reachable, retrying
        # replays the identical decode failure
        return APPLICATION
    code = None
    code_fn = getattr(exc, "code", None)
    if callable(code_fn):
        try:
            code = code_fn()
        except Exception:
            code = None
    name = getattr(code, "name", "")
    if name in ("UNAVAILABLE", "CANCELLED", "UNKNOWN"):
        # UNKNOWN: a server that died mid-handler surfaces as UNKNOWN on
        # some grpc versions; treat like a transport blip
        return UNAVAILABLE
    if name == "DEADLINE_EXCEEDED":
        return DEADLINE
    if name == "RESOURCE_EXHAUSTED":
        return OVERLOADED
    if name:
        return APPLICATION
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return UNAVAILABLE
    return APPLICATION


RETRYABLE = frozenset({UNAVAILABLE, DEADLINE, OVERLOADED})


# -- jittered exponential backoff ------------------------------------------


@dataclasses.dataclass(frozen=True)
class BackoffPolicy:
    """Jittered exponential backoff shape.

    ``jitter`` is the +/- fraction applied to each delay (0.2 → each
    sleep lands uniformly in [0.8d, 1.2d]); a fleet of clients with the
    same policy therefore de-phases instead of thundering together.
    ``budget_s`` bounds the *total* sleep across one logical call —
    attempts stop when spending the next delay would exceed it."""

    base_s: float = 0.1
    multiplier: float = 2.0
    max_s: float = 8.0
    jitter: float = 0.2
    budget_s: float = 60.0
    max_attempts: int = 8

    def delays(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """The delay sequence (jittered, budget-bounded). Yields at most
        ``max_attempts - 1`` delays: one fewer sleep than attempts."""
        rnd = rng or random
        spent = 0.0
        delay = self.base_s
        for _ in range(max(0, self.max_attempts - 1)):
            d = min(delay, self.max_s)
            if self.jitter > 0.0:
                d *= 1.0 + self.jitter * (2.0 * rnd.random() - 1.0)
            d = max(0.0, d)
            if spent + d > self.budget_s:
                return
            spent += d
            yield d
            delay *= self.multiplier


#: client-side default for master RPCs — the same 1, 2, 4, 8… ladder
#: the pre-policy transport slept (now jittered): a default-retries
#: call must keep riding out the multi-second master blips it always
#: did, so the base must NOT be made snappier without auditing every
#: call site's relaunch tolerance
DEFAULT_RPC = BackoffPolicy(base_s=1.0)

#: rides out a master relaunch (~20s+ of cumulative sleep)
RELAUNCH_TOLERANT = BackoffPolicy(
    base_s=0.5, multiplier=2.0, max_s=10.0, budget_s=120.0, max_attempts=12
)

#: polling loops (barrier / rendezvous world / num_nodes_waiting):
#: start fast for snappy small jobs, widen so 1k waiters don't
#: synchronize — max_attempts unbounded-ish, the caller's deadline
#: terminates the loop
POLL = BackoffPolicy(
    base_s=0.1, multiplier=1.5, max_s=2.0, jitter=0.5,
    budget_s=float("inf"), max_attempts=1_000_000,
)


def poll_intervals(
    policy: BackoffPolicy = POLL, rng: Optional[random.Random] = None
) -> Iterator[float]:
    """Jittered, growing poll intervals for wait-until loops. Unlike
    :meth:`BackoffPolicy.delays` this never exhausts — after the growth
    phase it keeps yielding jittered ``max_s`` — because poll loops are
    bounded by the caller's deadline, not by attempt count."""
    rnd = rng or random
    delay = policy.base_s
    while True:
        d = min(delay, policy.max_s)
        if policy.jitter > 0.0:
            d *= 1.0 + policy.jitter * (2.0 * rnd.random() - 1.0)
        yield max(0.0, d)
        delay *= policy.multiplier


# -- AIMD report cadence ----------------------------------------------------


class AdaptiveInterval:
    """Additive-decrease / multiplicative-increase report interval.

    ``widen()`` on an ``Overloaded`` reply (or an unreachable master)
    multiplies the interval up to ``max_s``; ``ok()`` on a served
    report decays it back toward ``base_s`` by ``recovery`` per report.
    The asymmetry is deliberate: overload must shed load *now*, while
    recovery creeping back spreads the fleet's return over many report
    periods instead of snapping 1k workers back to the fast cadence in
    the same second. Thread-safe (reporter thread + monitor callbacks).
    """

    def __init__(
        self,
        base_s: float,
        max_s: Optional[float] = None,
        factor: float = 2.0,
        recovery: float = 0.8,
        jitter: float = 0.25,
    ):
        self.base_s = float(base_s)
        self.max_s = float(max_s) if max_s is not None else self.base_s * 16
        self.factor = float(factor)
        self.recovery = float(recovery)
        self.jitter = float(jitter)
        self._lock = threading.Lock()
        self._current = self.base_s
        self._widened = 0

    @property
    def current_s(self) -> float:
        with self._lock:
            return self._current

    def next_delay_s(self, rng: Optional[random.Random] = None) -> float:
        """The jittered wait until the next report. The jitter is NOT
        cosmetic: an overload widens many workers in the same instant,
        and un-jittered AIMD phase-locks them into cohorts that pound
        the admission gate in the same beat forever — the same unlucky
        members get shed every round until the heartbeat evictor
        declares live workers dead (found by the fleet chaos harness's
        overload scenario)."""
        rnd = rng or random
        with self._lock:
            d = self._current
        if self.jitter > 0.0:
            d *= 1.0 + self.jitter * (2.0 * rnd.random() - 1.0)
        return max(0.0, d)

    @property
    def widen_events(self) -> int:
        with self._lock:
            return self._widened

    def widen(self, hint_s: float = 0.0, ceiling_s: float = 0.0) -> float:
        """Overload signal: jump to max(current*factor, server hint),
        bounded by ``ceiling_s`` when the server advertised its
        liveness ceiling (``OverloadedResponse.max_interval_s``) —
        backing off must never back the client into an eviction."""
        cap = self.max_s
        if ceiling_s > 0.0:
            cap = min(cap, ceiling_s)
        with self._lock:
            target = min(cap, max(self._current * self.factor, hint_s))
            # monotonic under overload: a liveness ceiling BELOW the
            # current cadence must freeze widening, never SPEED THE
            # CLIENT UP (min() alone would shrink the interval under
            # load and amplify the overload)
            self._current = max(self._current, target)
            self._widened += 1
            return self._current

    def ok(self) -> float:
        """Served report: geometric decay back toward the base."""
        with self._lock:
            if self._current > self.base_s:
                self._current = max(
                    self.base_s, self._current * self.recovery
                )
            return self._current
