"""Agent-side rendezvous handler backed by the master.

Parity: reference ``MasterRendezvousHandler`` (``training.py:238-425``):
join -> poll comm world -> derive rank. TPU-natively the completed world
yields the ``jax.distributed`` bootstrap triple (coordinator_address,
num_processes, process_id) instead of a torch Store.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.rpc import policy as rpc_policy


class RendezvousTimeoutError(Exception):
    pass


class RendezvousOutSyncError(Exception):
    """A different rendezvous (e.g. network check) superseded this one."""


@dataclass
class CommWorld:
    """The agent's view of a completed rendezvous."""

    rdzv_round: int = 0
    group: int = 0
    node_rank: int = -1
    world_size: int = 0  # number of nodes (hosts)
    num_processes: int = 0  # total JAX processes = sum of local worlds
    process_id_base: int = 0  # first process id owned by this node
    coordinator_addr: str = ""
    members: Dict[int, Tuple[int, int, str, int]] = field(default_factory=dict)
    # members: node_rank -> (node_id, local_world_size, ip, port)
    slice_names: Dict[int, str] = field(default_factory=dict)
    # slice_names: node_rank -> TPU slice the node belongs to ("" if N/A)

    @property
    def n_slices(self) -> int:
        """Distinct TPU slices in the seated world (>=1). Drives the DCN
        axis of the multislice mesh — slice-count elasticity means this
        changes across re-rendezvous."""
        names = {s for s in self.slice_names.values() if s}
        return max(len(names), 1)


class MasterRendezvousHandler:
    def __init__(
        self,
        client: MasterClient,
        rdzv_name: str = RendezvousName.TRAINING,
        local_world_size: int = 1,
        node_ip: str = "",
        node_port: int = 0,
        slice_name: str = "",
        coords: Tuple = (),
        join_timeout: float = 600.0,
        poll_interval: Optional[float] = None,
    ):
        self._client = client
        self.rdzv_name = rdzv_name
        self.local_world_size = local_world_size
        self.node_ip = node_ip
        self.node_port = node_port
        self.slice_name = slice_name
        self.coords = coords
        self.join_timeout = join_timeout
        # None -> the shared jittered growing schedule (rpc/policy.py):
        # a fleet of waiters polling the incomplete world de-phases
        # instead of hitting the master in lockstep every 0.3s
        self.poll_interval = poll_interval

    def next_rendezvous(self, node_rank_hint: int = -1) -> CommWorld:
        """Join and block until a *new* round seats this node.

        The round guard (only accept rdzv_round > the round at join time)
        prevents a rejoining node — or its still-seated peers — from acting
        on the stale previous world whose coordinator is already dead.
        """
        from dlrover_tpu.observability import trace

        rank_hint = node_rank_hint if node_rank_hint >= 0 else self._client.node_id
        t_mono = time.monotonic()
        start_round = self._client.join_rendezvous(
            node_rank=rank_hint,
            local_world_size=self.local_world_size,
            rdzv_name=self.rdzv_name,
            node_ip=self.node_ip,
            node_port=self.node_port,
            slice_name=self.slice_name,
            coords=self.coords,
        )
        deadline = time.time() + self.join_timeout
        delays = rpc_policy.poll_intervals()
        while time.time() < deadline:
            resp = self._client.get_comm_world(self.rdzv_name)
            if (
                resp.completed
                and resp.world
                and resp.rdzv_round > start_round
                and any(
                    info[0] == self._client.node_id
                    for info in resp.world.values()
                )
            ):
                world = self._build_comm_world(resp)
                # trace spine: join -> seated, the rendezvous half of
                # any downtime bracket (observability/trace.py)
                trace.record(
                    "rendezvous", f"rendezvous.{self.rdzv_name}",
                    t_mono, time.monotonic() - t_mono,
                    round=world.rdzv_round, world_size=world.world_size,
                    node_rank=world.node_rank,
                )
                return world
            time.sleep(
                self.poll_interval
                if self.poll_interval is not None
                else next(delays)
            )
        raise RendezvousTimeoutError(
            f"rendezvous {self.rdzv_name} (joined round {start_round}) "
            f"not completed within {self.join_timeout}s"
        )

    def _build_comm_world(self, resp) -> CommWorld:
        members: Dict[int, Tuple[int, int, str, int]] = {}
        for rank_str, info in resp.world.items():
            node_id, local_ws, ip, port = info[:4]
            members[int(rank_str)] = (node_id, local_ws, ip, port)
        slice_names = {
            int(rank): name or ""
            for rank, name in (getattr(resp, "slice_names", None)
                               or {}).items()
        }
        my_rank = -1
        for rank in sorted(members):
            if members[rank][0] == self._client.node_id:
                my_rank = rank
                break
        num_processes = sum(m[1] for m in members.values())
        process_id_base = sum(
            members[r][1] for r in sorted(members) if r < my_rank
        )
        world = CommWorld(
            rdzv_round=resp.rdzv_round,
            group=resp.group,
            node_rank=my_rank,
            world_size=len(members),
            num_processes=num_processes,
            process_id_base=process_id_base,
            coordinator_addr=resp.coordinator_addr,
            members=members,
            slice_names=slice_names,
        )
        logger.info(
            "node %s: rendezvous %s round %s -> rank %s/%s, coordinator %s",
            self._client.node_id,
            self.rdzv_name,
            world.rdzv_round,
            world.node_rank,
            world.world_size,
            world.coordinator_addr,
        )
        return world

    def num_nodes_waiting(self) -> int:
        return self._client.num_nodes_waiting(self.rdzv_name)
