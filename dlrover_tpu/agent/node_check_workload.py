"""The node-health benchmark workload run in a check subprocess.

Parity: reference ``trainer/torch/node_check/`` (matmul x N + allreduce over
NCCL, ``utils.py:61-248``). TPU-natively: a jitted bf16 einsum chain on every
local device (exercises the MXU) and, when the check group spans processes,
a ``psum`` over ICI/gloo (exercises the interconnect). Elapsed seconds are
written to a file the agent reads.

Fault injection for tests (parity: ``mock_error()`` / MOCK_ERR_RANK env):
set ``DLROVER_TPU_MOCK_ERR_NODE`` to this node's id to force a failure, or
``DLROVER_TPU_MOCK_SLOW_NODE`` to add sleep (straggler simulation).
"""

from __future__ import annotations

import sys
import time

from dlrover_tpu.common import flags


def main() -> int:
    node_id = int(flags.NODE_ID.get())
    out_file = flags.CHECK_OUT.get()
    matmul_size = int(flags.CHECK_MATMUL_SIZE.get())
    matmul_iters = int(flags.CHECK_MATMUL_ITERS.get())
    psum_bytes = int(flags.CHECK_PSUM_BYTES.get())

    if flags.MOCK_ERR_NODE.get() == str(node_id):
        print(f"node {node_id}: injected check failure", flush=True)
        return 1

    from dlrover_tpu.train import bootstrap

    ctx = bootstrap.init(connect_master=False)

    import jax
    import jax.numpy as jnp

    start = time.time()

    # 1) per-device matmul benchmark (MXU on TPU)
    @jax.jit
    def chain(x):
        for _ in range(4):
            x = jnp.einsum("ij,jk->ik", x, x) / matmul_size
        return x

    results = []
    for d in jax.local_devices():
        x = jax.device_put(
            jnp.ones((matmul_size, matmul_size), dtype=jnp.bfloat16), d
        )
        for _ in range(matmul_iters // 4):
            x = chain(x)
        results.append(x)
    for r in results:
        r.block_until_ready()

    # 2) cross-process collective benchmark when the group spans processes
    if ctx.num_processes > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        import numpy as np

        mesh = Mesh(np.array(jax.devices()), ("x",))
        n = psum_bytes // 4
        local = jnp.ones((n,), dtype=jnp.float32)
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("x")), local
        )
        total = jax.jit(
            lambda a: a.sum(), out_shardings=NamedSharding(mesh, P())
        )(arr)
        total.block_until_ready()

    elapsed = time.time() - start

    slow_node = flags.MOCK_SLOW_NODE.get()
    if slow_node == str(node_id):
        time.sleep(float(flags.MOCK_SLOW_SECS.get()))
        elapsed = time.time() - start

    if out_file:
        with open(out_file, "w") as f:
            f.write(f"{elapsed}")
    print(f"node {node_id}: check done in {elapsed:.3f}s", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
