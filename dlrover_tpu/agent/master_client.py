"""Agent/worker-side client of the master (singleton, typed wrappers).

Parity: reference ``elastic_agent/master_client.py:61-499`` — every RPC the
agent or a worker issues goes through here.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import flags
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import NodeEnv, NodeType, RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.rpc import policy as rpc_policy
from dlrover_tpu.rpc.transport import RpcClient


class MasterClient:
    _instance: Optional["MasterClient"] = None
    _instance_lock = threading.Lock()

    def __init__(
        self,
        master_addr: str,
        node_id: int,
        node_type: str = NodeType.WORKER,
        client=None,
    ):
        # client injection: anything exposing get/report/available/close
        # over the serde wire — the fleet harness plugs its in-process
        # loopback here so 1k simulated workers exercise the SAME typed
        # wrappers production agents use. The node id rides every call
        # as a cheap header so the master's admission gate knows who it
        # shed (shed-aware liveness)
        self._client = client or RpcClient(master_addr, node_id=node_id)
        self.master_addr = master_addr
        self.node_id = node_id
        self.node_type = node_type

    # -- singleton ----------------------------------------------------------

    @classmethod
    def singleton_instance(cls) -> "MasterClient":
        with cls._instance_lock:
            if cls._instance is None:
                addr = flags.MASTER_ADDR.get()
                node_id = int(flags.NODE_ID.get())
                if not addr:
                    raise RuntimeError(
                        f"{NodeEnv.MASTER_ADDR} not set; no master to talk to"
                    )
                cls._instance = MasterClient(addr, node_id)
            return cls._instance

    @classmethod
    def reset_singleton(cls, instance: Optional["MasterClient"] = None):
        with cls._instance_lock:
            cls._instance = instance

    def available(self, timeout: float = 5.0) -> bool:
        return self._client.available(timeout)

    # -- rendezvous ---------------------------------------------------------

    def join_rendezvous(
        self,
        node_rank: int,
        local_world_size: int = 1,
        rdzv_name: str = RendezvousName.TRAINING,
        node_ip: str = "",
        node_port: int = 0,
        slice_name: str = "",
        coords: Tuple = (),
    ) -> int:
        resp = self._client.get(
            msg.JoinRendezvousRequest(
                node_id=self.node_id,
                node_rank=node_rank,
                local_world_size=local_world_size,
                rdzv_name=rdzv_name,
                node_ip=node_ip,
                node_port=node_port,
                slice_name=slice_name,
                coords=coords,
            )
        )
        return resp.round

    def get_comm_world(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> msg.CommWorldResponse:
        return self._client.get(
            msg.CommWorldRequest(node_id=self.node_id, rdzv_name=rdzv_name)
        )

    def num_nodes_waiting(self, rdzv_name: str = RendezvousName.TRAINING) -> int:
        resp = self._client.get(msg.NumNodesWaitingRequest(rdzv_name=rdzv_name))
        return resp.waiting_num

    def rendezvous_status(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> Tuple[int, int, Dict]:
        """(waiting_num, latest_round, speculation_hint). A worker
        whose seated round is older than ``latest_round`` is hung in a
        dead collective (the hang watchdog re-formed the world without
        it) and must re-join even though nobody is waiting. The hint is
        the goodput planner's intended next world ({} = no intent /
        pre-planner master — the ``getattr`` default keeps version
        skew harmless); it rides the SAME response so a caller that
        already polls membership pays zero extra RPCs for it."""
        resp = self._client.get(msg.NumNodesWaitingRequest(rdzv_name=rdzv_name))
        return (
            resp.waiting_num,
            getattr(resp, "latest_round", 0),
            dict(getattr(resp, "speculation_hint", None) or {}),
        )

    def speculation_hint(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> Dict:
        """Hint-only poll for processes that do NOT otherwise poll
        membership (the training worker's throttled
        ``WorkerContext.poll_speculation_hint``); anything already
        calling :meth:`rendezvous_status` should read the hint from
        that response instead of paying a second RPC."""
        return self.rendezvous_status(rdzv_name)[2]

    def network_ready(self) -> Tuple[bool, str]:
        resp = self._client.get(msg.NetworkReadyRequest())
        return resp.success, resp.reason

    def get_fault_nodes(self) -> List[int]:
        return self._client.get(msg.FaultNodesRequest()).nodes

    def get_stragglers(self) -> List[int]:
        return self._client.get(msg.StragglersRequest()).nodes

    def report_network_check_result(self, normal: bool, elapsed: float):
        return self._client.report(
            msg.NetworkCheckResult(
                node_id=self.node_id, normal=normal, elapsed_time=elapsed
            )
        )

    # -- node lifecycle -----------------------------------------------------

    def report_node_address(
        self, addr: str, port: int = 0, slice_name: str = "", coords: Tuple = ()
    ):
        return self._client.report(
            msg.NodeAddressReport(
                node_type=self.node_type,
                node_id=self.node_id,
                addr=addr,
                port=port,
                slice_name=slice_name,
                coords=coords,
            )
        )

    def report_heartbeat(
        self, timestamp: float = 0.0
    ) -> List[msg.DiagnosisAction]:
        """Legacy heartbeat-only RPC. The agent now sends the folded
        :meth:`report_worker_status` instead (heartbeat + digest +
        resource in one message, backpressure-honoring —
        agent/reporter.py); this wrapper stays for version skew and
        tests, not for new callers. ``timestamp`` defaults to now
        (injectable: the fleet harness's version_skew scenarios drive
        N-1 workers through this path on the virtual clock)."""
        resp = self._client.report(
            msg.HeartbeatReport(
                node_type=self.node_type,
                node_id=self.node_id,
                timestamp=timestamp or time.time(),
            )
        )
        return resp.actions if resp else []

    def report_failure(
        self,
        error_data: str,
        restart_count: int = 0,
        level: str = "error",
        exit_code: int = 1,
        timestamp: float = 0.0,
    ):
        return self._client.report(
            msg.NodeFailureReport(
                node_type=self.node_type,
                node_id=self.node_id,
                restart_count=restart_count,
                error_data=error_data,
                level=level,
                exit_code=exit_code,
                # stamp at send so a retried report (master relaunch
                # gap) still opens the downtime bracket at the true
                # failure time — RELAUNCH_TOLERANT backoff gives the
                # retries ~35s of cumulative sleep to span the gap
                timestamp=timestamp or time.time(),
            ),
            retries=8,
            policy=rpc_policy.RELAUNCH_TOLERANT,
        )

    def report_succeeded(self):
        # the agent's LAST message — it concludes the job master-side.
        # RELAUNCH_TOLERANT: finishing during a master relaunch gap
        # must conclude the job, not crash the agent after a clean run
        return self._client.report(
            msg.SucceededReport(node_type=self.node_type, node_id=self.node_id),
            retries=8,
            policy=rpc_policy.RELAUNCH_TOLERANT,
        )

    def report_used_resource(
        self, cpu_percent: float, memory_mb: float, tpu_duty_cycle: float = 0.0
    ):
        return self._client.report(
            msg.ResourceUsageReport(
                node_type=self.node_type,
                node_id=self.node_id,
                cpu_percent=cpu_percent,
                memory_mb=memory_mb,
                tpu_duty_cycle=tpu_duty_cycle,
            )
        )

    def report_global_step(
        self,
        step: int,
        digest: Optional[Dict] = None,
        comm_links: Optional[Dict] = None,
        overlap_ratio: float = -1.0,
        timestamp: float = 0.0,
    ):
        return self._client.report(
            msg.GlobalStepReport(
                node_id=self.node_id,
                step=step,
                timestamp=timestamp or time.time(),
                digest=dict(digest) if digest else {},
                comm_links=dict(comm_links) if comm_links else {},
                overlap_ratio=float(overlap_ratio),
            )
        )

    def report_worker_status(
        self,
        step: int = -1,
        digest: Optional[Dict] = None,
        cpu_percent: Optional[float] = None,
        memory_mb: float = 0.0,
        tpu_duty_cycle: float = 0.0,
        tpu_hbm_used_mb: float = 0.0,
        timestamp: float = 0.0,
    ) -> msg.WorkerReportResponse:
        """The folded periodic report: heartbeat + step digest +
        resource usage in ONE RPC. ``on_overload="raise"`` — a shed
        periodic report is not retried; the caller honors the
        backpressure by widening its interval
        (:class:`~dlrover_tpu.rpc.policy.AdaptiveInterval`)."""
        return self._client.report(
            msg.WorkerReport(
                node_type=self.node_type,
                node_id=self.node_id,
                timestamp=timestamp or time.time(),
                step=step,
                digest=dict(digest) if digest else {},
                has_resource=cpu_percent is not None,
                cpu_percent=cpu_percent or 0.0,
                memory_mb=memory_mb,
                tpu_duty_cycle=tpu_duty_cycle,
                tpu_hbm_used_mb=tpu_hbm_used_mb,
            ),
            retries=1,
            on_overload="raise",
        )

    def report_node_check_status(self, status: str):
        return self._client.report(
            msg.NodeCheckStatusReport(node_id=self.node_id, status=status)
        )

    def get_running_nodes(self) -> List[msg.NodeMeta]:
        return self._client.get(msg.RunningNodesRequest()).nodes

    def get_training_status(self) -> str:
        return self._client.get(msg.TrainingStatusRequest()).status

    # -- data sharding ------------------------------------------------------

    def report_dataset_shard_params(self, params: msg.DatasetShardParams):
        return self._client.report(params)

    def report_model_info(self, **fields) -> None:
        self._client.report(
            msg.ModelInfoReport(node_id=self.node_id, **fields)
        )

    def get_task(self, dataset_name: str) -> msg.Task:
        # RELAUNCH_TOLERANT backoff (~45s of cumulative sleep over 9
        # attempts): the data path stalling through a master relaunch
        # gap is what lets workers keep training across an
        # operator-relaunched master
        return self._client.get(
            msg.TaskRequest(dataset_name=dataset_name, node_id=self.node_id),
            timeout=60,
            retries=9,
            policy=rpc_policy.RELAUNCH_TOLERANT,
        )

    def report_task_result(
        self,
        dataset_name: str,
        task_id: int,
        success: bool = True,
        lease_epoch: int = -1,
    ):
        return self._client.report(
            msg.TaskResult(
                dataset_name=dataset_name,
                task_id=task_id,
                node_id=self.node_id,
                success=success,
                lease_epoch=lease_epoch,
            ),
            retries=9,
            policy=rpc_policy.RELAUNCH_TOLERANT,
        )

    def lease_shards(
        self,
        dataset_name: str,
        count: int,
        done_ids: Optional[List[int]] = None,
        failed_ids: Optional[List[int]] = None,
        lease_epoch: int = -1,
    ) -> msg.ShardLeaseResponse:
        """The batched data plane: ack the finished shards of the
        previous batch and lease up to ``count`` fresh shards under one
        per-worker lease in a single RPC (renewed by the folded
        WorkerReport; expiry re-enqueues at-least-once, the fence dedups
        — docs/design/data_plane.md). RELAUNCH_TOLERANT like get_task:
        the data plane stalls through a master relaunch gap instead of
        failing the epoch."""
        return self._client.get(
            msg.ShardLeaseRequest(
                dataset_name=dataset_name,
                node_id=self.node_id,
                count=count,
                done_task_ids=[int(t) for t in done_ids or ()],
                failed_task_ids=[int(t) for t in failed_ids or ()],
                lease_epoch=lease_epoch,
            ),
            timeout=60,
            retries=9,
            policy=rpc_policy.RELAUNCH_TOLERANT,
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._client.get(msg.ShardCheckpointRequest(dataset_name=dataset_name))
        return resp.content

    def report_shard_checkpoint(self, dataset_name: str, content: str):
        return self._client.report(
            msg.ShardCheckpointReport(dataset_name=dataset_name, content=content)
        )

    def get_dataset_epoch(self, dataset_name: str) -> int:
        return self._client.get(
            msg.DatasetEpochRequest(dataset_name=dataset_name)
        ).epoch

    # -- kv / sync ----------------------------------------------------------

    def kv_store_set(self, key: str, value: bytes):
        return self._client.report(msg.KVStoreSet(key=key, value=value))

    def kv_store_get(self, key: str) -> bytes:
        return self._client.get(msg.KVStoreGet(key=key)).value

    def kv_store_multi_get(self, keys: List[str]) -> Dict[str, bytes]:
        return self._client.get(msg.KVStoreMultiGet(keys=keys)).kvs

    def kv_store_multi_set(self, kvs: Dict[str, bytes]):
        return self._client.report(msg.KVStoreMultiSet(kvs=kvs))

    def kv_store_add(self, key: str, amount: int = 1) -> int:
        return self._client.get(msg.KVStoreAdd(key=key, amount=amount)).num

    def join_sync(self, sync_name: str, node_rank: int) -> bool:
        resp = self._client.report(
            msg.SyncJoin(sync_name=sync_name, node_id=self.node_id, node_rank=node_rank)
        )
        return resp.success

    def sync_finished(self, sync_name: str) -> bool:
        return self._client.get(msg.SyncQuery(sync_name=sync_name)).success

    def barrier(
        self,
        sync_name: str,
        timeout: float = 300,
        interval: Optional[float] = None,
    ) -> bool:
        """Join a named barrier and wait for everyone (master decides).

        Polls on the shared jittered-backoff schedule
        (:func:`rpc.policy.poll_intervals`) instead of a fixed busy
        poll: 1k waiters entering a barrier in the same round would
        otherwise synchronize their polls into a square wave on the
        master. An explicit ``interval`` pins a fixed cadence (tests)."""
        self.join_sync(sync_name, self.node_id)
        deadline = time.time() + timeout
        delays = rpc_policy.poll_intervals()
        while time.time() < deadline:
            if self.sync_finished(sync_name):
                return True
            time.sleep(
                interval if interval is not None else next(delays)
            )
        return False

    # -- config / diagnosis -------------------------------------------------

    def get_paral_config(self) -> msg.ParallelConfig:
        return self._client.get(msg.ParallelConfigRequest(node_id=self.node_id))

    def get_elastic_run_config(self) -> Dict:
        return self._client.get(msg.ElasticRunConfigRequest()).configs

    def report_diagnosis_data(self, data_cls: str, content: str, node_rank: int = -1):
        return self._client.report(
            msg.DiagnosisReportData(
                data_cls=data_cls,
                data_content=content,
                node_id=self.node_id,
                node_type=self.node_type,
                node_rank=node_rank,
            )
        )

    def report_ckpt_step(self, step: int, blocking_s: float, persist_s: float = 0.0):
        return self._client.report(
            msg.CheckpointStepReport(
                node_id=self.node_id,
                step=step,
                blocking_s=blocking_s,
                persist_s=persist_s,
            )
        )

    def report_resize_breakdown(
        self,
        rendezvous_s: float = 0.0,
        compile_s: float = 0.0,
        state_transfer_s: float = 0.0,
        restore_tier: str = "",
    ):
        return self._client.report(
            msg.ResizeBreakdownReport(
                node_id=self.node_id,
                rendezvous_s=rendezvous_s,
                compile_s=compile_s,
                state_transfer_s=state_transfer_s,
                restore_tier=restore_tier,
            )
        )

    def close(self):
        self._client.close()


def build_master_client(
    master_addr: str = "", node_id: Optional[int] = None
) -> MasterClient:
    addr = master_addr or flags.MASTER_ADDR.get()
    nid = node_id if node_id is not None else int(flags.NODE_ID.get())
    client = MasterClient(addr, nid)
    MasterClient.reset_singleton(client)
    return client
