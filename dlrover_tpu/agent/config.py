"""Elastic launch configuration.

Parity: reference ``ElasticLaunchConfig`` (``elastic_agent/torch/training.py:147-236``)
minus torch-specific knobs, plus TPU ones (slice name, chips per host).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.constants import TpuTimerConsts


@dataclass
class ElasticLaunchConfig:
    min_nodes: int = 1
    max_nodes: int = 1
    nproc_per_node: int = 1  # JAX processes per host (1 is TPU-canonical)
    node_id: int = 0
    job_name: str = "dlrover-tpu-job"
    master_addr: str = ""

    rdzv_join_timeout: float = 600.0
    node_unit: int = 1

    max_restarts: int = 3
    monitor_interval: float = 2.0
    network_check: bool = False
    comm_perf_test: bool = False
    exclude_straggler: bool = False
    # persist the staged shm checkpoint before stopping workers at a
    # restart boundary. Default True (reference defaults False because
    # its save costs minutes; ours is the flash path's shm->storage copy)
    save_at_breakpoint: bool = True
    accelerator: str = "tpu"  # "tpu" | "cpu" (cpu = gloo test mode)
    training_port: int = 0  # coordinator port base; 0 = auto
    tpu_timer: bool = False  # interpose the native PJRT profiler
    tpu_timer_port: int = TpuTimerConsts.DEFAULT_PORT
    # per-collective comm attribution: workers serve the comm ledger on
    # comm_metrics_port + local_rank; the agent scrapes into diagnosis
    comm_metrics: bool = False
    comm_metrics_port: int = 29700
    ckpt_replica: bool = False  # cross-host backup of staged checkpoints
    # persistent XLA compile cache dir injected into workers
    # (DLROVER_TPU_COMPILE_CACHE_DIR); "" = workers default it under
    # their checkpoint dir (train/warm_compile.py)
    compile_cache_dir: str = ""

    # TPU topology hints (injected by the platform or discovered)
    slice_name: str = ""
    coords: tuple = ()

    entrypoint: str = ""
    entrypoint_args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)

    def auto_configure(self):
        """Fill in defaults from the environment (parity: auto_configure_params)."""
        if not self.slice_name:
            self.slice_name = os.environ.get("TPU_SLICE_NAME", "")
        if not self.coords:
            coords = os.environ.get("TPU_WORKER_COORDS", "")
            if coords:
                self.coords = tuple(int(c) for c in coords.replace(",", " ").split())
        return self
