"""Agent-side node health check orchestration.

Parity: reference ``NodeCheckElasticAgent`` + ``run_network_check``
(``training.py:1358-1527,1585-1644``): join the NETWORK_CHECK rendezvous
(master pairs nodes into groups), run the benchmark workload as a
subprocess, report elapsed/status, and query fault/straggler verdicts.
Two rounds localize the fault: the master swaps group membership between
rounds and intersects failures.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Tuple

from dlrover_tpu.agent.config import ElasticLaunchConfig
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler
from dlrover_tpu.common.constants import NodeEnv, RendezvousName
from dlrover_tpu.common.log import logger
from dlrover_tpu.utils.net import find_free_port, local_ip


def _run_check_round(
    config: ElasticLaunchConfig, client: MasterClient, timeout: float = 300.0
) -> Tuple[bool, float]:
    """One round: rendezvous into a check group, run the workload."""
    node_ip = local_ip()
    handler = MasterRendezvousHandler(
        client,
        RendezvousName.NETWORK_CHECK,
        local_world_size=config.nproc_per_node,
        node_ip=node_ip,
        node_port=find_free_port(),
        slice_name=config.slice_name,
        coords=config.coords,
        join_timeout=config.rdzv_join_timeout,
    )
    world = handler.next_rendezvous(node_rank_hint=config.node_id)

    # Each node runs exactly ONE check workload process, so the check's
    # process world is node-indexed: num_processes = nodes in the group,
    # process_id = our position in it. The group's first member hosts the
    # coordination service for the collective benchmark.
    group_members = sorted(world.members)
    my_index = group_members.index(world.node_rank)
    out_file = tempfile.mktemp(prefix="dlrover_tpu_check_")
    from dlrover_tpu.common import flags

    env = flags.child_env(
        {
            "DLROVER_TPU_NODE_ID": str(config.node_id),
            "DLROVER_TPU_CHECK_OUT": out_file,
            NodeEnv.COORDINATOR_ADDR: world.coordinator_addr,
            NodeEnv.NUM_PROCESSES: str(len(group_members)),
            NodeEnv.PROCESS_ID: str(my_index),
            NodeEnv.NODE_RANK: str(world.node_rank),
            NodeEnv.NODE_NUM: str(world.world_size),
            NodeEnv.MASTER_ADDR: "",
            "DLROVER_TPU_ACCELERATOR": config.accelerator,
        }
    )
    cmd = [sys.executable, "-m", "dlrover_tpu.agent.node_check_workload"]
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=timeout, capture_output=True, text=True
        )
        ok = proc.returncode == 0
        if not ok:
            logger.warning(
                "node check workload failed (rc=%s): %s",
                proc.returncode,
                (proc.stdout or "")[-500:] + (proc.stderr or "")[-500:],
            )
    except subprocess.TimeoutExpired:
        logger.warning("node check workload timed out after %ss", timeout)
        ok = False
    elapsed = timeout
    if ok and os.path.exists(out_file):
        try:
            elapsed = float(open(out_file).read().strip())
        except ValueError:
            ok = False
    if os.path.exists(out_file):
        os.unlink(out_file)
    client.report_network_check_result(ok, elapsed)
    return ok, elapsed


def _wait_group_results(client: MasterClient, timeout: float = 120.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        success, reason = client.network_ready()
        if success:
            return True
        if reason == "node_failure":
            return False
        time.sleep(1.0)
    return False


def run_network_check(
    config: ElasticLaunchConfig, client: MasterClient, rounds: int = 2
) -> bool:
    """Returns True if THIS node is healthy (regardless of others)."""
    # Straggler localization NEEDS both rounds even when every group
    # passes: a slow node drags its collective partners to the same
    # elapsed time, so any single round flags the whole group — only the
    # cross-round intersection under different pairings isolates the true
    # straggler (same reason the reference always runs its second
    # comm-perf round, training.py:1585-1644).
    need_all_rounds = config.exclude_straggler or config.comm_perf_test
    for rnd in range(rounds):
        ok, elapsed = _run_check_round(config, client)
        logger.info(
            "node %s: check round %s -> ok=%s elapsed=%.3fs",
            config.node_id,
            rnd,
            ok,
            elapsed,
        )
        group_ok = _wait_group_results(client)
        if group_ok and not need_all_rounds:
            # All groups healthy: no need for the fault-localization round.
            break
    fault_nodes = client.get_fault_nodes()
    if config.node_id in fault_nodes:
        client.report_node_check_status("failed")
        return False
    if config.exclude_straggler:
        stragglers = client.get_stragglers()
        if config.node_id in stragglers:
            logger.warning("node %s: excluded as straggler", config.node_id)
            client.report_node_check_status("straggler")
            return False
    client.report_node_check_status("passed")
    return True
