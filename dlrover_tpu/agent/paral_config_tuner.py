"""ParalConfigTuner: master-pushed runtime tunables -> a JSON file the
training processes watch.

Parity: reference ``elastic_agent/config/paral_config_tuner.py:30-101``
(exchanges ParallelConfig with the master every 30s and materializes it
as a file the ElasticDataLoader re-reads). The file write is atomic
(rename) so a reader never sees a torn config.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Optional

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

#: workers find the config file through this env var (set by the agent)
# derives from the typed registry (the env contract's single owner):
# elastic_agent WRITES this name into worker envs, read_paral_config
# reads it back through flags.PARAL_CONFIG_PATH — same flag object
PARAL_CONFIG_PATH_ENV = flags.PARAL_CONFIG_PATH.name


def default_config_path(job_name: str, node_id: int) -> str:
    return os.path.join(
        "/tmp", "dlrover_tpu", job_name, f"node-{node_id}", "paral_config.json"
    )


class ParalConfigTuner:
    def __init__(
        self,
        client,
        job_name: str,
        node_id: int,
        path: str = "",
        interval: float = 30.0,
    ):
        self._client = client
        self.path = path or default_config_path(job_name, node_id)
        self._interval = interval
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_written = ""
        os.makedirs(os.path.dirname(self.path), exist_ok=True)

    def start(self):
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paral-config-tuner", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop_evt.set()

    def poll_once(self) -> bool:
        """Fetch the master's current config; write the file on change."""
        try:
            config = self._client.get_paral_config()
        except Exception as e:
            logger.warning("paral config fetch failed: %s", e)
            return False
        if config is None:
            return False
        payload = json.dumps(dataclasses.asdict(config), sort_keys=True)
        if payload == self._last_written:
            return False
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, self.path)
        self._last_written = payload
        logger.info("paral config updated: %s", payload)
        return True

    def _loop(self):
        while not self._stop_evt.wait(self._interval):
            try:
                self.poll_once()
            except Exception:
                logger.exception("paral config tuner cycle failed")


def read_paral_config(path: str = "") -> dict:
    """Worker-side: read the tuner file (empty dict when absent/unset)."""
    path = path or flags.PARAL_CONFIG_PATH.get()
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("paral config read failed: %s", e)
        return {}
