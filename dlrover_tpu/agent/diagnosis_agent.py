"""Agent-side diagnosis: observe worker health, decide restart vs relaunch.

Parity: reference ``elastic_agent/diagnosis/diagnosis_agent.py:60-302``
(periodic observe loop + ``diagnose_training_failure``). The agent-side
decision matters because it is the one place that knows the restart budget
and sees the worker log before the master does.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.common.log import logger
from dlrover_tpu.diagnosis.operators import classify_log


class WorkerAction:
    RESTART_WORKER = "restart"  # respawn processes on this host
    RELAUNCH_WORKER = "relaunch"  # exit; platform replaces this host


@dataclass
class WorkerFailure:
    node_id: int
    restart_count: int
    max_restarts: int
    exit_code: int = 1
    log_tail: str = ""


class DiagnosisAgent:
    """Runs inside the elastic agent process on every host."""

    def __init__(self, client=None, node_id: int = -1, interval_secs: float = 60.0):
        self._client = client
        self._node_id = node_id
        self._interval = interval_secs
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._log_source = None  # callable -> str (worker log tail)
        self._metrics_source = None  # callable -> dict (tpu_timer scrape)
        self._comm_metrics_source = None  # callable -> dict (comm ledger)
        self._hang_dumper = None  # profiler.hang_dump.HangDumper

    def set_log_source(self, fn):
        self._log_source = fn

    def set_metrics_source(self, fn):
        self._metrics_source = fn

    def set_comm_metrics_source(self, fn):
        """Per-collective comm attribution scrape (profiler/comm.py
        CommMetricsSource); shipped as CommMetricsRecord."""
        self._comm_metrics_source = fn

    def set_hang_dumper(self, dumper):
        """On a detected hang the agent collects all-rank Python stacks +
        pending device programs and ships them as a HangDumpRecord
        (reference manager.cc:454-464 gdb/py-spy dump)."""
        self._hang_dumper = dumper

    # -- failure-time decision ---------------------------------------------

    def diagnose_training_failure(self, failure: WorkerFailure) -> str:
        """Reference semantics (``training.py:1016-1027``): retryable errors
        restart in place while budget remains; fatal user errors also retry
        (the log may be incidental) but exhaust the budget faster is not
        replicated — budget exhaustion or hardware/preemption signatures
        relaunch the node."""
        kind = classify_log(failure.log_tail)
        budget_left = failure.restart_count < failure.max_restarts
        if kind == "hardware":
            logger.warning(
                "node %s: hardware/preemption failure -> relaunch",
                failure.node_id,
            )
            return WorkerAction.RELAUNCH_WORKER
        # retryable, fatal or unclassified: restart while budget lasts
        # (transient corruption is common), then hand back to the platform
        if budget_left:
            return WorkerAction.RESTART_WORKER
        return WorkerAction.RELAUNCH_WORKER

    # -- periodic observation ----------------------------------------------

    def start(self):
        if self._client is None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._observe_loop, name="diagnosis-agent", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop_evt.set()

    def _observe_loop(self):
        while not self._stop_evt.wait(self._interval):
            try:
                self.report_once()
            except Exception as e:
                logger.warning("diagnosis report failed: %s", e)

    def report_once(self):
        import json

        if self._log_source is not None:
            tail = self._log_source()
            if tail:
                self._client.report_diagnosis_data("TrainingLogRecord", tail)
        if self._metrics_source is not None:
            metrics = self._metrics_source()
            if metrics:
                self._client.report_diagnosis_data(
                    "TpuMetricsRecord", json.dumps(metrics)
                )
                if (
                    metrics.get("hang")
                    and self._hang_dumper is not None
                    and self._hang_dumper.should_dump()
                ):
                    bundle = self._hang_dumper.dump(reason="tpu_timer_hang")
                    self._client.report_diagnosis_data(
                        "HangDumpRecord", json.dumps(bundle)
                    )
        if self._comm_metrics_source is not None:
            comm = self._comm_metrics_source()
            if comm:
                self._client.report_diagnosis_data(
                    "CommMetricsRecord", json.dumps(comm)
                )

    def collect_and_ship_dump(
        self, reason: str = "master_request", min_interval: float = 20.0
    ) -> bool:
        """Master-orchestrated synchronized dump (CollectHangDump action):
        capture this host's worker stacks + pending programs NOW and ship
        them, regardless of the local hang heuristic. A short cooldown
        absorbs a re-broadcast while the previous dump is in flight."""
        import json
        import time

        if self._hang_dumper is None:
            logger.warning("collect-dump requested but no hang dumper wired")
            return False
        now = time.time()
        if now - getattr(self, "_last_forced_dump", 0.0) < min_interval:
            return False
        self._last_forced_dump = now
        bundle = self._hang_dumper.dump(reason=reason)
        self._client.report_diagnosis_data(
            "HangDumpRecord", json.dumps(bundle)
        )
        logger.info("shipped master-requested hang dump (%s)", reason)
        return True
