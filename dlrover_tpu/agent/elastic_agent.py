"""Per-host elastic agent: spawn, monitor, and restart JAX worker processes.

Parity: reference ``ElasticTrainingAgent`` (``elastic_agent/torch/training.py:428-1212``):
the ``_invoke_run`` monitor loop, membership-change restarts, failure
reporting and restart-vs-relaunch decision. TPU-natively the agent owns the
``jax.distributed`` bootstrap env (coordinator address, process ids) that it
derives from the master rendezvous, replacing torchelastic's PContext/store.
"""

from __future__ import annotations

import enum
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from dlrover_tpu.agent.config import ElasticLaunchConfig
from dlrover_tpu.agent.diagnosis_agent import (
    DiagnosisAgent,
    WorkerAction,
    WorkerFailure,
)
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.rendezvous import (
    CommWorld,
    MasterRendezvousHandler,
    RendezvousTimeoutError,
)
from dlrover_tpu.common import flags
from dlrover_tpu.common.constants import (
    DefaultValues,
    NodeEnv,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.utils.net import find_free_port, local_ip


class RunResult(enum.Enum):
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    MEMBERSHIP_CHANGED = "membership_changed"
    AGENT_STOPPED = "agent_stopped"


@dataclass
class WorkerProc:
    local_rank: int
    process_id: int
    proc: subprocess.Popen
    log_path: str


class ElasticAgent:
    def __init__(
        self,
        config: ElasticLaunchConfig,
        client: Optional[MasterClient] = None,
        log_dir: str = "",
    ):
        self._config = config
        self._client = client or MasterClient.singleton_instance()
        self._log_dir = log_dir or os.path.join(
            "/tmp", "dlrover_tpu_logs", config.job_name, f"node-{config.node_id}"
        )
        os.makedirs(self._log_dir, exist_ok=True)
        self._node_ip = local_ip()
        self._workers: List[WorkerProc] = []
        self._restart_count = 0
        self._stop_evt = threading.Event()
        self._restart_requested = threading.Event()
        self._relaunch_requested = False
        self._status_reporter = None
        self._current_world: Optional[CommWorld] = None
        self._ckpt_saver = None  # wired by the flash-checkpoint layer
        # non-numeric values warn once and fall back to the default
        # inside the typed registry (common/flags.py)
        diag_interval = float(flags.DIAG_INTERVAL.get())
        self._diagnosis = DiagnosisAgent(
            client=self._client, node_id=config.node_id,
            interval_secs=max(diag_interval, 1.0),
        )
        self._diagnosis.set_log_source(self._last_worker_log_tail)
        self._tpu_timer_env: Dict[str, str] = {}
        self._hang_dumper = None
        # external accelerator exporters (GKE TPU metrics agent etc.):
        # comma-separated host:port/path endpoints
        self._metric_monitor = None
        endpoints = flags.METRIC_ENDPOINTS.get()
        if endpoints:
            from dlrover_tpu.common.metric import TpuMetricMonitor

            self._metric_monitor = TpuMetricMonitor(
                [e.strip() for e in endpoints.split(",") if e.strip()],
                client=self._client,
            )
        self._paral_tuner = None
        from dlrover_tpu.observability import trace

        if trace.enabled():
            # the agent's spine (rendezvous spans) dumps next to the
            # workers' at exit; JOB_NAME rides the registry so the
            # default dump dir matches theirs
            flags.JOB_NAME.propagate(config.job_name)
            trace.dump_at_exit(role="agent", node_id=config.node_id)
        if config.tpu_timer:
            self._setup_tpu_timer()
        if config.comm_metrics:
            from dlrover_tpu.profiler.comm import CommMetricsSource

            self._diagnosis.set_comm_metrics_source(CommMetricsSource([
                config.comm_metrics_port + i
                for i in range(config.nproc_per_node)
            ]))

    def _setup_tpu_timer(self):
        """Route workers' PJRT plugin loading through the native profiler
        and scrape its metrics into diagnosis (reference: xpu_timer launch
        wrapper + XpuTimerMetricsCollector). Each local rank gets its own
        metrics port (base + local_rank) so servers never collide."""
        import subprocess

        from dlrover_tpu.profiler import TpuTimerMetricsSource, interposer_env

        try:
            self._tpu_timer_env = interposer_env(
                port=self._config.tpu_timer_port
            )
        except subprocess.CalledProcessError as e:
            logger.error(
                "tpu_timer native build failed; disabled:\n%s",
                (e.stderr or b"").decode(errors="replace")[-2000:],
            )
            self._tpu_timer_env = {}
            return
        except Exception:
            logger.exception("tpu_timer setup failed; disabled")
            self._tpu_timer_env = {}
            return
        if self._tpu_timer_env:
            from dlrover_tpu.profiler.hang_dump import HangDumper

            ports = [
                self._config.tpu_timer_port + i
                for i in range(self._config.nproc_per_node)
            ]
            self._diagnosis.set_metrics_source(TpuTimerMetricsSource(ports))
            self._hang_dumper = HangDumper(
                stack_dir=os.path.join(self._log_dir, "hang"),
                metrics_ports=ports,
            )
            self._diagnosis.set_hang_dumper(self._hang_dumper)

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> int:
        self._client.report_node_address(
            self._node_ip,
            slice_name=self._config.slice_name,
            coords=self._config.coords,
        )
        self._start_ckpt_saver()
        self._start_heartbeats()
        if self._metric_monitor is not None:
            self._metric_monitor.start()
        self._install_signal_handlers()
        self._diagnosis.start()
        self._start_paral_config_tuner()
        try:
            return self._invoke_run()
        finally:
            self._stop_evt.set()
            if self._status_reporter is not None:
                self._status_reporter.stop()
            self._diagnosis.stop()
            if self._metric_monitor is not None:
                self._metric_monitor.stop()
            if self._paral_tuner is not None:
                self._paral_tuner.stop()
            self._stop_workers()
            if self._ckpt_saver is not None:
                self._ckpt_saver.stop()

    def _start_paral_config_tuner(self):
        from dlrover_tpu.agent.paral_config_tuner import ParalConfigTuner

        try:
            self._paral_tuner = ParalConfigTuner(
                self._client,
                job_name=self._config.job_name,
                node_id=self._config.node_id,
            )
            self._paral_tuner.start()
        except Exception:
            logger.exception("paral config tuner failed to start")
            self._paral_tuner = None

    def _start_ckpt_saver(self):
        """Host the flash-checkpoint saver so staged state survives worker
        crashes (reference: AsyncCheckpointSaver.start_async_saving_ckpt)."""
        from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

        try:
            self._ckpt_saver = AsyncCheckpointSaver(
                job_name=self._config.job_name,
                node_id=self._config.node_id,
                replica=self._config.ckpt_replica,
            )
            self._ckpt_saver.start()
            if self._ckpt_saver.replica_port:
                # publish the replica server so peers can reach it
                self._client.report_node_address(
                    self._node_ip,
                    port=self._ckpt_saver.replica_port,
                    slice_name=self._config.slice_name,
                    coords=self._config.coords,
                )
        except Exception:
            logger.exception("checkpoint saver failed to start; continuing")
            self._ckpt_saver = None

    def _invoke_run(self) -> int:
        while not self._stop_evt.is_set():
            try:
                world = self._rendezvous()
            except RendezvousTimeoutError as e:
                logger.error("rendezvous timed out: %s", e)
                self._client.report_failure(str(e), self._restart_count)
                return 1
            self._start_workers(world)
            result, exit_code, err = self._monitor_workers()
            if result == RunResult.SUCCEEDED:
                logger.info("node %s: workers succeeded", self._config.node_id)
                self._client.report_succeeded()
                if self._ckpt_saver is not None:
                    self._ckpt_saver.cleanup_shm()
                return 0
            if result == RunResult.AGENT_STOPPED:
                # Stopped by a master action (relaunch) or a signal: exit
                # nonzero so the platform replaces this node.
                self._save_checkpoint_at_breakpoint()
                self._stop_workers()
                return 143 if self._relaunch_requested else 0
            if result == RunResult.MEMBERSHIP_CHANGED:
                logger.info(
                    "node %s: membership changed; restarting workers",
                    self._config.node_id,
                )
                self._save_checkpoint_at_breakpoint()
                self._stop_workers()
                continue
            # FAILED: the diagnostician decides restart-in-place vs handing
            # the node back to the platform (reference training.py:1016-1027)
            self._save_checkpoint_at_breakpoint()
            self._stop_workers()
            self._client.report_failure(
                err, self._restart_count, TrainingExceptionLevel.ERROR, exit_code
            )
            action = self._diagnosis.diagnose_training_failure(
                WorkerFailure(
                    node_id=self._config.node_id,
                    restart_count=self._restart_count,
                    max_restarts=self._config.max_restarts,
                    exit_code=exit_code,
                    log_tail=err,
                )
            )
            if action == WorkerAction.RESTART_WORKER:
                self._restart_count += 1
                logger.warning(
                    "node %s: worker failed (exit=%s); restart %s/%s",
                    self._config.node_id,
                    exit_code,
                    self._restart_count,
                    self._config.max_restarts,
                )
                continue
            logger.error(
                "node %s: diagnosis says relaunch (exit=%s); exiting",
                self._config.node_id,
                exit_code,
            )
            return exit_code or 1
        return 0

    # -- rendezvous ---------------------------------------------------------

    def _rendezvous(self) -> CommWorld:
        coord_port = self._config.training_port or find_free_port()
        handler = MasterRendezvousHandler(
            self._client,
            RendezvousName.TRAINING,
            local_world_size=self._config.nproc_per_node,
            node_ip=self._node_ip,
            node_port=coord_port,
            slice_name=self._config.slice_name,
            coords=self._config.coords,
            join_timeout=self._config.rdzv_join_timeout,
        )
        world = handler.next_rendezvous(node_rank_hint=self._config.node_id)
        self._current_world = world
        self._rdzv_handler = handler
        if self._ckpt_saver is not None:
            self._ckpt_saver.update_topology(
                node_rank=world.node_rank,
                num_nodes=world.world_size,
                process_ids=[
                    world.process_id_base + i
                    for i in range(self._config.nproc_per_node)
                ],
            )
            if self._config.ckpt_replica:
                self._sync_replica_peers(world)
        return world

    def _replica_token(self, world: CommWorld) -> str:
        """Shared secret for the cross-host replica servers, minted by the
        round's rank-0 agent and distributed through the master KV store
        (the replica port is reachable cross-host, unlike the node-local
        IPC socket, so requests must be authenticated)."""
        key = "ckpt-replica-token"
        if world.node_rank == 0:
            token = self._client.kv_store_get(key)
            if not token:
                import secrets

                token = secrets.token_hex(16).encode()
                self._client.kv_store_set(key, token)
            return bytes(token).decode()
        deadline = time.time() + 60
        while time.time() < deadline:
            token = self._client.kv_store_get(key)
            if token:
                return bytes(token).decode()
            time.sleep(0.5)
        logger.warning("replica token not available; replica push disabled")
        return ""

    def _sync_replica_peers(self, world: CommWorld):
        """Map rendezvous ranks to peers' replica servers, then pull this
        seat's backup if nothing is staged locally (node replacement)."""
        try:
            token = self._replica_token(world)
            if token:
                self._ckpt_saver.set_replica_token(token)
            by_id = {
                m.node_id: m
                for m in self._client.get_running_nodes()
                if m.port
            }
            peers = {}
            for rank, (node_id, _lws, ip, _port) in world.members.items():
                meta = by_id.get(node_id)
                if meta is not None:
                    peers[rank] = (meta.addr or ip, meta.port)
            self._ckpt_saver.update_replica_peers(
                peers, world.node_rank, world.world_size
            )
            step = self._ckpt_saver.maybe_fetch_replica()
            if step >= 0:
                logger.info(
                    "node %s: staged step %s recovered from peer replica",
                    self._config.node_id,
                    step,
                )
        except Exception:
            logger.exception("replica peer sync failed")

    # -- workers ------------------------------------------------------------

    def _worker_env(self, world: CommWorld, local_rank: int) -> Dict[str, str]:
        env = flags.child_env(self._config.env)
        if self._config.ckpt_replica:
            env["DLROVER_TPU_CKPT_REPLICA"] = "1"
        if self._config.compile_cache_dir:
            # workers point JAX's persistent compile cache here
            # (train/warm_compile.py via bootstrap.init) so a restarted
            # worker's step rebuild is a cache hit, not a cold compile
            env["DLROVER_TPU_COMPILE_CACHE_DIR"] = (
                self._config.compile_cache_dir
            )
        if self._paral_tuner is not None:
            from dlrover_tpu.agent.paral_config_tuner import (
                PARAL_CONFIG_PATH_ENV,
            )

            env[PARAL_CONFIG_PATH_ENV] = self._paral_tuner.path
        if self._tpu_timer_env:
            env.update(self._tpu_timer_env)
            # one metrics server per local rank
            env["DLROVER_TPU_TIMER_PORT"] = str(
                self._config.tpu_timer_port + local_rank
            )
        process_id = world.process_id_base + local_rank
        if self._config.comm_metrics:
            env["DLROVER_TPU_COMM_METRICS_PORT"] = str(
                self._config.comm_metrics_port + local_rank
            )
        env.update(
            {
                NodeEnv.JOB_NAME: self._config.job_name,
                NodeEnv.MASTER_ADDR: self._client.master_addr,
                NodeEnv.NODE_ID: str(self._config.node_id),
                NodeEnv.NODE_RANK: str(world.node_rank),
                NodeEnv.NODE_NUM: str(world.world_size),
                NodeEnv.COORDINATOR_ADDR: world.coordinator_addr,
                NodeEnv.PROCESS_ID: str(process_id),
                NodeEnv.NUM_PROCESSES: str(world.num_processes),
                NodeEnv.RESTART_COUNT: str(self._restart_count),
                "DLROVER_TPU_ACCELERATOR": self._config.accelerator,
                "DLROVER_TPU_LOCAL_RANK": str(local_rank),
                # distinct TPU slices in the seated world: training code
                # sizes the multislice mesh's DCN axis from this, so a
                # slice-count resize flows through re-rendezvous
                "DLROVER_TPU_NUM_SLICES": str(world.n_slices),
                # workers install a SIGUSR2 faulthandler writing here; the
                # agent's HangDumper signals + collects on a detected hang
                "DLROVER_TPU_STACK_DIR": os.path.join(self._log_dir, "hang"),
            }
        )
        return env

    def _start_workers(self, world: CommWorld):
        self._workers = []
        for local_rank in range(self._config.nproc_per_node):
            process_id = world.process_id_base + local_rank
            log_path = os.path.join(
                self._log_dir,
                f"worker-{process_id}-restart{self._restart_count}.log",
            )
            log_file = open(log_path, "ab")
            cmd = [sys.executable, self._config.entrypoint] + list(
                self._config.entrypoint_args
            )
            proc = subprocess.Popen(
                cmd,
                env=self._worker_env(world, local_rank),
                stdout=log_file,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            log_file.close()
            self._workers.append(WorkerProc(local_rank, process_id, proc, log_path))
            logger.info(
                "node %s: started worker process_id=%s pid=%s log=%s",
                self._config.node_id,
                process_id,
                proc.pid,
                log_path,
            )
        if self._hang_dumper is not None:
            self._hang_dumper.set_workers(
                [w.proc.pid for w in self._workers]
            )

    def _stop_workers(self, grace: float = 10.0):
        for w in self._workers:
            if w.proc.poll() is None:
                try:
                    os.killpg(w.proc.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + grace
        for w in self._workers:
            timeout = max(0.1, deadline - time.time())
            try:
                w.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(w.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                w.proc.wait()
        self._workers = []

    def _last_worker_log_tail(self, max_bytes: int = 4096) -> str:
        """Concatenated log tails across all local workers (any process on
        this host may carry the failure signature)."""
        workers = list(self._workers)
        if not workers:
            return ""
        per = max(512, max_bytes // len(workers))
        return "\n".join(
            t for t in (self._tail_log(w.log_path, per) for w in workers) if t
        )

    def _tail_log(self, path: str, max_bytes: int = 4096) -> str:
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - max_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    # -- monitoring ---------------------------------------------------------

    def _membership_changed(self) -> bool:
        """A node is waiting to (re)join -> the world must re-form."""
        try:
            return self._rdzv_handler.num_nodes_waiting() > 0
        except Exception:
            return False

    def _monitor_workers(self):
        """Returns (RunResult, exit_code, error_text)."""
        while not self._stop_evt.is_set():
            time.sleep(self._config.monitor_interval)
            states = [(w, w.proc.poll()) for w in self._workers]
            failed = next((s for s in states if s[1] not in (None, 0)), None)
            if failed is not None:
                err = self._tail_log(failed[0].log_path)
                return RunResult.FAILED, failed[1] or 1, err
            if all(code == 0 for _, code in states):
                return RunResult.SUCCEEDED, 0, ""
            if self._restart_requested.is_set():
                self._restart_requested.clear()
                return RunResult.MEMBERSHIP_CHANGED, 0, ""
            if self._membership_changed():
                return RunResult.MEMBERSHIP_CHANGED, 0, ""
        return RunResult.AGENT_STOPPED, 0, ""

    # -- heartbeats / signals ----------------------------------------------

    def _start_heartbeats(self):
        """Folded status reports replace the old heartbeat-only loop:
        heartbeat + host resource usage ride one periodic RPC
        (agent/reporter.py), and an ``Overloaded`` master widens the
        cadence instead of being hammered. Diagnosis actions still
        arrive on the ack exactly as before."""
        from dlrover_tpu.agent.reporter import StatusReporter

        self._status_reporter = StatusReporter(
            self._client,
            interval_s=DefaultValues.SEC_AGENT_HEARTBEAT_INTERVAL,
            on_actions=lambda actions: [
                self._handle_action(a) for a in actions
            ],
        )
        self._status_reporter.start()

    def _handle_action(self, action):
        cls = getattr(action, "action_cls", "")
        if cls == "RestartWorker":
            self._restart_requested.set()
        elif cls == "RelaunchWorker":
            logger.warning("master requested node relaunch; stopping agent")
            self._relaunch_requested = True
            self._stop_evt.set()
        elif cls == "CollectHangDump":
            # synchronized cross-node dump: off the heartbeat thread (the
            # dump settles ~1.5s waiting for SIGUSR2 stacks to land)
            threading.Thread(
                target=self._diagnosis.collect_and_ship_dump,
                kwargs={"reason": action.action_content or "master_request"},
                name="collect-dump",
                daemon=True,
            ).start()

    def _install_signal_handlers(self):
        if threading.current_thread() is not threading.main_thread():
            return

        def handle(signum, frame):
            # intentional save-on-signal: the preemption grace window is
            # the ONLY time to persist the staged checkpoint, so this
            # handler owns the blocking-I/O risk (the reference agent
            # makes the same trade)  # graftlint: disable=JG005
            logger.warning("agent got signal %s; saving + stopping", signum)
            self._save_checkpoint_at_breakpoint()
            self._stop_evt.set()
            self._stop_workers(grace=5)
            raise SystemExit(143 if signum == signal.SIGTERM else 130)

        signal.signal(signal.SIGTERM, handle)

    # -- checkpoint hook (flash ckpt wires in) ------------------------------

    def set_checkpoint_saver(self, saver):
        self._ckpt_saver = saver

    def _save_checkpoint_at_breakpoint(self):
        if not self._config.save_at_breakpoint:
            return
        if self._ckpt_saver is not None:
            try:
                self._ckpt_saver.save_shm_to_storage()
            except Exception:
                logger.exception("breakpoint checkpoint persist failed")
