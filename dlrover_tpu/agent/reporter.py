"""Agent-side folded status reporter (ROADMAP item 5 backpressure).

The agent used to run one thread per signal: a heartbeat loop and a
``ResourceMonitor`` loop, each its own RPC — at 1k nodes that is 2k
periodic streams the master serves for no reason. This reporter folds
heartbeat + host resource usage into ONE :class:`WorkerReport` per
period (step digests already ride the trainer's throttled step report),
and honors the server's explicit ``Overloaded`` reply by widening its
cadence (AIMD — :class:`~dlrover_tpu.rpc.policy.AdaptiveInterval`)
instead of retrying into the overload. An unreachable master widens
too: a relaunch gap with 1k nodes retrying at full cadence is a
self-inflicted overload on the fresh master.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from dlrover_tpu.agent.monitor import (
    get_process_cpu_percent,
    get_tpu_metrics,
    get_used_memory_mb,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.rpc.policy import AdaptiveInterval, OverloadedError


class StatusReporter:
    """Periodic folded worker report with backpressure honor.

    ``on_actions`` receives the diagnosis actions the master piggybacks
    on the ack (same contract as the old heartbeat loop)."""

    def __init__(
        self,
        client,
        interval_s: float = 15.0,
        max_interval_s: Optional[float] = None,
        on_actions: Optional[Callable[[List], None]] = None,
    ):
        self._client = client
        # default widening bound of 4x base: the unreachable-master
        # path has no server-advertised liveness ceiling (nobody is
        # answering), and an unbounded AIMD walk (16x = 240s on the
        # default cadence) would carry a healthy agent past aggressive
        # heartbeat timeouts during a long master outage — the same
        # eviction-by-politeness bug the chaos harness caught on the
        # Overloaded path. An unreachable master gains nothing from
        # widening beyond spam reduction, so the bound is cheap.
        self._interval = AdaptiveInterval(
            interval_s,
            max_interval_s if max_interval_s is not None
            else interval_s * 4,
        )
        self._on_actions = on_actions
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.reports_sent = 0
        self.reports_shed = 0

    @property
    def current_interval_s(self) -> float:
        return self._interval.current_s

    def report_once(self) -> bool:
        """One folded report; returns False when shed/unreachable (and
        the cadence has been widened accordingly)."""
        try:
            tpu = get_tpu_metrics()
            resp = self._client.report_worker_status(
                cpu_percent=get_process_cpu_percent(),
                memory_mb=get_used_memory_mb(),
                tpu_duty_cycle=tpu.get("duty_cycle", 0.0),
            )
        except OverloadedError as e:
            self.reports_shed += 1
            widened = self._interval.widen(e.retry_after_s, e.max_interval_s)
            logger.warning(
                "master shed status report (depth=%s); widening interval "
                "to %.1fs", e.queue_depth, widened,
            )
            return False
        except Exception as e:  # master restartable
            self._interval.widen()
            logger.warning("status report failed: %s", e)
            return False
        self.reports_sent += 1
        self._interval.ok()
        if self._on_actions is not None and getattr(resp, "actions", None):
            try:
                self._on_actions(list(resp.actions))
            except Exception:
                logger.exception("status-report action handler failed")
        return True

    def start(self):
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="status-reporter", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop_evt.set()

    def _loop(self):
        # jittered wait: overload-widened reporters must de-phase, not
        # pound the gate in cohorts (policy.AdaptiveInterval.next_delay_s)
        while not self._stop_evt.wait(self._interval.next_delay_s()):
            self.report_once()
