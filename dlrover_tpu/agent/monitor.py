"""Per-node resource monitor (parity: elastic_agent/monitor/resource.py).

Reports host CPU/memory (psutil) and, when available, TPU duty cycle /
HBM usage to the master every interval. TPU metrics come from libtpu's
metrics endpoint when present; absent that (e.g. CPU test mode) they are 0.
"""

from __future__ import annotations

import threading
from typing import Optional

from dlrover_tpu.common.constants import DefaultValues
from dlrover_tpu.common.log import logger

try:
    import psutil
except ImportError:  # pragma: no cover
    psutil = None


def get_process_cpu_percent() -> float:
    if psutil is None:
        return 0.0
    try:
        return psutil.cpu_percent(interval=None) / 100.0
    except Exception:
        return 0.0


def get_used_memory_mb() -> float:
    if psutil is None:
        return 0.0
    try:
        mem = psutil.virtual_memory()
        return float(mem.used) / (1024 * 1024)
    except Exception:
        return 0.0


def get_tpu_metrics() -> dict:
    """Best-effort TPU duty-cycle/HBM metrics; zeros off-TPU."""
    return {"duty_cycle": 0.0, "hbm_used_mb": 0.0}


class ResourceMonitor:
    def __init__(self, client, interval: float = 15.0):
        self._client = client
        self._interval = interval
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="resource-monitor", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop_evt.set()

    def report_once(self):
        tpu = get_tpu_metrics()
        self._client.report_used_resource(
            cpu_percent=get_process_cpu_percent(),
            memory_mb=get_used_memory_mb(),
            tpu_duty_cycle=tpu["duty_cycle"],
        )

    def _loop(self):
        while not self._stop_evt.wait(self._interval):
            try:
                self.report_once()
            except Exception as e:
                logger.warning("resource report failed: %s", e)
