"""Warm-path elasticity: make the post-resize recompile a cache hit.

The flash-checkpoint port minimizes the *save* side of a membership
change; this module attacks the *rebuild* side. After a resize,
``ElasticTrainer.remesh()`` drops the jitted step and the next
``step()`` call recompiles the full fwd+bwd+adamw program from scratch
— tens of seconds of dead chip time for a billion-param model. Three
layers turn that cold compile into a warm one:

1. **Persistent compilation cache** (:func:`enable_persistent_cache`):
   JAX's on-disk executable cache, pointed at
   ``DLROVER_TPU_COMPILE_CACHE_DIR`` (the elastic agent injects it; the
   checkpoint engine defaults it under the checkpoint dir so it lives
   on the same volume that already survives pod restarts). A restarted
   worker deserializes the step executable instead of recompiling.

2. **AOT compilation** (:meth:`ElasticTrainer.lower_step`): the step
   can be lowered and compiled against ``jax.ShapeDtypeStruct``
   avatars, so a world size that is *not live* can be compiled for —
   no state arrays, no training pause. Compiled executables are kept
   in an in-process cache keyed by the step *signature* (mesh shape +
   device assignment + accum + state/batch avatars), so a same-process
   remesh picks the executable up with zero compile.

3. **Speculative neighbor compilation** (:func:`neighbor_worlds` +
   :class:`WarmCompiler`): after each successful live build, a single
   bounded daemon thread compiles the step for the neighbor world
   sizes the ``MeshConfig`` admits (world ± one node, world/2 — the
   memberships an elastic resize actually lands on), populating both
   caches before the resize happens. Worlds larger than the attached
   device set cannot be speculated from here; they are covered by the
   persistent cache instead (a grow event returns to a world that
   compiled before the shrink).

Everything is behind the ``DLROVER_TPU_WARM_COMPILE=0`` kill-switch,
which restores the plain ``jax.jit`` rebuild path exactly. Compile
times land in a small JSON ledger (``compile_ledger.json`` next to the
cache) keyed by ``(world, config-hash)`` with a cold/warm/speculative
source tag, and are exported as Prometheus gauges on the worker
``/metrics`` endpoint (profiler/comm.py).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

PyTree = Any

# flag names kept importable for tests/docs; reads go through the
# typed registry (common/flags.py, graftlint JG003)
ENV_KILL_SWITCH = flags.WARM_COMPILE.name
ENV_CACHE_DIR = flags.COMPILE_CACHE_DIR.name
ENV_MIN_COMPILE_S = flags.COMPILE_CACHE_MIN_S.name
ENV_MAX_TARGETS = flags.WARM_COMPILE_MAX_TARGETS.name

LEDGER_FILENAME = "compile_ledger.json"

__all__ = [
    "warm_compile_enabled",
    "enable_persistent_cache",
    "default_cache_under",
    "configured_cache_dir",
    "neighbor_worlds",
    "CompileLedger",
    "compile_ledger",
    "WarmCompiler",
    "prometheus_lines",
]


def warm_compile_enabled() -> bool:
    """Kill-switch, read at call time so tests/benches can flip it."""
    return flags.WARM_COMPILE.get()


_enable_lock = threading.Lock()
_enabled_dir: Optional[str] = None


def configured_cache_dir() -> Optional[str]:
    """The persistent-cache dir this process actually runs with: what
    this module configured, else whatever jax was already given
    (``JAX_COMPILATION_CACHE_DIR``, bench's ``_enable_jit_cache``)."""
    if _enabled_dir:
        return _enabled_dir
    try:
        import jax

        return getattr(jax.config, "jax_compilation_cache_dir", None) or None
    except Exception:
        return None


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (default:
    ``DLROVER_TPU_COMPILE_CACHE_DIR``). Idempotent, and never overrides
    a cache dir jax already has — the jax config is process-global and
    the first owner (a user's ``JAX_COMPILATION_CACHE_DIR``, bench's
    per-user cache) wins. Returns the effective dir, or None when
    disabled/unconfigured. Purely an optimization: any failure logs and
    returns None rather than failing the caller."""
    global _enabled_dir
    if not warm_compile_enabled():
        return None
    with _enable_lock:
        existing = configured_cache_dir()
        if existing:
            return existing
        path = path or flags.COMPILE_CACHE_DIR.get()
        if not path:
            return None
        try:
            os.makedirs(path, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(flags.COMPILE_CACHE_MIN_S.get()),
            )
        except Exception as e:
            logger.warning("persistent compile cache unavailable: %s", e)
            return None
        # children (speculative compile helpers, interposed probes,
        # restarted workers forked from this env) inherit the same dir
        flags.COMPILE_CACHE_DIR.propagate(path)
        _enabled_dir = path
        logger.info("persistent compile cache at %s", path)
        return path


def default_cache_under(base_dir: str) -> Optional[str]:
    """Checkpoint-engine hook: when nothing configured a cache dir,
    default it to ``<ckpt_dir>/compile_cache`` — the checkpoint dir is
    the one path the deployment already persists across pod restarts,
    so the compile cache survives exactly as far as the checkpoints
    do. An explicit ``DLROVER_TPU_COMPILE_CACHE_DIR`` wins."""
    if not warm_compile_enabled():
        return None
    if flags.COMPILE_CACHE_DIR.present():
        return enable_persistent_cache()
    if not base_dir:
        return None
    return enable_persistent_cache(os.path.join(base_dir, "compile_cache"))


# ---------------------------------------------------------------------------
# Compile-seconds ledger
# ---------------------------------------------------------------------------


class CompileLedger:
    """Compile seconds per ``(world, config-hash)``, with provenance.

    In-memory always (tests and the bench's resize phase read it); when
    a persistent cache dir is configured the ledger is also mirrored to
    ``compile_ledger.json`` inside it, atomically, so post-mortems can
    see what each membership's step cost to build and whether resizes
    were landing warm."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, dict] = {}
        self._disk_merged = False

    def _merge_disk_locked(self):
        """Fold the previous lifetime's ledger in before the first
        persist — a restarted worker must extend the file, not clobber
        it (the whole point is seeing cold→warm across restarts)."""
        if self._disk_merged:
            return
        cache_dir = configured_cache_dir()
        if not cache_dir:
            return  # retry on a later record; a dir may appear
        self._disk_merged = True
        path = os.path.join(cache_dir, LEDGER_FILENAME)
        try:
            with open(path) as f:
                disk = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(disk, dict):
            return
        for key, entry in disk.items():
            if not isinstance(entry, dict) or "compiles" not in entry:
                continue
            ours = self._entries.get(key)
            if ours is None:
                self._entries[key] = dict(entry)
            else:
                ours["compiles"] = (
                    list(entry["compiles"]) + ours["compiles"]
                )

    def record(
        self,
        world: int,
        config_hash: str,
        seconds: float,
        source: str,
    ) -> dict:
        """``source``: ``cold`` (live blocking compile), ``warm``
        (in-process AOT cache hit), ``speculative`` (background
        neighbor compile), ``jit`` (kill-switch path, first-call time
        not separable from the first step)."""
        key = f"world{world}:{config_hash}"
        with self._lock:
            self._merge_disk_locked()
            entry = self._entries.setdefault(
                key,
                {
                    "world": world,
                    "config_hash": config_hash,
                    "compiles": [],
                },
            )
            entry["compiles"].append(
                {
                    "seconds": round(seconds, 4),
                    "source": source,
                    "ts": time.time(),
                }
            )
            snapshot = {k: dict(v) for k, v in self._entries.items()}
        self._persist(snapshot)
        return entry

    def get(self, world: int, config_hash: str) -> Optional[dict]:
        with self._lock:
            return self._entries.get(f"world{world}:{config_hash}")

    def entries(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def clear(self):
        with self._lock:
            self._entries.clear()

    def _persist(self, snapshot: Dict[str, dict]):
        cache_dir = configured_cache_dir()
        if not cache_dir or not os.path.isdir(cache_dir):
            return
        path = os.path.join(cache_dir, LEDGER_FILENAME)
        try:
            # multiple workers share one cache dir (the intended k8s
            # layout): fold in keys other writers added since our merge
            # so the file converges instead of ping-pong clobbering.
            # Same-key concurrent updates are still last-writer-wins
            # within a write window — acceptable for telemetry.
            try:
                with open(path) as f:
                    disk = json.load(f)
                if isinstance(disk, dict):
                    for key, entry in disk.items():
                        if key not in snapshot and isinstance(entry, dict):
                            snapshot[key] = entry
            except (OSError, ValueError):
                pass
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # telemetry only, never worth failing a compile over

    def prometheus_lines(self) -> List[str]:
        """Gauges for the worker /metrics endpoint: last compile
        seconds per (world, source) plus warm-hit counts."""
        lines = [
            "# TYPE dlrover_tpu_compile_seconds gauge",
            "# TYPE dlrover_tpu_compile_count gauge",
        ]
        with self._lock:
            entries = {k: dict(v) for k, v in self._entries.items()}
        for key in sorted(entries):
            e = entries[key]
            by_source: Dict[str, List[dict]] = {}
            for c in e["compiles"]:
                by_source.setdefault(c["source"], []).append(c)
            for source in sorted(by_source):
                rows = by_source[source]
                label = (
                    f'world="{e["world"]}",config="{e["config_hash"]}",'
                    f'source="{source}"'
                )
                lines.append(
                    f"dlrover_tpu_compile_seconds{{{label}}} "
                    f"{rows[-1]['seconds']:.4f}"
                )
                lines.append(
                    f"dlrover_tpu_compile_count{{{label}}} {len(rows)}"
                )
        return lines


#: process-wide ledger (one trainer per process is the normal shape;
#: bench sweeps share it, which is fine — entries are keyed by config)
compile_ledger = CompileLedger()


def prometheus_lines() -> List[str]:
    """Module-level convenience for the metrics server."""
    return compile_ledger.prometheus_lines()


# ---------------------------------------------------------------------------
# Neighbor-world heuristic
# ---------------------------------------------------------------------------


def neighbor_worlds(
    world: int,
    mesh_config,
    *,
    n_devices_available: int,
    devices_per_node: int = 1,
    global_batch_size: int,
    micro_batch_size: int,
    max_targets: Optional[int] = None,
    n_slices: int = 1,
) -> List["WorldDescriptor"]:
    """Candidate :class:`~dlrover_tpu.common.world.WorldDescriptor`\\ s
    a resize is likely to land on, filtered to the ones we can actually
    compile for from here. Each descriptor carries the refit mesh axes
    and the surviving slice count — the same checked type the goodput
    planner scores and the contract specs key on, so the speculated
    executable and everything downstream describe one world.

    Candidates, in priority order: world minus one node (the single
    most common elastic event — a preemption/eviction), world/2 (an
    autoscaler halving), world plus one node (node recovered). A
    candidate survives only if

    - it differs from ``world`` and is > 0;
    - a mesh for it exists within the attached device set (speculation
      compiles against a *subset* mesh of live devices; a world larger
      than what is attached has no devices to lower against — the
      persistent cache covers grow events instead);
    - the refit ``MeshConfig`` (``parallel.mesh.remesh``) admits it —
      model axes are preserved, so the world must still hold them;
    - the elastic global-batch invariant holds: ``global_batch %
      (micro_batch * dp') == 0`` for the refit config.

    ``n_slices > 1`` (multislice): the resize unit is a whole SLICE,
    not a node — a preemption takes the slice with it and the survivor
    worlds are whole-slice multiples. Candidates become world minus one
    slice (the most common multislice loss), half the slices, world
    plus one slice; every candidate must tile into whole slices AND the
    refit dp (or, for stage-pinned pp worlds, pp) must still decompose
    over the surviving slice count (dp and pp are the only axes allowed
    to span DCN). A slice loss then resizes warm: the speculated
    executable was compiled on the slice-major neighbor mesh the
    re-seated world actually forms.

    Stage-aware enumeration (``pp > 1``): each candidate world size is
    tried both pp-preserving (shrink/grow the data axes WITHIN every
    stage — `parallel.mesh.remesh` keeps model axes) and with the stage
    count rebalanced (pp halved / doubled, layers re-slabbed), so a
    node loss that starves a stage of its dp width still has a
    speculated executable waiting."""
    import dataclasses as _dc

    from dlrover_tpu.common.world import WorldDescriptor
    from dlrover_tpu.parallel.mesh import remesh as remesh_config

    if max_targets is None:
        max_targets = int(flags.WARM_COMPILE_MAX_TARGETS.get())
    node = max(1, devices_per_node)
    per_slice = world // n_slices if n_slices > 1 else 0
    if n_slices > 1 and (world % n_slices or per_slice == 0):
        per_slice = 0
    if per_slice:
        raw = [world - per_slice, (n_slices // 2) * per_slice,
               world + per_slice]
    else:
        raw = [world - node, world // 2, world + node]
    pp0 = getattr(mesh_config, "pp", 1)
    base_cfgs = [mesh_config]
    if pp0 > 1:
        if pp0 % 2 == 0:
            base_cfgs.append(_dc.replace(mesh_config, pp=pp0 // 2))
        base_cfgs.append(_dc.replace(mesh_config, pp=pp0 * 2))
    out: List[WorldDescriptor] = []
    seen: set = set()
    for w in raw:
        if w <= 0 or w == world:
            continue
        if w > n_devices_available:
            continue
        for base in base_cfgs:
            try:
                refit = remesh_config(base, w)
                resolved = refit.resolve(w)
                dp = resolved.data_parallel_size
            except ValueError:
                continue
            if global_batch_size % (micro_batch_size * dp):
                continue
            slices = 1
            if per_slice:
                slices = w // per_slice
                if w % per_slice:
                    continue
                # the surviving world must still host a legal
                # multislice mesh: dp spans DCN when it can, else
                # whole pp stages pin to slices; nothing else may
                if slices > 1 and resolved.dp % slices \
                        and resolved.pp % slices:
                    continue
            try:
                cand = WorldDescriptor.from_axis_sizes(
                    resolved.shape(),
                    n_slices=max(1, slices),
                    hier=slices > 1,
                )
            except ValueError:
                continue
            if cand.spec in seen:
                continue
            seen.add(cand.spec)
            out.append(cand)
            if len(out) >= max_targets:
                return out
    return out


# ---------------------------------------------------------------------------
# In-process AOT executable cache + speculative compile thread
# ---------------------------------------------------------------------------


def signature_hash(parts: Sequence[str]) -> str:
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:12]


class WarmCompiler:
    """Holds compiled step executables and runs the speculative thread.

    The cache is in-process: a same-process remesh (bench resize phase,
    slice-count change absorbed without a restart) reuses the compiled
    executable directly. Across restarts the persistent XLA cache does
    the same job one layer down. One ``WarmCompiler`` per trainer.

    The speculative thread is deliberately modest: a single daemon
    thread, targets compiled serially, bounded count
    (``DLROVER_TPU_WARM_COMPILE_MAX_TARGETS``, default 2), and it skips
    entirely when no persistent cache dir is configured — without one,
    a speculative compile only helps a same-process resize, and a
    billion-param lowering costs real host RAM that the live step's
    input pipeline may want. It never raises into the training loop."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: Dict[str, Any] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        _live_compilers.add(self)

    # -- executable cache ---------------------------------------------------

    def get(self, sig: str) -> Optional[Any]:
        with self._lock:
            return self._cache.get(sig)

    def put(self, sig: str, compiled: Any):
        with self._lock:
            self._cache[sig] = compiled

    def evict(self, sig: str):
        """Drop a signature whose executable proved unusable (e.g. the
        live state rejected its input shardings) so later remeshes
        don't keep warm-hitting a poisoned entry."""
        with self._lock:
            self._cache.pop(sig, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)

    def clear(self):
        self.cancel()
        with self._lock:
            self._cache.clear()

    # -- speculation --------------------------------------------------------

    @property
    def speculating(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def speculate(
        self,
        targets: Sequence[Any],
        compile_for_world: Callable[[Any], Any],
        require_cache_dir: bool = True,
    ) -> bool:
        """Kick the background thread compiling ``compile_for_world(w)``
        for each target (``WorldDescriptor``\\ s from
        ``neighbor_worlds``, or whatever the caller's compile fn
        accepts). Returns True if a thread was started.
        At most one speculation generation runs at a time; a new call
        while one is in flight is dropped (the next build re-triggers)."""
        if not warm_compile_enabled() or not targets:
            return False
        if require_cache_dir and not (
            configured_cache_dir() or enable_persistent_cache()
        ):
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run,
                args=(list(targets), compile_for_world),
                name="warm-compile",
                daemon=True,
            )
            self._thread.start()
        return True

    def _run(self, targets: List[Any], compile_for_world):
        for w in targets:
            if self._stop.is_set():
                return
            try:
                compile_for_world(w)
            except Exception as e:
                # a neighbor that cannot lower (odd divisibility the
                # heuristic missed, OOM in the compiler) is just an
                # uncached future resize, not an error worth a restart
                logger.warning(
                    "speculative compile for world=%s skipped: %s",
                    getattr(w, "spec", w), e,
                )

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Join the speculative thread (tests / bench). True if idle."""
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def cancel(self):
        self._stop.set()
        self.wait_idle(timeout=5.0)


#: every live WarmCompiler, so interpreter exit can join their threads:
#: a daemon thread abandoned inside an XLA compile segfaults CPython's
#: teardown (pthread_exit mid-C++-frame). The stop flag bounds the wait
#: to at most the one in-flight target.
_live_compilers: "weakref.WeakSet[WarmCompiler]" = weakref.WeakSet()


def _shutdown_speculation():
    # bounded join: holding exit for a full billion-param compile could
    # outlive the pod's termination grace (SIGKILL mid-teardown); past
    # the bound we accept the daemon-thread teardown risk instead. The
    # stop flag bounds the common case to "finish the current target".
    timeout = float(flags.WARM_COMPILE_EXIT_JOIN_S.get())
    for wcm in list(_live_compilers):
        wcm._stop.set()
    deadline = time.monotonic() + timeout
    for wcm in list(_live_compilers):
        try:
            wcm.wait_idle(max(0.0, deadline - time.monotonic()))
        except Exception:
            pass


atexit.register(_shutdown_speculation)
