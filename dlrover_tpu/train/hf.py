"""Hugging Face (Flax) model interop for the elastic trainer.

Parity: the reference ships a drop-in HF Trainer integration
(``trainer/torch/flash_checkpoint/hf_trainer.py:59-393`` — a Trainer
subclass whose ``_save_checkpoint`` goes through flash checkpoint). The
TPU-native equivalent is thinner by design: any Flax model from
``transformers`` becomes an ``ElasticTrainer`` workload by deriving
FSDP-style partition specs for its (arbitrary) param pytree and wrapping
its forward in a causal-LM loss — checkpointing then works unchanged
because the engine is pytree-generic.

Usage::

    model = FlaxGPT2LMHeadModel(config, seed=0)
    adapter = HFCausalLMAdapter(model)
    trainer = ElasticTrainer(adapter.loss_fn,
                             adapter.param_specs(mesh), mesh, mc, tc)
    state = trainer.init_state(adapter.shard_params(mesh))
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

#: leaves smaller than this stay replicated — sharding tiny biases/norms
#: buys nothing and costs an all-gather each
MIN_SHARD_SIZE = 1 << 16


def derive_param_specs(params, n_shards: int, axis: str = "fsdp",
                       min_size: int = MIN_SHARD_SIZE):
    """FSDP-style specs for an arbitrary pytree: each big-enough leaf is
    sharded along its largest dimension divisible by ``n_shards``;
    everything else replicates. This is how ZeRO-3 partitions torch
    models it knows nothing about — here the choice is per-leaf static,
    so XLA still lays collectives optimally."""

    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        size = getattr(leaf, "size", 0)
        if n_shards <= 1 or len(shape) == 0 or size < min_size:
            return P()
        for dim in sorted(range(len(shape)), key=lambda i: -shape[i]):
            if shape[dim] % n_shards == 0:
                spec = [None] * len(shape)
                spec[dim] = axis
                return P(*spec)
        return P()

    return jax.tree.map(spec_for, params)


class HFCausalLMAdapter:
    """Wraps a ``transformers`` Flax causal-LM so ElasticTrainer can
    drive it: loss, param specs, and sharded placement.

    The forward runs deterministic (``train=False``): ElasticTrainer's
    loss signature carries no dropout rng, and LLM pretraining runs
    dropout-free anyway. A model config with nonzero dropout gets a
    loud warning at construction rather than silently-disabled
    regularization."""

    def __init__(self, model, pad_token_id: Optional[int] = None):
        self.model = model
        self.pad_token_id = pad_token_id
        cfg_dict = getattr(getattr(model, "config", None), "__dict__", {})
        drops = {
            k: v for k, v in cfg_dict.items()
            if ("drop" in k and isinstance(v, (int, float))
                and not isinstance(v, bool) and v > 0)
        }
        if drops:
            from dlrover_tpu.common.log import logger

            logger.warning(
                "HFCausalLMAdapter runs the model deterministic "
                "(train=False); configured dropout %s will NOT be applied "
                "— set the rates to 0 in the config to silence this",
                drops,
            )

    def loss_fn(self, params, tokens: jnp.ndarray) -> jnp.ndarray:
        """Next-token cross entropy over ``tokens`` (batch, seq) int32.
        Positions whose *target* is pad_token_id are masked out."""
        logits = self.model(tokens, params=params, train=False).logits
        logits = logits[:, :-1].astype(jnp.float32)
        targets = tokens[:, 1:]
        # logsumexp + gather keeps the extra activation at (batch, seq)
        # instead of materializing full (batch, seq, vocab) log-probs
        # (same form as models/llama.py _ce_sums)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        nll = logz - gold
        if self.pad_token_id is not None:
            mask = (targets != self.pad_token_id).astype(jnp.float32)
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.mean(nll)

    def param_specs(self, mesh, axis: str = "fsdp"):
        n = dict(mesh.shape).get(axis, 1)
        return derive_param_specs(self.model.params, n, axis=axis)

    def shard_params(self, mesh, axis: str = "fsdp"):
        """Place the model's (host) params onto the mesh under the
        derived specs."""
        from dlrover_tpu.parallel.sharding import shard_pytree

        return shard_pytree(
            mesh, self.param_specs(mesh, axis=axis), self.model.params
        )
