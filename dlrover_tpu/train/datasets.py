"""Concrete pretraining datasets for the elastic data pipeline.

The reference's data story is index-based sharding over user torch
datasets (`sharding_client` + `ElasticDataLoader`); the framework here
has the same sharding spine (`train/data.py`), but a user switching
from the reference still needs an actual high-throughput corpus reader
for LM pretraining. This module provides it TPU-natively:

- :class:`TokenFileDataset`: a memory-mapped flat binary of token ids
  (the nanoGPT/Megatron ``.bin`` convention — uint16/uint32, no
  framing), sliced into fixed-length sequences. ``np.memmap`` keeps
  the host RSS independent of corpus size and the page cache does the
  read-ahead; `__getitem__` is a zero-copy slice + dtype cast, so the
  loader feeds `prefetch_to_device` at memory bandwidth.
- :func:`pack_tokens` / :func:`pack_text_file`: corpus writers for the
  same format.

Composes with everything already here: `ElasticDistributedSampler`
(elastic epoch iteration), `ElasticDataLoader` (runtime-tunable batch
size), `ShardingClient` (master-issued shard ranges with exactly-once
resume), and `prefetch_to_device`.
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Optional

import numpy as np

__all__ = ["TokenFileDataset", "pack_tokens", "pack_text_file"]

_DTYPES = {"uint16": np.uint16, "uint32": np.uint32, "int32": np.int32}


class TokenFileDataset:
    """Fixed-length sequences out of a flat binary token file.

    ``sample i = tokens[i*stride : i*stride + seq_len]`` as int32 (what
    the model families take); ``stride`` defaults to ``seq_len``
    (non-overlapping). The LM families derive next-token targets by
    shifting internally, so samples are exactly ``seq_len`` long.
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        dtype: str = "uint16",
        stride: Optional[int] = None,
    ):
        if dtype not in _DTYPES:
            raise ValueError(
                f"dtype={dtype!r}: expected one of {sorted(_DTYPES)}"
            )
        self.path = path
        self.seq_len = int(seq_len)
        self.stride = int(stride or seq_len)
        if self.seq_len <= 0 or self.stride <= 0:
            raise ValueError("seq_len and stride must be positive")
        self._tokens = np.memmap(path, dtype=_DTYPES[dtype], mode="r")
        n_tok = len(self._tokens)
        self._n = max(0, (n_tok - self.seq_len) // self.stride + 1)

    @property
    def n_tokens(self) -> int:
        return len(self._tokens)

    def validate_vocab(self, vocab_size: int, sample: int = 1 << 20):
        """Raise if any of the first ``sample`` tokens is >= vocab_size.

        An out-of-range token id reaches the embedding gather as an
        out-of-bounds index and trains on garbage (nan loss at best,
        silent corruption at worst); a truncated scan catches the common
        corpus/tokenizer-vs-model mismatch for the cost of one page-in."""
        head = self._tokens[: min(sample, len(self._tokens))]
        if not len(head):
            return
        lo, hi = int(head.min()), int(head.max())
        if hi >= vocab_size or lo < 0:  # signed dtypes can go negative
            raise ValueError(
                f"corpus {self.path} has token ids in [{lo}, {hi}], "
                f"outside the model vocab [0, {vocab_size}) (checked first "
                f"{len(head)} tokens): wrong tokenizer or wrong --data-dtype?"
            )

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int) -> np.ndarray:
        if not 0 <= i < self._n:
            raise IndexError(i)
        off = i * self.stride
        return np.asarray(
            self._tokens[off:off + self.seq_len], dtype=np.int32
        )


def pack_tokens(
    path: str, tokens: Iterable[int], dtype: str = "uint16"
) -> int:
    """Append token ids to ``path`` in the flat-binary format; returns
    the number of tokens written. Streams in chunks so corpora larger
    than RAM pack fine."""
    if dtype not in _DTYPES:
        raise ValueError(f"dtype={dtype!r}")
    np_dtype = _DTYPES[dtype]
    limit = np.iinfo(np_dtype).max
    written = 0
    buf = []
    with open(path, "ab") as f:
        for t in tokens:
            if not 0 <= t <= limit:
                raise ValueError(
                    f"token {t} out of range for {dtype} (max {limit})"
                )
            buf.append(t)
            if len(buf) >= 1 << 20:
                np.asarray(buf, dtype=np_dtype).tofile(f)
                written += len(buf)
                buf.clear()
        if buf:
            np.asarray(buf, dtype=np_dtype).tofile(f)
            written += len(buf)
    return written


def pack_text_file(
    text_path: str,
    bin_path: str,
    tokenize: Optional[Callable[[str], Iterable[int]]] = None,
    dtype: str = "uint16",
    chunk_bytes: int = 1 << 20,
) -> int:
    """Tokenize a text file into the binary format, streaming in
    chunks extended to the next newline (a subword tokenizer applied to
    a mid-word split produces different ids than contiguous text;
    newline boundaries are far more stable, though tokenizers that
    merge runs of newlines can still differ by a token per boundary).
    Memory stays bounded: a "line" longer than ``chunk_bytes`` is split
    mid-line rather than buffered whole. Default tokenizer is raw UTF-8
    bytes (vocab 256) — a real run passes e.g. a ``transformers``
    tokenizer's encode.

    Atomicity: output goes to ``bin_path + '.tmp'`` and replaces
    ``bin_path`` only on success, so a failed re-pack never destroys an
    existing corpus and a partial pack is never mistaken for a complete
    one (``pack_tokens`` itself appends, for multi-file packing)."""
    tmp_path = bin_path + ".tmp"
    open(tmp_path, "wb").close()  # truncate the temp
    total = 0

    def flush(text: str) -> int:
        ids = (
            list(text.encode("utf-8")) if tokenize is None
            else list(tokenize(text))
        )
        return pack_tokens(tmp_path, ids, dtype=dtype)

    with open(text_path, "r", encoding="utf-8", errors="replace") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            if not chunk.endswith("\n"):
                # extend to the next newline for tokenizer stability,
                # but never past another chunk_bytes (single-huge-line
                # corpora must not buffer unboundedly)
                tail = f.readline(chunk_bytes)
                chunk += tail
            total += flush(chunk)
    os.replace(tmp_path, bin_path)
    return total
