"""Worker-process bootstrap: the in-training-process face of the framework.

A user script starts with::

    import dlrover_tpu.train as dtrain
    ctx = dtrain.init()          # jax.distributed up, master client connected

which (a) reads the env the elastic agent injected, (b) runs
``jax.distributed.initialize`` against the rendezvous-elected coordinator,
and (c) connects the master client for sharding/steps/checkpoint RPCs.

Parity: the reference reaches this point via torchelastic env + its
trainer-SDK singletons; there is no single ``init`` — this is the
TPU-native consolidation.
"""

from __future__ import annotations

import atexit
import os
import time
from dataclasses import dataclass
from typing import Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import logger


@dataclass
class WorkerEnv:
    job_name: str = "local"
    master_addr: str = ""
    node_id: int = 0
    node_rank: int = 0
    node_num: int = 1
    coordinator_addr: str = ""
    process_id: int = 0
    num_processes: int = 1
    restart_count: int = 0
    accelerator: str = "tpu"
    local_rank: int = 0
    # distinct TPU slices in the current world (agent-injected; sizes
    # the multislice mesh's DCN axis, changing across slice resizes)
    num_slices: int = 1

    @classmethod
    def from_env(cls) -> "WorkerEnv":
        e = os.environ
        return cls(
            job_name=e.get(NodeEnv.JOB_NAME, "local"),
            master_addr=e.get(NodeEnv.MASTER_ADDR, ""),
            node_id=int(e.get(NodeEnv.NODE_ID, "0")),
            node_rank=int(e.get(NodeEnv.NODE_RANK, "0")),
            node_num=int(e.get(NodeEnv.NODE_NUM, "1")),
            coordinator_addr=e.get(NodeEnv.COORDINATOR_ADDR, ""),
            process_id=int(e.get(NodeEnv.PROCESS_ID, "0")),
            num_processes=int(e.get(NodeEnv.NUM_PROCESSES, "1")),
            restart_count=int(e.get(NodeEnv.RESTART_COUNT, "0")),
            accelerator=e.get("DLROVER_TPU_ACCELERATOR", "tpu"),
            local_rank=int(e.get("DLROVER_TPU_LOCAL_RANK", "0")),
            num_slices=int(e.get("DLROVER_TPU_NUM_SLICES", "1") or 1),
        )


class WorkerContext:
    """What a training process holds after ``init()``."""

    def __init__(self, env: WorkerEnv, client=None):
        self.env = env
        self.client = client
        self._last_reported_step = 0
        self._last_report_ts = 0.0
        self.step_report_interval = 15.0
        # input-wait seconds already shipped with earlier digests (the
        # spine counter is cumulative; reports carry the delta)
        self._input_wait_mark = 0.0
        # whether this worker has ever shipped a comm_links split with
        # a dcn row: after a resize REMOVES the slow link (slice loss →
        # single-slice world) one more report must replace the master's
        # stale dcn row, or the goodput report advertises slow-link
        # load that no longer exists
        self._sent_comm_links = False
        # drained-but-unsent digest window (failed report): merged into
        # the next report so the master's ledger never loses it
        self._unreported_digest = None

    @property
    def process_id(self) -> int:
        return self.env.process_id

    @property
    def num_processes(self) -> int:
        return self.env.num_processes

    @property
    def is_chief(self) -> bool:
        return self.env.process_id == 0

    @property
    def restart_count(self) -> int:
        return self.env.restart_count

    def report_model_info(
        self,
        param_count: int = 0,
        flops_per_step: float = 0.0,
        batch_size: int = 0,
        seq_len: int = 0,
        hidden_dim: int = 0,
        n_layers: int = 0,
        n_heads: int = 0,
        remat: bool = True,
    ):
        """Describe the model to the master (chief only): feeds the
        hyperparam strategy's activation-memory sizing and the MFU
        accounting (reference report_model_info)."""
        if self.client is None or not self.is_chief:
            return
        try:
            self.client.report_model_info(
                param_count=param_count,
                flops_per_step=flops_per_step,
                batch_size=batch_size,
                seq_len=seq_len,
                hidden_dim=hidden_dim,
                n_layers=n_layers,
                n_heads=n_heads,
                remat=remat,
            )
        except Exception as e:
            logger.warning("model info report failed: %s", e)

    def report_resize_breakdown(
        self,
        rendezvous_s: float = 0.0,
        compile_s: float = 0.0,
        state_transfer_s: float = 0.0,
        restore_tier: str = "",
    ):
        """Per-resize downtime breakdown for the master's goodput
        ledger: what this membership change spent on rendezvous vs the
        step rebuild vs moving the train state (live reshard or
        checkpoint restore), and — ``restore_tier`` — which tier the
        state came back through (live | shm | disk | object), so the
        goodput report separates tier-0 fast restarts from real
        node-loss recoveries. Chief-only, like model info — every
        worker sees the same resize."""
        if self.client is None or not self.is_chief:
            return
        try:
            self.client.report_resize_breakdown(
                rendezvous_s=rendezvous_s,
                compile_s=compile_s,
                state_transfer_s=state_transfer_s,
                restore_tier=restore_tier,
            )
        except Exception as e:
            logger.warning("resize breakdown report failed: %s", e)

    def poll_speculation_hint(self, trainer) -> Optional[dict]:
        """Fetch the goodput planner's intended-next-world hint from
        the membership poll and arm the trainer's warm compiler with it
        (brain/planner.py; docs/design/brain_planner.md). The master
        plans in NODES; the hint scales by this process's local device
        count, so the trainer speculates the exact DEVICE world the
        planner-directed resize will seat. A missing/empty hint clears
        nothing armed and returns None — pre-planner masters and
        version skew are harmless (serde drops the unknown field)."""
        if self.client is None:
            return None
        try:
            hint = self.client.speculation_hint()
        except Exception as e:
            logger.debug("speculation-hint poll failed: %s", e)
            return None
        if not hint:
            return None
        world_nodes = int(hint.get("world", 0) or 0)
        if world_nodes <= 0:
            return None
        import jax

        devices_per_node = max(1, jax.local_device_count())
        trainer.set_speculation_hint(
            world_nodes * devices_per_node,
            n_slices=int(hint.get("n_slices", 0) or 0) or None,
        )
        return hint

    def report_step(self, step: int, force: bool = False, digest=None):
        """Throttled global-step report feeding the master's SpeedMonitor.

        ``digest``: a :class:`~dlrover_tpu.observability.digest.
        StepTimeDigest` the caller folds per-step wall times into; the
        report DRAINS one window from it (count/mean/p50/p95/max) and
        attaches the worker's input-wait seconds since the last report
        (trace spine ``input_wait`` counter) — per-rank step-time
        distributions ride the existing throttled RPC, so the master's
        straggler detector and attribution cost no extra chatter."""
        if self.client is None:
            return
        now = time.time()
        if not force and now - self._last_report_ts < self.step_report_interval:
            return
        payload = None
        if digest is not None:
            try:
                payload = digest.snapshot_and_reset()
            except Exception as e:
                logger.warning("step digest drain failed: %s", e)
                payload = None
        if payload:
            from dlrover_tpu.observability import digest as digest_mod
            from dlrover_tpu.observability import trace

            total_iw = trace.trace_ring.kind_seconds().get("input_wait", 0.0)
            payload["input_wait_s"] = round(
                max(0.0, total_iw - self._input_wait_mark), 6
            )
            self._input_wait_mark = total_iw
            digest_mod.set_last_window(payload)  # worker /metrics gauge
        if self._unreported_digest:
            # a window whose report failed (master relaunch gap) rides
            # the next attempt instead of vanishing from the
            # attribution's productive/input-wait ledgers
            from dlrover_tpu.observability.digest import merge_windows

            payload = merge_windows(self._unreported_digest, payload)
            self._unreported_digest = None
        # per-link comm bytes (profiler/comm.py): the analytic ici/dcn
        # split of this worker's program, riding the same throttled RPC
        # — only attached when a slow link exists (a dcn row), so
        # single-slice jobs add nothing to the wire. One FINAL split is
        # sent after a resize removes the slow link, replacing the
        # master's now-stale dcn row (record_comm_links is
        # last-report-wins per rank).
        comm_links = None
        overlap_ratio = -1.0
        try:
            from dlrover_tpu.profiler.comm import comm_ledger

            links = comm_ledger.link_bytes()
            if links.get("dcn"):
                comm_links = links
                self._sent_comm_links = True
                # the schedule's DCN overlap share rides with the dcn
                # row it qualifies (−1.0 = program reported no split)
                overlap_ratio = comm_ledger.overlap_ratio()
            elif self._sent_comm_links:
                # the {"ici": 0} floor keeps the clearing report
                # truthy through serde (an empty dict would be
                # indistinguishable from "no split attached")
                comm_links = links or {"ici": 0}
                self._sent_comm_links = False
        except Exception:
            comm_links = None
        try:
            try:
                self.client.report_global_step(
                    step, digest=payload, comm_links=comm_links,
                    overlap_ratio=overlap_ratio,
                )
            except TypeError:
                # link/overlap-unaware client (older stubs): retry
                # without the newest field, then plain
                try:
                    self.client.report_global_step(
                        step, digest=payload, comm_links=comm_links
                    )
                except TypeError:
                    self.client.report_global_step(step, digest=payload)
            self._last_reported_step = step
            self._last_report_ts = now
        except Exception as e:
            self._unreported_digest = payload
            logger.warning("step report failed: %s", e)


_context: Optional[WorkerContext] = None


def init(
    connect_master: bool = True,
    init_distributed: bool = True,
    local_device_count: Optional[int] = None,
) -> WorkerContext:
    """Bootstrap this training process; idempotent."""
    global _context
    if _context is not None:
        return _context
    env = WorkerEnv.from_env()

    # hang diagnosis: register the SIGUSR2 all-thread stack dumper the
    # agent's HangDumper triggers (profiler/hang_dump.py)
    stack_dir = os.environ.get("DLROVER_TPU_STACK_DIR", "")
    if stack_dir:
        try:
            from dlrover_tpu.profiler.hang_dump import (
                install_stack_dump_handler,
            )

            install_stack_dump_handler(stack_dir)
        except Exception:
            logger.exception("stack-dump handler install failed; continuing")
    from dlrover_tpu.common import flags as _flags

    if _flags.PY_TRACING.get() or _flags.TRACE.get():
        # GC pauses + user spans into the host timeline; the trace
        # spine needs the same emitters (gc_pause/input_wait spans), so
        # either flag turns the tracer on (typed registry, was a raw
        # DLROVER_TPU_PY_TRACING env read)
        from dlrover_tpu.profiler.py_tracing import py_tracer

        py_tracer.start()
    if _flags.TRACE.get():
        # dump this process's span ring at exit so the job-timeline CLI
        # (profiler/analysis.py) can merge every rank + the master into
        # one perfetto-loadable trace
        from dlrover_tpu.observability import trace as _trace

        _trace.dump_at_exit(
            role="worker", node_id=env.node_id, process_id=env.process_id
        )
    try:
        sampler_ms = float(
            os.environ.get("DLROVER_TPU_STACK_SAMPLER_MS", "0") or 0
        )
    except ValueError:
        logger.warning("DLROVER_TPU_STACK_SAMPLER_MS not numeric; ignored")
        sampler_ms = 0.0
    if sampler_ms > 0:
        # in-process hotspot sampler (reference stack_util.cc); dumps the
        # weighted stack trie at interpreter exit
        from dlrover_tpu.profiler.stack_sampler import StackSampler

        _sampler = StackSampler(interval=sampler_ms / 1000.0).start()
        out = os.environ.get(
            "DLROVER_TPU_STACK_SAMPLER_OUT",
            f"/tmp/dlrover_tpu_hotspots-{os.getpid()}.txt",
        )

        def _dump_hotspots():
            _sampler.stop()
            try:
                _sampler.dump(out)
            except OSError:
                logger.warning("hotspot dump to %s failed", out)

        atexit.register(_dump_hotspots)

    import jax

    will_init_distributed = bool(
        init_distributed and env.num_processes > 1 and env.coordinator_addr
    )
    if env.accelerator == "cpu":
        # Test mode: virtual CPU devices + gloo cross-process collectives.
        # (The axon image overrides JAX_PLATFORMS; config update wins.)
        if local_device_count:
            import re

            flags = os.environ.get("XLA_FLAGS", "")
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", "", flags
            ).strip()
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_device_count}"
            ).strip()
        jax.config.update("jax_platforms", "cpu")
        if will_init_distributed:
            # gloo needs the distributed client: configuring it in a
            # single-process run makes CPU backend init itself fail
            # (make_gloo_tcp_collectives(distributed_client=None))
            jax.config.update(
                "jax_cpu_collectives_implementation", "gloo"
            )

    # warm-path elasticity: point JAX's persistent compilation cache at
    # the agent-injected dir (train/warm_compile.py) so a restarted
    # worker deserializes the step executable instead of recompiling —
    # the resize-downtime twin of the flash-checkpoint restore
    from dlrover_tpu.train.warm_compile import enable_persistent_cache

    enable_persistent_cache()

    if will_init_distributed:
        logger.info(
            "process %s/%s: jax.distributed.initialize(coordinator=%s)",
            env.process_id,
            env.num_processes,
            env.coordinator_addr,
        )
        init_timeout = int(
            os.environ.get("DLROVER_TPU_DIST_INIT_TIMEOUT", "120")
        )
        jax.distributed.initialize(
            coordinator_address=env.coordinator_addr,
            num_processes=env.num_processes,
            process_id=env.process_id,
            initialization_timeout=init_timeout,
        )

    client = None
    if connect_master and env.master_addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(env.master_addr, env.node_id)
        MasterClient.reset_singleton(client)

    _context = WorkerContext(env, client)
    atexit.register(_shutdown)
    return _context


def get_context() -> Optional[WorkerContext]:
    return _context


def _shutdown():
    global _context
    _context = None
