"""Live state resharding: old-mesh → new-mesh without the checkpoint
round-trip.

PR 2 (train/warm_compile.py) made the *compile* half of an elastic
resize warm; this module attacks the *state* half. When a membership
change is absorbed in-process (``ElasticTrainer.remesh()`` with the old
state still resident in HBM), the post-resize restore used to pay a
full checkpoint round-trip anyway: stage to shm / read from storage,
reassemble every leaf as a full host array, re-place it with
``jax.make_array_from_callback`` — downtime scaling with model bytes
even though every byte already sits on surviving devices. ElasWave
(arXiv:2510.00606) and Orbax's distributed restore (arXiv:2605.23066)
both show elastic-native systems hiding membership changes with live
migration instead; this is the TPU-native version of that move.

The transfer plan:

1. **Target shardings from the step-signature machinery.** The trainer
   already derives mesh-independent avatars (shape/dtype/PartitionSpec
   per leaf) for warm compilation; binding each avatar's spec to the
   *new* mesh yields the exact ``NamedSharding`` pytree the post-resize
   step will demand — no reference state, no checkpoint metadata.
2. **Batched ``jax.device_put``.** One call over the whole state pytree
   with the sharding pytree as target: XLA/the runtime schedules all
   leaf transfers together and handles the cross-device (ICI — and on
   jax versions that support it, cross-host) moves device-to-device.
3. **Fallback ladder.** Where the running jax rejects a direct
   cross-mesh transfer, fall back leaf-wise (salvaging the leaves that
   do transfer directly), and per-leaf to a host-gather bridge
   (device_get the full leaf — only possible when it is fully
   addressable — then re-place against the new sharding). If even the
   bridge cannot move a leaf, :class:`LiveReshardError` propagates and
   the caller falls back to the checkpoint restore path, which remains
   the restart-based resize path anyway.

Everything is behind the ``DLROVER_TPU_LIVE_RESHARD=0`` kill-switch
(common/flags.py): off, ``remesh()`` ignores the passed state and the
caller restores through the checkpoint engine exactly as before.

Per-resize downtime lands in :data:`resize_ledger` broken into
rendezvous / compile / state-transfer seconds, exported as Prometheus
gauges on the worker ``/metrics`` endpoint (profiler/comm.py) and
reported to the master's SpeedMonitor for goodput attribution.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

PyTree = Any

__all__ = [
    "live_reshard_enabled",
    "LiveReshardError",
    "state_shardings",
    "state_targets",
    "stage_transfer_plan",
    "transfer_state",
    "ResizeLedger",
    "resize_ledger",
    "prometheus_lines",
]


def live_reshard_enabled() -> bool:
    """Kill-switch, read at call time so tests/benches can flip it."""
    return flags.LIVE_RESHARD.get()


class LiveReshardError(RuntimeError):
    """No rung of the transfer ladder could move some leaf; the caller
    must fall back to the checkpoint restore path."""


def state_shardings(avatar_tree: PyTree, mesh, world=None) -> PyTree:
    """Bind each avatar's PartitionSpec to ``mesh``: the NamedSharding
    pytree the post-resize step expects its state in. ``avatar_tree``
    is the trainer's ``_state_avatar`` (or any tree whose leaves carry
    a ``.spec``) — the same machinery ``lower_step`` compiles against,
    so transfer targets and executable signature can never disagree.

    ``world`` (a :class:`~dlrover_tpu.common.world.WorldDescriptor`):
    when given, the mesh is CHECKED against it before any sharding is
    derived — the transfer target and the AOT executable then describe
    the same world through one checked type instead of trusting that
    two call sites re-derived the same shape."""
    import jax
    from jax.sharding import NamedSharding

    if world is not None:
        world.check_mesh(mesh)
    return jax.tree.map(
        lambda av: NamedSharding(mesh, av.spec), avatar_tree
    )


def state_targets(avatar_tree: PyTree, mesh, world=None) -> PyTree:
    """``ShapeDtypeStruct`` (with sharding) pytree for ``mesh`` — the
    restore-target form of :func:`state_shardings`, for callers driving
    the checkpoint engine's placed restore against the same avatars
    (bench's shm-round-trip leg, parity tests). ``world``: optional
    WorldDescriptor checked against ``mesh`` exactly as in
    :func:`state_shardings`."""
    import jax
    from jax.sharding import NamedSharding

    if world is not None:
        world.check_mesh(mesh)
    return jax.tree.map(
        lambda av: jax.ShapeDtypeStruct(
            av.shape, av.dtype, sharding=NamedSharding(mesh, av.spec)
        ),
        avatar_tree,
    )


def _bridge_leaf(leaf, sharding):
    """Host-gather bridge for one leaf: d2h the full array, re-place it
    under the new sharding. Only possible when every shard of the leaf
    is addressable from this process — a multi-host leaf that the
    direct transfer rejected cannot be gathered here and must take the
    checkpoint path."""
    import jax
    import numpy as np

    if not getattr(leaf, "is_fully_addressable", True):
        raise LiveReshardError(
            "leaf is not fully addressable from this process; the host "
            "bridge cannot gather it (checkpoint restore required)"
        )
    host = np.asarray(jax.device_get(leaf))
    # the alias is safe — and the point: `host` is a private snapshot
    # whose only consumer is the array placed right here (the caller
    # drops the source leaf after transfer), and copying would double
    # peak host RAM for the leaf. Nothing rewrites the buffer.
    if host.ndim == 0:
        return jax.device_put(host, sharding)  # graftlint: disable=JG007
    return jax.make_array_from_callback(  # graftlint: disable=JG007
        host.shape, sharding, lambda idx: np.ascontiguousarray(host[idx])
    )


def stage_transfer_plan(old_world, new_world) -> Optional[Dict[str, Any]]:
    """Per-stage movement plan for a pp-aware resize, derived from the
    same :class:`~dlrover_tpu.common.world.WorldDescriptor` pair that
    keys the AOT executable — so what moves and what signs can never
    disagree. Returns ``None`` when neither world pipelines (the plain
    dp/fsdp transfer needs no stage bookkeeping). Kinds:

    - ``dp_within_stage``: stage count unchanged — each stage's data
      axes shrink/grow in place, layer slabs never cross stages;
    - ``stage_rebalance``: stage count changed — layer slabs re-slab
      (new stage ``s'`` takes the old-stage fraction
      ``[s'*old_pp/new_pp, (s'+1)*old_pp/new_pp)``);

    plus, per new stage, its slice placement before/after (from the
    canonical ``stage_map``) — ``cross_slice`` marks a stage whose
    bytes must ride DCN."""
    if old_world is None or new_world is None:
        return None
    old_pp, new_pp = old_world.pp, new_world.pp
    if old_pp <= 1 and new_pp <= 1:
        return None
    kind = "dp_within_stage" if old_pp == new_pp else "stage_rebalance"
    old_map, new_map = old_world.stage_map(), new_world.stage_map()
    stages = []
    for s in range(new_pp):
        # old stages whose layer slab lands (fully or partly) on s:
        # the old-stage fraction [s/new_pp, (s+1)/new_pp) of the stack
        lo = s * old_pp // new_pp
        hi = -(-(s + 1) * old_pp // new_pp)  # ceil
        src = tuple(range(lo, max(lo + 1, hi)))
        src_slices = sorted({sl for o in src if o < old_pp
                             for sl in old_map[o]})
        dst_slices = list(new_map[s])
        stages.append({
            "stage": s,
            "src_stages": list(src),
            "src_slices": src_slices,
            "dst_slices": dst_slices,
            "cross_slice": bool(src_slices) and src_slices != dst_slices,
        })
    return {
        "kind": kind,
        "old_pp": old_pp,
        "new_pp": new_pp,
        "from": old_world.spec,
        "to": new_world.spec,
        "stages": stages,
    }


def transfer_state(
    state: PyTree,
    shardings: PyTree,
    *,
    block: bool = True,
    old_world=None,
    new_world=None,
) -> tuple:
    """Move ``state`` onto the shardings' mesh device-to-device.

    Returns ``(new_state, info)``; ``info`` records the path taken
    (``direct`` | ``leafwise`` | ``bridge``), per-rung leaf counts and
    the transfer seconds. ``block=True`` waits for the transfers so the
    recorded seconds are the real cost (callers on a hot path can defer
    the sync to their first step instead).

    Raises :class:`LiveReshardError` when some leaf could not be moved
    by any rung — state is untouched and the caller should restore
    through the checkpoint engine.
    """
    import jax

    from dlrover_tpu.observability import trace

    t0 = time.perf_counter()
    m0 = time.monotonic()
    info: Dict[str, Any] = {"path": "direct", "leaves_bridged": 0}
    plan = stage_transfer_plan(old_world, new_world)
    if plan is not None:
        info["stage_plan"] = plan
    try:
        new_state = jax.device_put(state, shardings)
    except Exception as e:
        logger.info(
            "batched cross-mesh device_put unsupported here (%s); "
            "falling back leaf-wise", str(e)[:200],
        )
        new_state, bridged = _transfer_leafwise(state, shardings)
        info["path"] = "bridge" if bridged else "leafwise"
        info["leaves_bridged"] = bridged
    if block:
        jax.block_until_ready(new_state)
    info["transfer_s"] = time.perf_counter() - t0
    # trace spine: the state half of a live resize is a state_transfer
    # span (the resize ledger keeps the per-event breakdown; the spine
    # is what merges into the job timeline)
    trace.record(
        "state_transfer", "live_reshard.transfer", m0,
        info["transfer_s"], path=info["path"],
        leaves_bridged=info["leaves_bridged"],
    )
    return new_state, info


def _transfer_leafwise(state: PyTree, shardings: PyTree):
    """Rung 2+3: per-leaf direct transfer, host bridge for the leaves
    the runtime rejects. Returns (new_state, n_bridged)."""
    import jax

    flat_s, treedef = jax.tree_util.tree_flatten(state)
    flat_sh = treedef.flatten_up_to(shardings)
    out: List[Any] = []
    bridged = 0
    for leaf, sh in zip(flat_s, flat_sh):
        try:
            out.append(jax.device_put(leaf, sh))
        except Exception:
            out.append(_bridge_leaf(leaf, sh))
            bridged += 1
    return jax.tree_util.tree_unflatten(treedef, out), bridged


# ---------------------------------------------------------------------------
# Per-resize downtime breakdown ledger
# ---------------------------------------------------------------------------


class ResizeLedger:
    """Downtime breakdown per resize event: rendezvous / compile /
    state-transfer seconds, with the transfer path taken.

    In-memory, process-wide (one trainer per process is the normal
    shape). ``prometheus_lines()`` exports the last event's phases as
    gauges plus cumulative per-phase totals — the fleet-level signal
    for whether resizes are landing warm on BOTH halves (executable
    AND state)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[dict] = []

    def record(
        self,
        world_from: int,
        world_to: int,
        *,
        rendezvous_s: float = 0.0,
        compile_s: float = 0.0,
        state_transfer_s: float = 0.0,
        path: str = "",
        restore_tier: str = "",
    ) -> dict:
        """``path``: ``direct`` | ``leafwise`` | ``bridge`` (live
        transfer rung) or ``checkpoint`` (the round-trip fallback).
        ``restore_tier``: where the state that ended this downtime came
        from — ``live`` (device-to-device, no restore) or the checkpoint
        engine's tier (``shm`` | ``disk`` | ``object``) — so the goodput
        ledger can separate tier-0 fast restarts from the slower
        disk/object recoveries."""
        event = {
            "world_from": int(world_from),
            "world_to": int(world_to),
            "rendezvous_s": round(float(rendezvous_s), 6),
            "compile_s": round(float(compile_s), 6),
            "state_transfer_s": round(float(state_transfer_s), 6),
            "path": path,
            "restore_tier": restore_tier,
            "ts": time.time(),
        }
        with self._lock:
            self._events.append(event)
        return event

    def events(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def last(self) -> Optional[dict]:
        with self._lock:
            return dict(self._events[-1]) if self._events else None

    def clear(self):
        with self._lock:
            self._events.clear()

    def prometheus_lines(self) -> List[str]:
        lines = [
            "# TYPE dlrover_tpu_resize_seconds gauge",
            "# TYPE dlrover_tpu_resize_seconds_total gauge",
            "# TYPE dlrover_tpu_resize_events gauge",
        ]
        with self._lock:
            events = [dict(e) for e in self._events]
        if not events:
            return lines
        last = events[-1]
        label_base = (
            f'world_from="{last["world_from"]}",'
            f'world_to="{last["world_to"]}",path="{last["path"]}"'
        )
        totals = {"rendezvous": 0.0, "compile": 0.0, "state_transfer": 0.0}
        for e in events:
            for phase in totals:
                totals[phase] += e[f"{phase}_s"]
        for phase in ("rendezvous", "compile", "state_transfer"):
            lines.append(
                f'dlrover_tpu_resize_seconds{{phase="{phase}",'
                f"{label_base}}} {last[f'{phase}_s']:.6f}"
            )
            lines.append(
                f'dlrover_tpu_resize_seconds_total{{phase="{phase}"}} '
                f"{totals[phase]:.6f}"
            )
        lines.append(f"dlrover_tpu_resize_events {len(events)}")
        return lines


#: process-wide ledger (trainer records; /metrics and bench read)
resize_ledger = ResizeLedger()


def prometheus_lines() -> List[str]:
    """Module-level convenience for the metrics server."""
    return resize_ledger.prometheus_lines()
