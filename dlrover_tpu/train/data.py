"""Worker-side elastic data plumbing.

Parity: reference ``elastic_agent/sharding/client.py`` (ShardingClient /
IndexShardingClient) and ``trainer/torch/elastic/sampler.py``
(ElasticDistributedSampler). Re-designed for SPMD: under ``pjit`` every
process must execute the same jitted steps in lockstep, so dynamic shard
dispatch is **chief-driven**: process 0 fetches tasks from the master and
broadcasts them to all processes (one tiny collective per shard), keeping
collective schedules identical across the world.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import logger
from dlrover_tpu.common.messages import DatasetShardParams, Task


def _broadcast_tuple(values: Tuple[int, ...], is_source: bool) -> Tuple[int, ...]:
    """Broadcast small ints from process 0 to all (no-op single process)."""
    import jax

    if jax.process_count() == 1:
        return values
    from jax.experimental import multihost_utils

    arr = np.array(values, dtype=np.int64)
    out = multihost_utils.broadcast_one_to_all(arr, is_source=is_source)
    return tuple(int(v) for v in np.asarray(out))


class ShardingClient:
    """Lockstep-safe dynamic shard consumption for SPMD workers.

    The chief's master traffic runs the batched lease protocol by
    default (docs/design/data_plane.md): ``lease_shards`` prefetches
    ``lease_count`` shards under one per-worker lease per RPC and the
    SAME call acks the previous batch's completions, so the data plane
    costs ~1/(2·lease_count) of the per-task ``get_task``+``report``
    protocol at fleet scale. The lease renews via the agent's folded
    WorkerReport (zero extra steady-state RPCs); if this worker dies,
    lease expiry re-enqueues its undone shards at-least-once and the
    fence keeps its zombie reports from double-counting.
    ``lease_count=0`` (or an old master that does not know the RPC)
    falls back to the legacy one-task-per-RPC path."""

    def __init__(
        self,
        dataset_name: str,
        master_client=None,
        lease_count: Optional[int] = None,
        idle_poll_s: Optional[float] = None,
    ):
        import jax

        from dlrover_tpu.common import flags

        self.dataset_name = dataset_name
        self._client = master_client
        self._is_chief = jax.process_index() == 0
        self._current_task: Optional[Task] = None
        self._lock = threading.Lock()
        self._lease_count = int(
            lease_count if lease_count is not None
            else flags.SHARD_LEASE_COUNT.get()
        )
        self._lease_supported = True
        self._lease_epoch = -1
        self._prefetched: List[Task] = []
        self._done_ids: List[int] = []
        #: fixed cadence for the idle (todo-drained, shards in flight
        #: elsewhere) poll; None = the shared jittered growing schedule
        self._idle_poll_s = idle_poll_s

    def register_dataset(
        self,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "text",
    ):
        if self._is_chief and self._client is not None:
            self._client.report_dataset_shard_params(
                DatasetShardParams(
                    dataset_name=self.dataset_name,
                    dataset_size=dataset_size,
                    shard_size=shard_size,
                    num_epochs=num_epochs,
                    shuffle=shuffle,
                    storage_type=storage_type,
                )
            )

    # -- leased prefetch (chief only) ---------------------------------------

    def _lease(self, count: int, failed_ids=()) -> Optional[object]:
        """One lease RPC: pending completions + up to ``count`` fresh
        shards. Returns None when the master predates the protocol
        (the caller falls back to per-task dispatch)."""
        from dlrover_tpu.common.messages import ShardLeaseResponse

        done, self._done_ids = self._done_ids, []
        try:
            resp = self._client.lease_shards(
                self.dataset_name,
                count,
                done_ids=done,
                failed_ids=list(failed_ids),
                lease_epoch=self._lease_epoch,
            )
        except Exception:
            # the RPC (and its whole retry budget) failed: the
            # completions are NOT lost — they ride the next call.
            # Dropping them would leave the shards in the master's
            # doing set until lease expiry and force an avoidable
            # re-delivery of up to a full batch.
            self._done_ids = done + self._done_ids
            raise
        if not isinstance(resp, ShardLeaseResponse):
            # version skew: an old master answers the unknown message
            # with a SimpleResponse — switch to the legacy protocol and
            # re-report the completions through it
            logger.warning(
                "master does not support lease_shards; falling back to "
                "per-task shard dispatch"
            )
            self._lease_supported = False
            for tid in done:
                self._client.report_task_result(self.dataset_name, tid, True)
            for tid in failed_ids:
                self._client.report_task_result(self.dataset_name, tid, False)
            return None
        # done ids the master did NOT ack were fenced off (this lease
        # expired and the shards were re-issued): drop them — the new
        # holder's completion is the one that counts
        if resp.lease_epoch >= 0:
            self._lease_epoch = resp.lease_epoch
        return resp

    def _fetch_leased(self) -> Task:
        """Pop the next prefetched shard, leasing the next batch when
        the queue runs dry. An IDLE grant (todo drained but shards
        still in flight on other workers) is NOT end-of-data: a death
        elsewhere will re-enqueue them, and ending the epoch here
        would silently lose those records — the chief polls (jittered,
        growing) until the master says ``exhausted``. Each poll also
        flushes any pending completions, so the final batch's acks
        never strand."""
        if self._prefetched:
            return self._prefetched.pop(0)
        delays = None
        while True:
            resp = self._lease(self._lease_count)
            if resp is None:
                return self._client.get_task(self.dataset_name)
            self._prefetched.extend(resp.tasks)
            if self._prefetched:
                return self._prefetched.pop(0)
            if resp.exhausted and not self._done_ids:
                return Task()  # epoch truly complete, everything acked
            if resp.exhausted:
                continue  # one more call flushes the final completions
            # idle: wait for a re-enqueue (or completion) elsewhere
            if self._idle_poll_s is not None:
                time.sleep(self._idle_poll_s)
            else:
                if delays is None:
                    from dlrover_tpu.rpc import policy as rpc_policy

                    delays = rpc_policy.poll_intervals()
                time.sleep(next(delays))

    def fetch_task(self) -> Optional[Task]:
        """Chief fetches; everyone receives the same task (or None at end)."""
        task_tuple: Tuple[int, ...]
        if self._is_chief:
            if self._client is None:
                task = Task()
            elif self._lease_count > 0 and self._lease_supported:
                task = self._fetch_leased()
            else:
                task = self._client.get_task(self.dataset_name)
            task_tuple = (
                task.task_id,
                task.shard_start,
                task.shard_end,
                task.epoch,
            )
        else:
            task_tuple = (-1, 0, 0, 0)
        task_tuple = _broadcast_tuple(task_tuple, is_source=self._is_chief)
        task_id, start, end, epoch = task_tuple
        if task_id < 0:
            self._current_task = None
            return None
        self._current_task = Task(
            task_id=task_id,
            dataset_name=self.dataset_name,
            shard_start=start,
            shard_end=end,
            epoch=epoch,
        )
        return self._current_task

    def report_task_done(self, success: bool = True):
        if (
            self._is_chief
            and self._client is not None
            and self._current_task is not None
        ):
            if self._lease_count > 0 and self._lease_supported:
                if success:
                    # completions batch up and ride the NEXT lease call
                    self._done_ids.append(self._current_task.task_id)
                else:
                    # failures flush immediately so the master requeues
                    # the shard for someone else without waiting a TTL
                    self._lease(0, failed_ids=[self._current_task.task_id])
            else:
                self._client.report_task_result(
                    self.dataset_name, self._current_task.task_id, success
                )
        self._current_task = None

    def iter_tasks(self) -> Iterator[Task]:
        while True:
            task = self.fetch_task()
            if task is None:
                return
            yield task
            self.report_task_done()

    # -- shard checkpoint (mid-epoch resume) --------------------------------

    def checkpoint_shards(self) -> str:
        if self._is_chief and self._client is not None:
            if self._done_ids and self._lease_supported:
                # the shard checkpoint must reflect everything consumed
                self._lease(0)
            return self._client.get_shard_checkpoint(self.dataset_name)
        return ""

    def restore_shards(self, content: str):
        if self._is_chief and self._client is not None and content:
            self._client.report_shard_checkpoint(self.dataset_name, content)


@dataclass
class SamplerState:
    epoch: int = 0
    completed_samples: int = 0


class ElasticDistributedSampler:
    """Deterministic per-process sample indices with mid-epoch resume.

    Parity: reference ``ElasticDistributedSampler`` (``sampler.py:25-175``):
    ``state_dict/load_state_dict`` carry the completed-sample offset so a
    restarted (possibly resized) world resumes where it left off.
    """

    def __init__(
        self,
        dataset_size: int,
        batch_size: int,
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
    ):
        import jax

        self.dataset_size = dataset_size
        self.batch_size = batch_size  # per-replica batch
        self.num_replicas = (
            num_replicas if num_replicas is not None else jax.process_count()
        )
        self.rank = rank if rank is not None else jax.process_index()
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.state = SamplerState()

    def _global_order(self) -> np.ndarray:
        order = np.arange(self.dataset_size)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self.state.epoch)
            rng.shuffle(order)
        return order

    def __iter__(self) -> Iterator[List[int]]:
        order = self._global_order()
        global_batch = self.batch_size * self.num_replicas
        start = self.state.completed_samples
        for gstart in range(start, self.dataset_size, global_batch):
            gbatch = order[gstart : gstart + global_batch]
            if len(gbatch) < global_batch and self.drop_last:
                break
            local = gbatch[self.rank :: self.num_replicas][: self.batch_size]
            self.state.completed_samples = min(
                gstart + global_batch, self.dataset_size
            )
            yield local.tolist()
        # Epoch exhausted (including a drop_last partial tail): advance.
        self.state.epoch += 1
        self.state.completed_samples = 0

    def state_dict(self) -> dict:
        return {
            "epoch": self.state.epoch,
            "completed_samples": self.state.completed_samples,
        }

    def load_state_dict(self, state: dict):
        self.state.epoch = int(state.get("epoch", 0))
        completed = int(state.get("completed_samples", 0))
        # Align to the *new* global batch so a resized world resumes cleanly.
        global_batch = self.batch_size * self.num_replicas
        self.state.completed_samples = (completed // global_batch) * global_batch


class ElasticDataLoader:
    """Batches from an indexable dataset with runtime-tunable batch size.

    Parity: reference ``ElasticDataLoader`` (``dataloader.py:26-147``): the
    batch size reloads from the ParalConfigTuner JSON the agent maintains,
    so a master-pushed ``dataloader_batch_size`` (e.g. the brain's HBM-OOM
    micro-batch adjustment) takes effect at the next batch without code
    changes in the training loop. ``collate`` turns a list of samples into
    the yielded batch (default: numpy stack).
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        collate=None,
        config_path: str = "",
        sampler: Optional[ElasticDistributedSampler] = None,
    ):
        self.dataset = dataset
        self._base_batch_size = batch_size
        self._config_path = config_path
        self._config_version = -1
        self._collate = collate or _default_collate
        self.sampler = sampler or ElasticDistributedSampler(
            dataset_size=len(dataset),
            batch_size=batch_size,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
        )

    @property
    def batch_size(self) -> int:
        return self.sampler.batch_size

    def update_batch_size_from_config(self) -> bool:
        """Apply the tuner config; returns True when the size changed.

        SPMD-safe: process 0 reads its node's file and BROADCASTS
        (version, size) so every process applies the identical change —
        per-node tuner files update on independent poll schedules, and a
        mismatched micro-batch under pjit lockstep hangs the collective.
        Called only between epochs: the sampler's iterator captures the
        global batch at epoch start, so a mid-epoch change would skip or
        duplicate samples.
        """
        from dlrover_tpu.agent.paral_config_tuner import read_paral_config

        version, new_size = self._config_version, self.sampler.batch_size
        config = read_paral_config(self._config_path)
        if config:
            version = int(config.get("dataloader_version", 0))
            new_size = int(config.get("dataloader_batch_size", 0))
            if new_size <= 0:
                # relative adjustment (HBM-OOM recovery halves micro-batch)
                scale = float(config.get("micro_batch_scale", 1.0) or 1.0)
                new_size = max(1, int(self._base_batch_size * scale))
        import jax

        version, new_size = _broadcast_tuple(
            (version, new_size), is_source=jax.process_index() == 0
        )
        if version == self._config_version:
            return False
        self._config_version = version
        if new_size == self.sampler.batch_size or new_size <= 0:
            return False
        logger.info(
            "elastic dataloader: batch size %s -> %s (config v%s)",
            self.sampler.batch_size,
            new_size,
            version,
        )
        self.sampler.batch_size = new_size
        return True

    def __iter__(self):
        from dlrover_tpu.profiler.py_tracing import py_tracer

        # flag-registry enablement (DLROVER_TPU_PY_TRACING / _TRACE):
        # entry scripts that never call bootstrap.init still get their
        # input-wait spans into the spine
        py_tracer.maybe_start()
        self.update_batch_size_from_config()
        for indices in self.sampler:
            # span only when tracing is on: fetch+collate stalls explain
            # device-idle gaps in the merged timeline (reference
            # py_tracing's dataloader interception); cat="dataloader"
            # maps onto the spine's `input_wait` span kind
            with py_tracer.span("dataloader.next", cat="dataloader"):
                batch = self._collate([self.dataset[i] for i in indices])
            yield batch
        # next epoch may pick up a new config (never mid-epoch)

    def state_dict(self) -> dict:
        return self.sampler.state_dict()

    def load_state_dict(self, state: dict):
        self.sampler.load_state_dict(state)


def _default_collate(samples):
    if isinstance(samples[0], (tuple, list)):
        return tuple(
            np.stack([s[i] for s in samples])
            for i in range(len(samples[0]))
        )
    if isinstance(samples[0], dict):
        return {k: np.stack([s[k] for s in samples]) for k in samples[0]}
    return np.stack(samples)


def prefetch_to_device(iterator, size: int = 2, sharding=None,
                       replicated: bool = False):
    """Overlap host->device transfer with compute by keeping ``size``
    batches in flight on the device.

    ``jax.device_put`` dispatches asynchronously, so enqueueing the next
    batch before yielding the current one hides the h2d copy behind the
    running step — the standard TPU input-pipeline idiom (cf. flax
    ``jax_utils.prefetch_to_device``), here aware of ``NamedSharding``
    (pass the batch's sharding to place each dp shard directly). The
    reference's analogue is the torch DataLoader's pinned-memory
    prefetch; on TPU the win is the same: the MXU never waits on PCIe.

    ``sharding`` may be a single sharding or a pytree matching the batch
    structure. On a multi-host mesh (sharding not fully addressable) the
    batch is taken as this process's LOCAL shard and the global array is
    assembled via ``jax.make_array_from_process_local_data`` — matching
    how ``ElasticDataLoader`` shards the sample space per process. Pass
    ``replicated=True`` when every host instead holds the IDENTICAL
    global batch (``ElasticDataLoader`` with ``num_replicas=1``): each
    device then slices its own shard out of the global value, so
    multi-host runs keep the h2d-behind-compute overlap too. With
    ``size=0`` placement still applies; only the overlap is dropped.

    The returned generator is one-shot (it follows the wrapped
    iterator): re-wrap per epoch, e.g.
    ``for epoch in range(E): for b in prefetch_to_device(loader, 2, sh):``.
    """
    import collections
    import itertools

    import jax

    # accept iterables (ElasticDataLoader defines only __iter__): without
    # this, each islice would restart iteration from batch 0
    iterator = iter(iterator)

    def place(leaf, sh):
        if sh is None:
            return jax.device_put(leaf)
        if sh.is_fully_addressable:
            return jax.device_put(leaf, sh)
        if replicated:
            # every process holds the identical global batch: each device
            # takes its slice (h2d of the addressable shards only)
            return jax.make_array_from_callback(
                leaf.shape, sh, lambda idx: leaf[idx]
            )
        # multi-host mesh: each process holds its LOCAL batch; device_put
        # would treat it as the global value (inconsistent global array).
        # Assemble the global array from per-process shards instead.
        return jax.make_array_from_process_local_data(sh, leaf)

    def put(batch):
        if sharding is None:
            return jax.device_put(batch)
        if isinstance(sharding, jax.sharding.Sharding):
            return jax.tree.map(lambda l: place(l, sharding), batch)
        return jax.tree.map(place, batch, sharding)

    if size <= 0:
        # no overlap, but placement is still honored
        yield from map(put, iterator)
        return

    queue = collections.deque()

    def enqueue(n):
        for data in itertools.islice(iterator, n):
            queue.append(put(data))

    enqueue(size)
    while queue:
        out = queue.popleft()
        enqueue(1)
        yield out
