"""ZeRO-1: weight-update sharding across the dp axis.

The trainer's optimizer state (adam mu/nu) is born with the *params'*
shardings (``init_state`` eager ``zeros_like``), which is exactly right
under fsdp — and exactly wrong under pure dp or small-fsdp meshes: the
moments replicate across every dp rank, 2x param bytes of HBM per rank
spent holding copies that are never read by anyone else. Xu et al.
(arXiv:2004.13336) showed the weight update can be cross-replica
sharded — reduce-scatter the gradients, update only your shard of the
state, all-gather the updated params — at zero convergence cost.

This module is the sharding brain of that move; the trainer's
``_build_step``/``init_state`` consume it. Two lowering strategies,
chosen per mesh by :func:`mode_for`:

- ``"scatter"`` (pure-dp meshes, loss factory available): the
  per-microbatch loss+grad runs inside a **full-manual** ``shard_map``
  over the mesh — every non-dp axis is trivial, so the body is plain
  single-device model code (``loss_factory(None)``) — and the dp grad
  reduction is an explicit ``lax.psum_scatter`` straight into the
  zero-1 layout. This lowers to a *real* ``reduce-scatter`` op in the
  post-GSPMD HLO on every backend (the shardcheck dp4+zero1 contract
  pins it), replacing the full grad all-reduce.
- ``"gspmd"`` (mixed meshes — fsdp/sp/tp/ep alongside dp): the grads /
  moments / updates carry zero-1 sharding *constraints* and GSPMD
  partitions the update. The moments shard and the param all-gather is
  real on every backend; whether the grad reduction lowers as a true
  reduce-scatter is the backend's allreduce-rewrite pass (XLA:TPU has
  it — Xu et al. *is* that pass; this image's CPU jaxlib lowers it as
  all-reduce + local slice, which the mixed-mesh zero-1 contracts
  record honestly).

The sharding rule (:func:`partition_spec`): partition along each
leaf's leading dim whose per-shard extent divides by dp — appending
``dp`` after any axes already sharding that dim, so an fsdp-sharded
dim becomes the fused ``("fsdp", "dp")`` tiling. Leaves with no
divisible dim **fall back to replicated** (their moments stay exactly
as today); scalars never shard. The rule is deterministic in (spec,
shape, mesh axis sizes) — the trainer re-derives it against any target
mesh, which is what keeps warm-compile AOT signatures, live-reshard
transfer targets and checkpoint restore placements in agreement across
resizes and zero-on/off transitions.

Kill-switch: ``DLROVER_TPU_ZERO1`` (common/flags.py) overrides the
``TrainConfig.zero1`` knob in both directions — ``0`` forces the
replicated path, any other value forces zero-1 on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

PyTree = Any

#: the axis the weight update shards over (fsdp already shards state
#: by construction; zero-1 exists for the dp replicas)
ZERO1_AXIS = "dp"

__all__ = [
    "ZERO1_AXIS",
    "enabled",
    "mode_for",
    "spec_has_dp",
    "strip_spec",
    "partition_spec",
    "scatter_dim",
    "sharded_value_and_grad",
]


def spec_has_dp(spec) -> bool:
    """Whether any entry of a PartitionSpec names the dp axis — i.e.
    the leaf carries a zero-1 layout."""
    for entry in spec:
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        if ZERO1_AXIS in axes:
            return True
    return False


def enabled(train_config) -> bool:
    """Effective zero-1 setting: the ``DLROVER_TPU_ZERO1`` env flag
    when set (``0`` = off, anything else = on), else the
    ``TrainConfig.zero1`` knob."""
    flag = flags.ZERO1
    if flag.present():
        return flag.get() != "0"
    return bool(getattr(train_config, "zero1", False))


def mode_for(
    mesh, train_config, has_factory: bool,
    enabled_override: Optional[bool] = None,
) -> str:
    """``"off"`` | ``"scatter"`` | ``"gspmd"`` for this build.

    ``scatter`` needs every non-dp axis trivial (the whole mesh goes
    manual, so the body must be single-device model code) and the
    factory form of the loss (``loss_factory(None)`` is the
    constraint-free local loss). pp is excluded entirely: its loss
    already runs its own shard_map schedule and the pipeline grads
    never meet a plain dp psum this rule could rewrite.

    ``enabled_override`` replaces the live :func:`enabled` read — the
    trainer pins it once per build so a concurrent env flip (a
    ``flags.ZERO1.scoped`` window on another thread) can never land
    between the cache-key computation and the program build."""
    on = (
        enabled(train_config)
        if enabled_override is None else enabled_override
    )
    if not on:
        return "off"
    shape = dict(mesh.shape)
    if shape.get(ZERO1_AXIS, 1) <= 1:
        return "off"
    if shape.get("pp", 1) > 1:
        logger.warning(
            "zero-1 requested but pp>1: weight-update sharding does not "
            "compose with the pipeline schedules yet; running replicated"
        )
        return "off"
    pure_dp = all(
        s <= 1 for a, s in shape.items() if a != ZERO1_AXIS
    )
    if pure_dp and has_factory:
        return "scatter"
    return "gspmd"


def strip_spec(spec) -> Any:
    """Remove ``dp`` from every entry of a PartitionSpec — the inverse
    of :func:`partition_spec`, so a zero-1 spec round-trips back to the
    params' base spec (params themselves never shard over dp; dp only
    ever enters a state spec through this module)."""
    from jax.sharding import PartitionSpec as P

    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        axes = tuple(a for a in axes if a != ZERO1_AXIS)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def partition_spec(
    spec, shape, axis_sizes: Dict[str, int]
) -> Optional[Any]:
    """The zero-1 spec for one state leaf: ``spec`` with ``dp``
    appended to the leading dim whose per-shard extent divides by dp.
    Returns None when no dim qualifies (the replicated fallback) or
    the leaf is a scalar. Idempotent: a spec already carrying dp is
    returned unchanged."""
    from jax.sharding import PartitionSpec as P

    dp = axis_sizes.get(ZERO1_AXIS, 1)
    if dp <= 1 or not shape:
        return None
    if spec_has_dp(spec):
        return spec  # idempotent: already a zero-1 layout
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for dim, entry in enumerate(entries):
        axes = () if entry is None else (
            entry if isinstance(entry, tuple) else (entry,)
        )
        div = 1
        for a in axes:
            div *= axis_sizes.get(a, 1)
        # per-shard extent shape[dim]/div must split dp ways exactly;
        # the >0 guard keeps zero-sized dims out (0 % n == 0)
        if shape[dim] > 0 and shape[dim] % (div * dp) == 0:
            new_axes = axes + (ZERO1_AXIS,)
            entries[dim] = (
                new_axes if len(new_axes) > 1 else new_axes[0]
            )
            # canonical form: no trailing Nones (P(x, None) and P(x)
            # place identically but compare unequal — and these specs
            # feed NamedSharding equality in the AOT signature)
            while entries and entries[-1] is None:
                entries.pop()
            return P(*entries)
    return None


def scatter_dim(spec, shape, axis_sizes: Dict[str, int]) -> Optional[int]:
    """Which dim :func:`partition_spec` would put ``dp`` on — the
    ``psum_scatter`` scatter_dimension for the manual strategy. None
    when the leaf falls back to replicated."""
    z = partition_spec(spec, shape, axis_sizes)
    if z is None:
        return None
    for dim, entry in enumerate(z):
        axes = () if entry is None else (
            entry if isinstance(entry, tuple) else (entry,)
        )
        if ZERO1_AXIS in axes:
            return dim
    return None


def sharded_value_and_grad(local_loss, mesh, p_specs, params):
    """The ``scatter`` strategy's grad engine: a full-manual shard_map
    whose body runs the *local* loss+backward on this rank's batch rows
    and explicitly ``psum_scatter``s each grad leaf into the zero-1
    layout (mean over dp). Returns ``fn(params, micro) -> (loss,
    grads)`` where ``loss`` is the global-mean scalar and ``grads``
    are global arrays sharded per :func:`partition_spec` (replicated
    for non-divisible leaves).

    Only valid on meshes where every non-dp axis is trivial — the body
    is single-device code and the manual axes besides dp are size 1.
    ``params`` may be live arrays, tracers or avatars: only ``.shape``
    is read.
    """
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.ops.shard_map_compat import shard_map
    from dlrover_tpu.parallel.sharding import batch_spec

    axis_sizes = dict(mesh.shape)
    dp = axis_sizes[ZERO1_AXIS]
    is_spec = lambda x: isinstance(x, P)  # noqa: E731
    dims = jax.tree.map(
        lambda s, leaf: scatter_dim(s, leaf.shape, axis_sizes),
        p_specs, params, is_leaf=is_spec,
    )
    out_grad_specs = jax.tree.map(
        lambda s, leaf: (
            partition_spec(s, leaf.shape, axis_sizes) or s
        ),
        p_specs, params, is_leaf=is_spec,
    )
    inv_dp = 1.0 / dp

    def body(p, micro):
        loss, g = jax.value_and_grad(local_loss)(p, micro)

        def reduce_leaf(dim, leaf):
            if dim is None:
                # non-divisible fallback: full psum, stays replicated
                return lax.psum(leaf, ZERO1_AXIS) * inv_dp
            return lax.psum_scatter(
                leaf, ZERO1_AXIS, scatter_dimension=dim, tiled=True
            ) * inv_dp

        g = jax.tree.map(
            reduce_leaf, dims, g,
            is_leaf=lambda x: x is None or isinstance(x, int),
        )
        # the global batch mean is the mean of equal-sized local means
        return lax.psum(loss, ZERO1_AXIS) * inv_dp, g

    def fn(p, micro):
        micro_specs = jax.tree.map(lambda _: batch_spec(), micro)
        return shard_map(
            body, mesh=mesh,
            in_specs=(p_specs, micro_specs),
            out_specs=(P(), out_grad_specs),
            check_vma=False,
        )(p, micro)

    return fn
