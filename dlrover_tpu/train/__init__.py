from dlrover_tpu.train.bootstrap import WorkerContext, get_context, init  # noqa: F401
from dlrover_tpu.train.datasets import (  # noqa: F401
    TokenFileDataset,
    pack_text_file,
    pack_tokens,
)
