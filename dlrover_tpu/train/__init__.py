from dlrover_tpu.train.bootstrap import WorkerContext, get_context, init  # noqa: F401
