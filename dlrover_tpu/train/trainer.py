"""ElasticTrainer: sharded train step with elastic gradient accumulation.

Parity target: the reference's `ElasticTrainer`
(`dlrover/trainer/torch/elastic/trainer.py:181-336` there) keeps the
*global* batch size fixed as the world grows/shrinks by re-deriving the
gradient-accumulation count and stepping the optimizer only at sync
boundaries. TPU-native version:

- the "world" is the mesh; accumulation count =
  ``global_batch // (micro_batch * data_parallel_size)`` re-derived on each
  re-mesh (`ElasticTrainer.accum_steps`);
- accumulation is a `lax.scan` over microbatches *inside one jitted step*
  (no eager loop, no grad hooks) — gradients live in one sharded f32
  accumulator, XLA overlaps the dp/fsdp reduce with backward compute;
- optimizer is optax (adamw + cosine), optimizer state sharded like the
  params (ZeRO by construction — optimizer state inherits the fsdp specs);
- the step reports to the master's SpeedMonitor via the worker context
  (`report_step`), which feeds goodput accounting and autoscaling exactly
  like the reference's `report_global_step` path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common.log import logger
from dlrover_tpu.parallel.mesh import MeshConfig
from dlrover_tpu.parallel.sharding import batch_spec

PyTree = Any


@dataclasses.dataclass
class TrainConfig:
    global_batch_size: int = 32
    micro_batch_size: int = 4          # per data-parallel shard
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.95


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    if tc.warmup_steps > 0:
        sched = optax.warmup_cosine_decay_schedule(
            0.0, tc.learning_rate, tc.warmup_steps,
            max(tc.total_steps, tc.warmup_steps + 1), tc.learning_rate * 0.1,
        )
    else:
        sched = optax.cosine_decay_schedule(
            tc.learning_rate, max(tc.total_steps, 1), 0.1
        )
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(sched, b1=tc.b1, b2=tc.b2, weight_decay=tc.weight_decay),
    )


class ElasticTrainer:
    """Builds and owns the jitted, sharded train step."""

    def __init__(
        self,
        loss_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
        p_specs: PyTree,
        mesh: Mesh,
        mesh_config: MeshConfig,
        train_config: TrainConfig,
        worker_ctx=None,
    ):
        self.loss_fn = loss_fn
        self.p_specs = p_specs
        self.mesh = mesh
        self.mesh_config = mesh_config
        self.tc = train_config
        self.optimizer = make_optimizer(train_config)
        self.worker_ctx = worker_ctx
        self._step_fn = None
        self._eval_fn = None
        self._host_step = 0
        self._applied_config_version = 0
        self._maybe_serve_comm_metrics()

    def _maybe_serve_comm_metrics(self):
        """Worker-side /metrics for the per-collective ledger
        (profiler/comm.py), opted in with
        ``DLROVER_TPU_COMM_METRICS_PORT`` (0 = ephemeral port)."""
        import os

        port = os.getenv("DLROVER_TPU_COMM_METRICS_PORT")
        if port is None:
            return
        try:
            port_num = int(port)
        except ValueError:
            logger.warning(
                "DLROVER_TPU_COMM_METRICS_PORT=%r is not a port; comm "
                "metrics disabled", port,
            )
            return
        from dlrover_tpu.profiler.comm import start_metrics_server

        try:
            _, bound = start_metrics_server(port_num)
            from dlrover_tpu.common.log import logger as _logger

            _logger.info("comm metrics on 127.0.0.1:%d/metrics", bound)
        except OSError:
            pass  # port taken (another trainer in-process)

    # ---- elastic global-batch math (reference trainer.py:307-327) ------
    @property
    def accum_steps(self) -> int:
        dp = self.mesh_config.resolve(self.mesh.size).data_parallel_size
        denom = self.tc.micro_batch_size * dp
        if self.tc.global_batch_size % denom:
            raise ValueError(
                f"global_batch={self.tc.global_batch_size} not divisible by "
                f"micro_batch*dp={denom}"
            )
        return self.tc.global_batch_size // denom

    @property
    def batch_sharding(self):
        """The NamedSharding the jitted step expects for its batch —
        the single source of truth input pipelines (prefetch) should
        place against."""
        return NamedSharding(self.mesh, P(None, *batch_spec()))

    @property
    def step_batch_shape(self) -> Tuple[int, int]:
        """(accum_steps, global_batch_per_accum) — how callers should shape
        the token batch fed to `step`."""
        dp = self.mesh_config.resolve(self.mesh.size).data_parallel_size
        return self.accum_steps, self.tc.micro_batch_size * dp

    def init_state(self, params: PyTree) -> dict:
        # EAGER init so adam's mu/nu are born with the params' shardings:
        # eager zeros_like follows its input's sharding exactly
        # (optimizer state is ZeRO-sharded for free whenever params carry
        # fsdp specs), whereas jit(opt.init) leaves the OUTPUT shardings
        # to XLA, which has been seen to choose SingleDeviceSharding for
        # some leaves — poisoning every later restore that places leaves
        # by this target's sharding (resized-world restore path).
        self._record_data_parallel_comm(params)
        opt_state = self.optimizer.init(params)
        # scalars born mesh-replicated, not on the default device: a
        # checkpoint restore places leaves by the target's sharding, and
        # a single-device-committed scalar (adam's count, step, lr_scale)
        # next to mesh-wide params makes the jitted step reject the
        # state (resized-world restore path)
        repl = NamedSharding(self.mesh, P())
        opt_state = jax.tree.map(
            lambda l: jax.device_put(l, repl) if getattr(l, "ndim", None)
            == 0 else l,
            opt_state,
        )
        return {
            "params": params,
            "opt": opt_state,
            "step": jax.device_put(jnp.zeros((), jnp.int32), repl),
            # runtime lr multiplier (master paral-config pushes): applied
            # to the optimizer's updates inside the jitted step, so the
            # master's sqrt-coupled lr actually takes effect without
            # recompiling (the wd term follows lr — exact decoupled-wd
            # rescaling would need a rebuilt optimizer)
            "lr_scale": jax.device_put(jnp.ones((), jnp.float32), repl),
        }

    def _record_data_parallel_comm(self, params: PyTree):
        """Analytic per-step inventory of the collectives XLA inserts
        for the data axes (profiler/comm.py). These aren't explicit in
        our code — fsdp re-gathers parameters fwd+bwd and reduce-
        scatters gradients; dp all-reduces gradients — so the byte
        counts come from the parameter tree, the same way the
        reference derives NCCL bus bandwidth from algorithm formulas
        rather than observed packets (xpu_timer parse_params.cc)."""
        from dlrover_tpu.profiler.comm import comm_ledger, record_collective

        # a new trainer means a new program inventory: drop rows from any
        # previous mesh/config so /metrics never mixes dead and live
        # configurations (elastic resize, bench candidate sweeps)
        comm_ledger.clear()
        comm_ledger.set_accum_steps(self.accum_steps)
        shape = dict(self.mesh.shape)
        param_bytes = sum(
            l.size * l.dtype.itemsize for l in jax.tree.leaves(params)
        )
        fsdp = shape.get("fsdp", 1)
        if fsdp > 1:
            # ledger unit is PER-SHARD payload per issue (what one rank
            # sends), matching measure_axis_bandwidth's accounting — an
            # fsdp all-gather/reduce-scatter moves 1/fsdp of the params
            # per rank per issue
            record_collective(
                "fsdp.param_all_gather", "all_gather", "fsdp",
                nbytes=param_bytes // fsdp, count=2 * self.accum_steps,
            )
            record_collective(
                "fsdp.grad_reduce_scatter", "reduce_scatter", "fsdp",
                nbytes=param_bytes // fsdp, count=1,
            )
        if shape.get("dp", 1) > 1:
            # grads entering the dp psum are fsdp-sharded when fsdp>1:
            # per-shard payload is param_bytes/fsdp
            record_collective(
                "dp.grad_allreduce", "psum", "dp",
                nbytes=param_bytes // max(fsdp, 1), count=1,
            )

    def _build_step(self):
        accum = self.accum_steps

        def step(state, batch):
            # batch: any pytree whose leaves lead with (accum, micro*dp):
            # token arrays for the LM families, (images, labels) for CV
            if accum == 1:
                # single microbatch: no accumulator scan — grads stay in
                # param dtype and the f32 accumulation buffer (a full extra
                # param-sized pytree) is never allocated
                loss_sum, grads = jax.value_and_grad(self.loss_fn)(
                    state["params"], jax.tree.map(lambda x: x[0], batch)
                )
            else:
                # NB: the model losses may route through the chunked-CE
                # custom_vjp (ops/chunked_ce.py), which itself scans over
                # vocab chunks — custom_vjp rules are opaque to this outer
                # scan's AD, so the grad-accum scan composes with it the
                # same as with any primitive (and the f32 accumulator
                # below absorbs its param-dtype dw chunks via promotion)
                def micro_grads(carry, micro):
                    loss_sum, grads = carry
                    loss, g = jax.value_and_grad(self.loss_fn)(
                        state["params"], micro
                    )
                    grads = jax.tree.map(jnp.add, grads, g)
                    return (loss_sum + loss, grads), None

                zero = jax.tree.map(
                    lambda p: jnp.zeros(p.shape, jnp.float32),
                    state["params"],
                )
                (loss_sum, grads), _ = jax.lax.scan(
                    micro_grads, (jnp.zeros((), jnp.float32), zero), batch
                )
            scale = 1.0 / accum
            grads = jax.tree.map(lambda g: g * scale, grads)
            updates, opt_state = self.optimizer.update(
                grads, state["opt"], state["params"]
            )
            lr_scale = state.get("lr_scale")
            if lr_scale is not None:
                updates = jax.tree.map(
                    lambda u: u * lr_scale.astype(u.dtype), updates
                )
            params = optax.apply_updates(state["params"], updates)
            out = {
                "params": params,
                "opt": opt_state,
                "step": state["step"] + 1,
            }
            if lr_scale is not None:
                out["lr_scale"] = lr_scale
            return out, loss_sum * scale

        # state keeps the shardings its arrays already carry (params placed
        # by the caller, opt state born sharded in init_state).
        batch_sh = self.batch_sharding
        return jax.jit(
            step,
            in_shardings=(None, batch_sh),
            donate_argnums=(0,),
        )

    def apply_paral_config(self, state: dict, config: dict) -> dict:
        """Apply a master-pushed runtime config to the train state: a new
        ``optimizer_learning_rate`` becomes an update multiplier relative
        to the configured base lr (the schedule shape is preserved). The
        dataloader fields are consumed by ``ElasticDataLoader``."""
        new_lr = float(config.get("optimizer_learning_rate", 0.0) or 0.0)
        if new_lr > 0 and self.tc.learning_rate > 0 and "lr_scale" in state:
            scale = new_lr / self.tc.learning_rate
            if abs(scale - float(state["lr_scale"])) > 1e-9:
                state = {
                    **state,
                    "lr_scale": jax.device_put(
                        jnp.asarray(scale, jnp.float32),
                        NamedSharding(self.mesh, P()),
                    ),
                }
                from dlrover_tpu.common.log import logger as _logger

                _logger.info(
                    "runtime lr update: base=%g -> %g (scale %.4f)",
                    self.tc.learning_rate, new_lr, scale,
                )
        return state

    def poll_runtime_config(
        self, state: dict, every_steps: int = 100
    ) -> dict:
        """Cheap per-step hook: every ``every_steps`` host steps re-read
        the agent-pushed paral config file and apply optimizer changes."""
        if self._host_step % max(1, every_steps):
            return state
        from dlrover_tpu.agent.paral_config_tuner import read_paral_config

        config = read_paral_config()
        version = int(config.get("optimizer_version", 0) or
                      config.get("dataloader_version", 0) or 0)
        if config and version != self._applied_config_version:
            self._applied_config_version = version
            state = self.apply_paral_config(state, config)
        return state

    def eval_step(self, state: dict, batch) -> jnp.ndarray:
        """Loss of one batch WITHOUT touching the train state: jitted
        forward-only, no donation (state survives), batch shaped
        (micro*dp, ...) — one microbatch row of ``step_batch_shape``."""
        if self._eval_fn is None:
            bspec = batch_spec()
            self._eval_fn = jax.jit(
                lambda params, b: self.loss_fn(params, b),
                in_shardings=(
                    None, NamedSharding(self.mesh, P(*bspec)),
                ),
            )
        return self._eval_fn(state["params"], batch)

    def evaluate(self, state: dict, batches) -> float:
        """Mean loss over an iterable of eval batches (each shaped like
        one ``step_batch_shape`` row). The evaluator-role analogue of the
        reference's estimator evaluation: the same jitted graph and mesh
        as training, params untouched, no optimizer state involved."""
        total = 0.0
        count = 0
        for batch in batches:
            total += float(self.eval_step(state, batch))
            count += 1
        if count == 0:
            # 0.0 would read as a perfect loss to early-stopping logic
            raise ValueError(
                "evaluate() got zero batches (eval dataset smaller than "
                "one batch under drop_last?)"
            )
        return total / count

    def step(self, state: dict, batch) -> Tuple[dict, jnp.ndarray]:
        """One optimizer step = ``accum_steps`` microbatches.

        ``batch``: any pytree whose leaves lead with (accum_steps,
        micro*dp, ...) — int32 token arrays for the LM families,
        (images, labels) tuples for CV."""
        if self._step_fn is None:
            self._step_fn = self._build_step()
        if self.worker_ctx is not None:
            state = self.poll_runtime_config(state)
        new_state, loss = self._step_fn(state, batch)
        # host-side step counter: reading new_state["step"] would block on
        # the just-dispatched computation and kill async dispatch
        self._host_step += 1
        if self.worker_ctx is not None:
            self.worker_ctx.report_step(self._host_step)
        return new_state, loss

    # ---- elasticity ----------------------------------------------------
    def remesh(self, mesh: Mesh, mesh_config: MeshConfig):
        """After a membership change: adopt the new mesh; the jitted step is
        rebuilt (recompiled) lazily; accumulation re-derives so the global
        batch is unchanged (the reference's core elasticity invariant)."""
        old = self.accum_steps
        dp = mesh_config.resolve(mesh.size).data_parallel_size
        denom = self.tc.micro_batch_size * dp
        if self.tc.global_batch_size % denom:
            raise ValueError(
                f"cannot remesh to world={mesh.size}: global_batch="
                f"{self.tc.global_batch_size} not divisible by "
                f"micro_batch*dp={denom}; trainer left on the old mesh"
            )
        self.mesh = mesh
        self.mesh_config = mesh_config
        self._step_fn = None
        self._eval_fn = None  # its NamedSharding binds the old mesh
        logger.info(
            "remesh: world=%d accum %d→%d (global batch fixed at %d)",
            mesh.size, old, self.accum_steps, self.tc.global_batch_size,
        )
