"""ElasticTrainer: sharded train step with elastic gradient accumulation.

Parity target: the reference's `ElasticTrainer`
(`dlrover/trainer/torch/elastic/trainer.py:181-336` there) keeps the
*global* batch size fixed as the world grows/shrinks by re-deriving the
gradient-accumulation count and stepping the optimizer only at sync
boundaries. TPU-native version:

- the "world" is the mesh; accumulation count =
  ``global_batch // (micro_batch * data_parallel_size)`` re-derived on each
  re-mesh (`ElasticTrainer.accum_steps`);
- accumulation is a `lax.scan` over microbatches *inside one jitted step*
  (no eager loop, no grad hooks) — gradients live in one sharded f32
  accumulator, XLA overlaps the dp/fsdp reduce with backward compute;
- optimizer is optax (adamw + cosine), optimizer state sharded like the
  params (ZeRO by construction — optimizer state inherits the fsdp specs);
- the step reports to the master's SpeedMonitor via the worker context
  (`report_step`), which feeds goodput accounting and autoscaling exactly
  like the reference's `report_global_step` path.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import threading
import time
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.world import WorldDescriptor
from dlrover_tpu.lint import retrace_guard
from dlrover_tpu.observability import trace
from dlrover_tpu.observability.digest import StepTimeDigest
from dlrover_tpu.ops import hier_collectives
from dlrover_tpu.parallel.mesh import MeshConfig
from dlrover_tpu.parallel.sharding import batch_spec
from dlrover_tpu.train import live_reshard, warm_compile, zero1

PyTree = Any


@dataclasses.dataclass(frozen=True)
class _Avatar:
    """Mesh-independent stand-in for one state/batch leaf: enough to
    rebuild a ``jax.ShapeDtypeStruct`` (with sharding) against any
    target mesh. A plain object on purpose — pytree LEAF, so avatar
    trees keep the state's treedef."""

    shape: Tuple[int, ...]
    dtype: Any
    spec: Any  # PartitionSpec (state leaves) | None (batch leaves)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1


def _avatar_of(leaf) -> _Avatar:
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        spec = P()  # single-device / uncommitted: replicated on retarget
    return _Avatar(tuple(leaf.shape), np.dtype(leaf.dtype), spec)


@dataclasses.dataclass
class TrainConfig:
    global_batch_size: int = 32
    micro_batch_size: int = 4          # per data-parallel shard
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    # ZeRO-1 weight-update sharding across dp (train/zero1.py):
    # reduce-scatter grads, update dp-sharded adam moments, all-gather
    # the params. The DLROVER_TPU_ZERO1 env flag overrides this knob in
    # both directions. No-op on meshes without a dp axis > 1.
    zero1: bool = False
    # Hierarchical DCN-aware gradient reduction on multislice meshes
    # (ops/hier_collectives.py): ICI reduce-scatter within each slice,
    # DCN exchange of only the slice-local 1/dp_in shard, ICI
    # all-gather. The DLROVER_TPU_HIER_COLLECTIVES env flag overrides
    # this knob in both directions; the flat path is the fallback.
    # No-op on single-slice meshes (the trainer's n_slices).
    hier_collectives: bool = True
    # Latency-hiding schedule of the hierarchical reduction
    # (ops/hier_collectives.py overlap_value_and_grad): bucket the
    # grads, run the ICI leg eagerly and carry each microbatch's DCN
    # exchange through the accumulation scan behind the NEXT
    # microbatch's backward. Same reduction, pipelined — the
    # DLROVER_TPU_OVERLAP_COLLECTIVES env flag overrides in both
    # directions (0 = kill-switch). Only effective where hier itself
    # applies; with accum == 1 there is no backward to hide behind and
    # the schedule degenerates to hier's.
    overlap_collectives: bool = True
    # Flash-attention Pallas tile sizes (ops/attention.py block_q /
    # block_k). 0 = keep the model config's default (the llama.py
    # numbers are a VMEM-budget guess, not a measurement — bench.py's
    # mfu tiling sweep measures 2–3 tilings and reports the winner, so
    # a deployment pins what its own chips prefer). Callers that build
    # a model config thread non-zero values into it.
    attn_block_q: int = 0
    attn_block_k: int = 0


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    if tc.warmup_steps > 0:
        sched = optax.warmup_cosine_decay_schedule(
            0.0, tc.learning_rate, tc.warmup_steps,
            max(tc.total_steps, tc.warmup_steps + 1), tc.learning_rate * 0.1,
        )
    else:
        sched = optax.cosine_decay_schedule(
            tc.learning_rate, max(tc.total_steps, 1), 0.1
        )
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(sched, b1=tc.b1, b2=tc.b2, weight_decay=tc.weight_decay),
    )


def _pin_zero1(fn):
    """Run a build entry point under ``ElasticTrainer._zero1_pin`` so
    every zero-1 read inside one build sees one consistent answer."""

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        with self._zero1_pin():
            return fn(self, *args, **kwargs)

    return wrapped


class ElasticTrainer:
    """Builds and owns the jitted, sharded train step."""

    def __init__(
        self,
        loss_fn: Optional[Callable[[PyTree, jnp.ndarray], jnp.ndarray]],
        p_specs: PyTree,
        mesh: Mesh,
        mesh_config: MeshConfig,
        train_config: TrainConfig,
        worker_ctx=None,
        loss_factory: Optional[Callable[[Optional[Mesh]], Callable]] = None,
        n_slices: int = 1,
    ):
        """``loss_fn`` may close over the live mesh (sharding
        constraints); that pins the step to one mesh forever. Passing
        ``loss_factory`` (mesh → loss_fn) instead lets the trainer
        re-derive the loss for any mesh — which is what makes
        cross-world AOT compilation (``lower_step`` for a world that is
        not live) and true in-process ``remesh()`` possible. With only
        ``loss_fn``, speculative neighbor compilation stays off.

        ``n_slices``: distinct TPU slices the mesh spans (the agent
        injects it as ``DLROVER_TPU_NUM_SLICES`` — ``WorkerEnv.
        num_slices``). >1 arms the hierarchical DCN-aware gradient
        reduction (ops/hier_collectives.py) and the per-link comm
        inventory; 1 (the default) is byte-identical to before."""
        self.loss_factory = loss_factory
        if loss_fn is None:
            if loss_factory is None:
                raise ValueError("need loss_fn or loss_factory")
            loss_fn = loss_factory(mesh)
        self.loss_fn = loss_fn
        self.p_specs = p_specs
        self.mesh = mesh
        self.mesh_config = mesh_config
        self.tc = train_config
        self.n_slices = max(1, int(n_slices))
        self.optimizer = make_optimizer(train_config)
        self.worker_ctx = worker_ctx
        self._step_fn = None
        self._eval_fn = None
        self._host_step = 0
        self._applied_config_version = 0
        # warm-compile layer (train/warm_compile.py): AOT executable
        # cache + the speculative neighbor-compile thread. Avatars are
        # captured from the first state/batch seen so the step can be
        # lowered for meshes that are not live.
        self.warm = warm_compile.WarmCompiler()
        self._state_avatar: Optional[PyTree] = None
        self._batch_avatar: Optional[PyTree] = None
        self._params_avatar: Optional[PyTree] = None
        # per-thread zero-1 pin (see _zero1_pin): holds the effective
        # enabled decision for the duration of one build on that thread
        self._zero1_tls = threading.local()
        # optional semantic hints for the shardcheck IR rules (SC003
        # needs seq_len and vocab to recognize a dense-logits tensor);
        # entry scripts that know the model set this, e.g.
        # trainer.shardcheck_hints = {"seq_len": s, "vocab": v}
        self.shardcheck_hints: dict = {}
        # open resize event (remesh() stamps the transfer half; the
        # first post-resize step build stamps the compile half and
        # records it to live_reshard.resize_ledger)
        self._pending_resize: Optional[dict] = None
        # planner-directed speculation target (set_speculation_hint):
        # the exact WorldDescriptor the master's goodput planner
        # intends next — compiled FIRST by the speculative thread
        self._speculation_hint: Optional[WorldDescriptor] = None
        # silent-recompile guard (lint/retrace_guard.py), opt-in via
        # DLROVER_TPU_RETRACE_GUARD: raises in place when the step (or
        # any jitted fn) recompiles an already-seen signature or drifts
        # through too many distinct ones
        self._retrace_guard = retrace_guard.maybe_install()
        # per-rank step-time digest (observability/digest.py): every
        # step folds its host wall seconds; the throttled report_step
        # drains one window to the master's straggler detector and
        # lost-time attribution
        self.step_digest = StepTimeDigest()
        self._maybe_serve_comm_metrics()

    def _maybe_serve_comm_metrics(self):
        """Worker-side /metrics for the per-collective ledger
        (profiler/comm.py), opted in with
        ``DLROVER_TPU_COMM_METRICS_PORT`` (0 = ephemeral port)."""
        port_num = flags.COMM_METRICS_PORT.get()
        if port_num is None:
            return  # unset (or non-numeric: flags warned) = disabled
        from dlrover_tpu.profiler.comm import start_metrics_server

        try:
            _, bound = start_metrics_server(port_num)
            from dlrover_tpu.common.log import logger as _logger

            _logger.info("comm metrics on 127.0.0.1:%d/metrics", bound)
        except OSError:
            pass  # port taken (another trainer in-process)

    # ---- zero-1 weight-update sharding (train/zero1.py) ----------------
    @contextlib.contextmanager
    def _zero1_pin(self):
        """Pin the effective zero-1 AND hier-collectives decisions for
        the calling thread.

        The ``DLROVER_TPU_ZERO1`` / ``DLROVER_TPU_HIER_COLLECTIVES``
        env flags are read live at build time (flips take effect at the
        next build — the documented resize/restore-boundary semantics).
        But ONE build reads them several times (cache key, avatars,
        contract lookup, the step body), and another thread's
        ``flags.*.scoped`` window (bench A/B legs, contract lowering)
        can flip the env between those reads — a cache key that says
        scatter over a replicated program, cached forever. Pinning
        makes every ``_zero1_mode`` / ``_hier_mode`` call within the
        ``with`` block (on this thread) see one consistent answer.
        Re-entrant: an outer pin wins."""
        tls = self._zero1_tls
        if getattr(tls, "enabled", None) is not None:
            yield
            return
        tls.enabled = zero1.enabled(self.tc)
        tls.hier_enabled = hier_collectives.enabled(self.tc)
        tls.overlap_enabled = hier_collectives.overlap_enabled(self.tc)
        try:
            yield
        finally:
            tls.enabled = None
            tls.hier_enabled = None
            tls.overlap_enabled = None

    def _zero1_mode(self, mesh: Mesh) -> str:
        """``"off"`` | ``"scatter"`` | ``"gspmd"`` — how the weight
        update shards over dp on ``mesh``. Inside a ``_zero1_pin``
        block the enabled decision is the pinned snapshot."""
        return zero1.mode_for(
            mesh, self.tc, self.loss_factory is not None,
            enabled_override=getattr(self._zero1_tls, "enabled", None),
        )

    def _slices_for(self, mesh: Mesh) -> int:
        """Slice count of ``mesh``: the live mesh carries the trainer's
        ``n_slices``; a warm-compile TARGET mesh (speculative neighbor,
        cross-world lowering) derives it from the invariant that slices
        are atomic resize units — devices per slice stay constant, so a
        neighbor world's slice count is ``size / per_slice``. Worlds
        that don't tile into whole slices are treated single-slice
        (they could only run flat anyway)."""
        return self._slices_for_size(mesh.size)

    def _slices_for_size(self, size: int) -> int:
        if self.n_slices <= 1:
            return 1
        if size == self.mesh.size:
            return self.n_slices
        per = self.mesh.size // self.n_slices
        if per > 0 and size % per == 0:
            return max(1, size // per)
        return 1

    def _hier_mode(self, mesh: Mesh) -> str:
        """``"flat"`` | ``"hier"`` | ``"overlap"`` — how the dp
        gradient reduction is scheduled over the slice topology
        (ops/hier_collectives.py); ``overlap`` is the hierarchy plus
        the latency-hiding bucketed DCN pipeline. Inside a
        ``_zero1_pin`` block the flag reads are the pinned snapshot,
        same as zero-1's."""
        return hier_collectives.mode_for(
            mesh, self._slices_for(mesh), self.tc,
            self.loss_factory is not None,
            zero1_mode=self._zero1_mode(mesh),
            enabled_override=getattr(
                self._zero1_tls, "hier_enabled", None
            ),
            overlap_override=getattr(
                self._zero1_tls, "overlap_enabled", None
            ),
        )

    def _state_avatar_for(self, mesh: Mesh) -> Optional[PyTree]:
        """State avatars with the optimizer-state specs RE-DERIVED for
        ``mesh``. Zero-1 shards each moment along whatever dim divides
        on the *current* dp size — a resized dp (or a zero-1 on/off
        flip at a resize boundary) changes the answer — so every
        cross-mesh consumer (AOT lowering, live-reshard transfer
        targets, checkpoint restore placement) re-derives here instead
        of reusing the captured specs verbatim. Leaves outside ``opt``
        never carry dp (the zero1.py invariant: dp only enters a state
        spec through that module) and pass through untouched."""
        if self._state_avatar is None:
            return None
        mode = self._zero1_mode(mesh)
        axis_sizes = dict(mesh.shape)

        def retarget(av):
            if not av.shape:
                return av
            has_dp = zero1.spec_has_dp(av.spec)
            if mode == "off" and not has_dp:
                # nothing to do — and strip_spec's trailing-None
                # normalization must not churn an untouched spec
                # (P(None,) and P() place identically but compare
                # unequal as NamedShardings)
                return av
            base = zero1.strip_spec(av.spec) if has_dp else av.spec
            z = (
                zero1.partition_spec(base, av.shape, axis_sizes)
                if mode != "off" else None
            )
            spec = z if z is not None else base
            if spec == av.spec:
                return av
            return dataclasses.replace(av, spec=spec)

        out = dict(self._state_avatar)
        if "opt" in out:
            out["opt"] = jax.tree.map(retarget, out["opt"])
        return out

    def state_targets(self, mesh: Optional[Mesh] = None) -> PyTree:
        """``ShapeDtypeStruct`` (with sharding) restore/transfer targets
        for ``mesh`` (default: live): state shapes from the avatars,
        optimizer-state specs re-derived for the target world (zero-1
        aware). The one tree checkpoint restore and the bench's
        round-trip leg should place against — placing by raw captured
        avatars instead would pin a resized world to the OLD dp's
        moment layout."""
        mesh = mesh if mesh is not None else self.mesh
        avatars = self._state_avatar_for(mesh)
        if avatars is None:
            raise RuntimeError(
                "state_targets needs avatars: run one step() or call "
                "record_avatars(state, batch) first"
            )
        # no world= check here: the only descriptor available derives
        # from this same mesh (a self-comparison proves nothing);
        # remesh() passes a config-derived one where it is meaningful
        return live_reshard.state_targets(avatars, mesh)

    # ---- elastic global-batch math (reference trainer.py:307-327) ------
    @property
    def accum_steps(self) -> int:
        return self._accum_for(self.mesh, self.mesh_config)

    def _accum_for(self, mesh: Mesh, mesh_config: MeshConfig) -> int:
        """Accumulation count keeping the global batch fixed on any
        (mesh, config) — the live pair or a warm-compile target."""
        dp = mesh_config.resolve(mesh.size).data_parallel_size
        denom = self.tc.micro_batch_size * dp
        if self.tc.global_batch_size % denom:
            raise ValueError(
                f"global_batch={self.tc.global_batch_size} not divisible by "
                f"micro_batch*dp={denom}"
            )
        return self.tc.global_batch_size // denom

    @property
    def batch_sharding(self):
        """The NamedSharding the jitted step expects for its batch —
        the single source of truth input pipelines (prefetch) should
        place against."""
        return NamedSharding(self.mesh, P(None, *batch_spec()))

    @property
    def step_batch_shape(self) -> Tuple[int, int]:
        """(accum_steps, global_batch_per_accum) — how callers should shape
        the token batch fed to `step`."""
        dp = self.mesh_config.resolve(self.mesh.size).data_parallel_size
        return self.accum_steps, self.tc.micro_batch_size * dp

    def init_state(self, params: PyTree) -> dict:
        # EAGER init so adam's mu/nu are born with the params' shardings:
        # eager zeros_like follows its input's sharding exactly
        # (optimizer state is ZeRO-sharded for free whenever params carry
        # fsdp specs), whereas jit(opt.init) leaves the OUTPUT shardings
        # to XLA, which has been seen to choose SingleDeviceSharding for
        # some leaves — poisoning every later restore that places leaves
        # by this target's sharding (resized-world restore path).
        self._params_avatar = jax.tree.map(_avatar_of, params)
        self._record_data_parallel_comm(params)
        opt_state = self.optimizer.init(params)
        # scalars born mesh-replicated, not on the default device: a
        # checkpoint restore places leaves by the target's sharding, and
        # a single-device-committed scalar (adam's count, step, lr_scale)
        # next to mesh-wide params makes the jitted step reject the
        # state (resized-world restore path)
        repl = NamedSharding(self.mesh, P())
        opt_state = jax.tree.map(
            lambda l: jax.device_put(l, repl) if getattr(l, "ndim", None)
            == 0 else l,
            opt_state,
        )
        if self._zero1_mode(self.mesh) != "off":
            # ZeRO-1 (train/zero1.py): re-place every non-scalar moment
            # dp-sharded along its leading divisible dim. The step's
            # update runs on (and returns) exactly this layout, and the
            # avatars captured from this state carry it into the AOT
            # signatures, live-reshard targets and restore placements.
            axis_sizes = dict(self.mesh.shape)

            def _shard_moment(l):
                if getattr(l, "ndim", 0) == 0:
                    return l
                spec = getattr(getattr(l, "sharding", None), "spec", None)
                z = zero1.partition_spec(
                    spec if spec is not None else P(), l.shape, axis_sizes
                )
                if z is None:
                    return l  # non-divisible leaf: replicated fallback
                return jax.device_put(l, NamedSharding(self.mesh, z))

            opt_state = jax.tree.map(_shard_moment, opt_state)
        return {
            "params": params,
            "opt": opt_state,
            "step": jax.device_put(jnp.zeros((), jnp.int32), repl),
            # runtime lr multiplier (master paral-config pushes): applied
            # to the optimizer's updates inside the jitted step, so the
            # master's sqrt-coupled lr actually takes effect without
            # recompiling (the wd term follows lr — exact decoupled-wd
            # rescaling would need a rebuilt optimizer)
            "lr_scale": jax.device_put(jnp.ones((), jnp.float32), repl),
        }

    def _record_data_parallel_comm(self, params: PyTree):
        """Analytic per-step inventory of the collectives XLA inserts
        for the data axes (profiler/comm.py). These aren't explicit in
        our code — fsdp re-gathers parameters fwd+bwd and reduce-
        scatters gradients; dp all-reduces gradients — so the byte
        counts come from the parameter tree, the same way the
        reference derives NCCL bus bandwidth from algorithm formulas
        rather than observed packets (xpu_timer parse_params.cc).
        ``params`` may be live arrays or their avatars (remesh path)."""
        from dlrover_tpu.profiler.comm import (
            axis_links,
            comm_ledger,
            record_collective,
        )

        # a new trainer means a new program inventory: drop rows from any
        # previous mesh/config so /metrics never mixes dead and live
        # configurations (elastic resize, bench candidate sweeps)
        comm_ledger.clear()
        comm_ledger.set_accum_steps(self.accum_steps)
        # per-link classification: on a multislice mesh the dp axis is
        # the one DCN axis; hier-mode events below override per leg
        comm_ledger.set_links(axis_links(self.mesh, self.n_slices))
        shape = dict(self.mesh.shape)
        param_bytes = sum(
            l.size * np.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(params)
        )
        fsdp = shape.get("fsdp", 1)
        if fsdp > 1:
            # ledger unit is PER-SHARD payload per issue (what one rank
            # sends), matching measure_axis_bandwidth's accounting — an
            # fsdp all-gather/reduce-scatter moves 1/fsdp of the params
            # per rank per issue
            record_collective(
                "fsdp.param_all_gather", "all_gather", "fsdp",
                nbytes=param_bytes // fsdp, count=2 * self.accum_steps,
            )
            record_collective(
                "fsdp.grad_reduce_scatter", "reduce_scatter", "fsdp",
                nbytes=param_bytes // fsdp, count=1,
            )
        dp = shape.get("dp", 1)
        if dp > 1:
            mode = self._zero1_mode(self.mesh)
            # grads entering the dp reduction are fsdp-sharded when
            # fsdp>1: per-shard payload is param_bytes/fsdp. Under grad
            # accumulation the partitioner reduces each microbatch's
            # grads inside the scan body (a GSPMD grad is a *global*
            # value the moment value_and_grad returns it — there is no
            # unreduced representation for the accumulator to hold), so
            # the reduction issues once per LOSS CALL, not once per
            # step; the census-diff test (tests/test_zero1.py) pins
            # this inventory against the lowered IR.
            grad_payload = param_bytes // max(fsdp, 1)
            hier_mode = self._hier_mode(self.mesh)
            hier = hier_mode != "flat"
            dp_in = dp // self.n_slices if hier else dp
            # overlap is a SCHEDULE of the hierarchical reduction — the
            # byte inventory below is identical; what changes is how
            # much of the DCN leg sits exposed on the critical path.
            # accum microbatches pipeline accum-1 exchanges behind
            # backward compute (the analytic ratio; the shardcheck
            # overlap dimension proves the scheduled one from the HLO)
            comm_ledger.set_overlap_ratio(
                (self.accum_steps - 1) / self.accum_steps
                if hier_mode == "overlap" and self.accum_steps > 1
                else 0.0
            )
            if hier and mode == "scatter":
                # hierarchical zero-1 (ops/hier_collectives.py): ICI
                # reduce-scatter within the slice, then a DCN
                # reduce-scatter whose cut carries only the slice-local
                # 1/dp_in shard and emits the owned 1/dp moment shard
                record_collective(
                    "dp.grad_reduce_scatter_ici", "reduce_scatter",
                    "dp", nbytes=grad_payload // dp_in, count=1,
                    per="loss_call", link="ici",
                )
                record_collective(
                    "dp.grad_reduce_scatter_dcn", "reduce_scatter",
                    "dp", nbytes=grad_payload // dp, count=1,
                    per="loss_call", link="dcn",
                )
            elif hier:
                # hierarchical replicated: RS (ici) → psum of the
                # 1/dp_in shard (the only DCN leg) → all-gather (ici)
                record_collective(
                    "dp.grad_reduce_scatter_ici", "reduce_scatter",
                    "dp", nbytes=grad_payload // dp_in, count=1,
                    per="loss_call", link="ici",
                )
                record_collective(
                    "dp.grad_allreduce_dcn", "psum", "dp",
                    nbytes=grad_payload // dp_in, count=1,
                    per="loss_call", link="dcn",
                )
                record_collective(
                    "dp.grad_all_gather_ici", "all_gather", "dp",
                    nbytes=grad_payload // dp_in, count=1,
                    per="loss_call", link="ici",
                )
            elif mode == "scatter":
                # explicit psum_scatter straight into the zero-1 layout
                # (train/zero1.py sharded_value_and_grad)
                record_collective(
                    "dp.grad_reduce_scatter", "reduce_scatter", "dp",
                    nbytes=grad_payload // dp, count=1, per="loss_call",
                )
            else:
                # replicated path AND gspmd zero-1: the dp reduction is
                # a psum (under gspmd zero-1 the backend's
                # allreduce-rewrite pass may lower it reduce-scatter;
                # the SC001 census records what actually happened)
                record_collective(
                    "dp.grad_allreduce", "psum", "dp",
                    nbytes=grad_payload, count=1, per="loss_call",
                )
            if mode != "off":
                if hier and mode == "scatter":
                    # hierarchized trailing gather (hier_param_gather):
                    # AG over slice FIRST — the DCN leg carries only
                    # the owned 1/dp shard per issue — then an ICI AG
                    # of the slice-complete 1/dp_in block
                    record_collective(
                        "dp.param_all_gather_dcn", "all_gather", "dp",
                        nbytes=grad_payload // dp, count=1, link="dcn",
                    )
                    record_collective(
                        "dp.param_all_gather_ici", "all_gather", "dp",
                        nbytes=grad_payload // dp_in, count=1,
                        link="ici",
                    )
                else:
                    # zero-1's second half: the dp-sharded updates
                    # gather back into full params once per step
                    record_collective(
                        "dp.param_all_gather", "all_gather", "dp",
                        nbytes=grad_payload // dp, count=1,
                    )

    def _build_step(
        self,
        mesh: Optional[Mesh] = None,
        mesh_config: Optional[MeshConfig] = None,
        out_shardings: Any = None,
    ):
        """The jitted step for ``(mesh, mesh_config)`` — defaults to the
        live pair. Parametrized so the warm-compile path can build the
        step for a mesh that is not (yet) the trainer's.

        ``out_shardings`` (AOT path): pin the output state to the input
        state's shardings. Left to XLA, some outputs come back sharded
        differently than they went in (observed: replicated norm-param
        adam moments returned tp-sharded) — which makes step N+1's
        input signature differ from step N's, silently recompiling
        under jit and hard-rejecting under an AOT executable."""
        mesh = mesh if mesh is not None else self.mesh
        mesh_config = (
            mesh_config if mesh_config is not None else self.mesh_config
        )
        accum = self._accum_for(mesh, mesh_config)
        # the loss must target the step's mesh: a loss closing over a
        # different mesh would bake foreign sharding constraints into
        # this program (cross-world AOT needs the factory form)
        loss_fn = (
            self.loss_factory(mesh)
            if self.loss_factory is not None
            else self.loss_fn
        )
        z1_mode = self._zero1_mode(mesh)
        hier_mode = self._hier_mode(mesh)
        hier = hier_mode != "flat"
        if z1_mode != "off" and self._params_avatar is None:
            # zero-1 derives its per-leaf layout from the param shapes;
            # a step built before any state exists (init_state and
            # record_avatars both set the avatar) has nothing to derive
            # from — and nothing it could run on either
            logger.warning(
                "zero-1 requested but no params avatar captured yet; "
                "building the replicated step"
            )
            z1_mode = "off"
        if hier_mode == "overlap" and self._params_avatar is None:
            # the bucket layout derives from the param shapes, same
            # dependency as zero-1's: degrade to the fused hierarchy
            # (which handles replicated leaves shape-free)
            logger.warning(
                "overlap collectives requested but no params avatar "
                "captured yet; building the fused hierarchical step"
            )
            hier_mode = "hier"
        is_spec = lambda s: isinstance(s, P)  # noqa: E731
        # the params' own layout, as placement targets: pins the f32
        # grad accumulator (a full extra param-sized pytree that used
        # to materialize with NO constraint — replicated under pure dp)
        # and, under zero-1, the post-update param all-gather
        param_put = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.p_specs,
            is_leaf=is_spec,
        )
        z1_grad_put = None
        z1_grad_fn = None
        if z1_mode != "off":
            axis_sizes = dict(mesh.shape)
            z1_grad_put = jax.tree.map(
                lambda s, av: NamedSharding(
                    mesh,
                    zero1.partition_spec(s, av.shape, axis_sizes) or s,
                ),
                self.p_specs, self._params_avatar, is_leaf=is_spec,
            )
        hier_grad_fn = None
        ov_compute = ov_exchange = None
        gather_fn = None
        if z1_mode == "scatter" and hier:
            # satellite of the hierarchy: the trailing param all-gather
            # runs AG(slice) → AG(dcn-free dp_in) → local unpermute
            # instead of the flat GSPMD gather over the whole dp axis,
            # so its DCN cut carries 1/dp_in of the params
            gather_fn = hier_collectives.hier_param_gather(
                mesh, self._slices_for(mesh), self.p_specs,
                self._params_avatar,
            )
        if hier_mode == "overlap":
            # latency-hiding split of the hierarchy: the eager half
            # (backward + ICI leg) and the deferred half (bucketed DCN
            # exchange) — the step below carries each microbatch's
            # exchange through the scan behind the NEXT backward
            ov_compute, ov_exchange = (
                hier_collectives.overlap_value_and_grad(
                    self.loss_factory(None), mesh,
                    self._slices_for(mesh), self.p_specs,
                    self._params_avatar,
                    zero1_scatter=(z1_mode == "scatter"),
                )
            )
        elif z1_mode == "scatter" and hier:
            # multislice pure-dp: the dp reduction is the two-stage
            # hierarchy — ICI reduce-scatter within the slice, then a
            # DCN reduce-scatter of only the slice-local shard straight
            # into the zero-1 layout (the dp4+2slice+zero1 contract
            # pins the link split)
            z1_grad_fn = hier_collectives.hier_value_and_grad(
                self.loss_factory(None), mesh, self._slices_for(mesh),
                self.p_specs, self._params_avatar, zero1_scatter=True,
            )
        elif z1_mode == "scatter":
            # pure-dp mesh: the loss+grad runs full-manual and the dp
            # reduction is an explicit psum_scatter straight into the
            # zero-1 layout — a REAL reduce-scatter in the lowered HLO
            # on every backend (the dp4+zero1 contract pins it)
            z1_grad_fn = zero1.sharded_value_and_grad(
                self.loss_factory(None), mesh, self.p_specs,
                self._params_avatar,
            )
        elif hier:
            # multislice, replicated weight update: same full-manual
            # engine, grads come back FULL — the DCN cut carries the
            # 1/dp_in shard instead of the whole gradient
            hier_grad_fn = hier_collectives.hier_value_and_grad(
                self.loss_factory(None), mesh, self._slices_for(mesh),
                self.p_specs, None, zero1_scatter=False,
            )

        def step(state, batch):
            # batch: any pytree whose leaves lead with (accum, micro*dp):
            # token arrays for the LM families, (images, labels) for CV
            grad_of = (
                z1_grad_fn if z1_grad_fn is not None
                else hier_grad_fn if hier_grad_fn is not None
                else jax.value_and_grad(loss_fn)
            )
            if ov_compute is not None and accum == 1:
                # single microbatch: no later backward to hide behind —
                # compute and exchange run back-to-back, which IS the
                # fused hierarchical reduction (same ops, bucketed)
                loss_sum, pend = ov_compute(
                    state["params"], jax.tree.map(lambda x: x[0], batch)
                )
                grads = ov_exchange(pend)
            elif ov_compute is not None:
                # the overlap pipeline, peeled: microbatch 0's backward
                # runs outside the scan so every scan iteration pairs
                # the PREVIOUS microbatch's deferred DCN exchange with
                # the CURRENT microbatch's backward — data-independent
                # halves the scheduler is free to run concurrently —
                # and the last exchange flushes after the scan.
                # Addition order matches the fused path exactly:
                # ((0+g0)+g1)+…+g_last into the f32 accumulator.
                acc_put = param_put if z1_mode == "off" else z1_grad_put
                zero = jax.tree.map(
                    lambda p, sh: jax.lax.with_sharding_constraint(
                        jnp.zeros(p.shape, jnp.float32), sh
                    ),
                    state["params"], acc_put,
                )
                loss0, pend0 = ov_compute(
                    state["params"], jax.tree.map(lambda x: x[0], batch)
                )

                def micro_overlap(carry, micro):
                    loss_sum, acc, pend = carry
                    g = ov_exchange(pend)  # previous micro's DCN leg
                    acc = jax.tree.map(jnp.add, acc, g)
                    loss, pend = ov_compute(state["params"], micro)
                    return (loss_sum + loss, acc, pend), None

                (loss_sum, acc, pend), _ = jax.lax.scan(
                    micro_overlap, (loss0, zero, pend0),
                    jax.tree.map(lambda x: x[1:], batch),
                )
                g = ov_exchange(pend)  # flush the last microbatch
                grads = jax.tree.map(jnp.add, acc, g)
            elif accum == 1:
                # single microbatch: no accumulator scan — grads stay in
                # param dtype and the f32 accumulation buffer (a full extra
                # param-sized pytree) is never allocated
                loss_sum, grads = grad_of(
                    state["params"], jax.tree.map(lambda x: x[0], batch)
                )
            else:
                # NB: the model losses may route through the chunked-CE
                # custom_vjp (ops/chunked_ce.py), which itself scans over
                # vocab chunks — custom_vjp rules are opaque to this outer
                # scan's AD, so the grad-accum scan composes with it the
                # same as with any primitive (and the f32 accumulator
                # below absorbs its param-dtype dw chunks via promotion)
                def micro_grads(carry, micro):
                    loss_sum, grads = carry
                    loss, g = grad_of(state["params"], micro)
                    grads = jax.tree.map(jnp.add, grads, g)
                    return (loss_sum + loss, grads), None

                # under zero-1 the accumulator itself lives dp-sharded
                # (1/dp of the f32 tree per device — the same layout the
                # scattered grads and the moments use)
                acc_put = param_put if z1_mode == "off" else z1_grad_put
                zero = jax.tree.map(
                    lambda p, sh: jax.lax.with_sharding_constraint(
                        jnp.zeros(p.shape, jnp.float32), sh
                    ),
                    state["params"], acc_put,
                )
                (loss_sum, grads), _ = jax.lax.scan(
                    micro_grads, (jnp.zeros((), jnp.float32), zero), batch
                )
            scale = 1.0 / accum
            grads = jax.tree.map(lambda g: g * scale, grads)
            if z1_mode != "off":
                # the optimizer update runs on the dp shard: grads,
                # moments (born sharded in init_state) and updates all
                # carry the zero-1 layout; clip's global norm reduces a
                # few scalars across dp, nothing param-sized
                grads = jax.tree.map(
                    jax.lax.with_sharding_constraint, grads, z1_grad_put
                )
            # named scope = the kernel ledger's attribution key: every
            # optimizer-update op carries it in HLO metadata, so the
            # per-kernel breakdown blames "optimizer", not "other"
            # (profiler/kernel_ledger.py)
            with jax.named_scope("optimizer_update"):
                updates, opt_state = self.optimizer.update(
                    grads, state["opt"], state["params"]
                )
                lr_scale = state.get("lr_scale")
                if lr_scale is not None:
                    updates = jax.tree.map(
                        lambda u: u * lr_scale.astype(u.dtype), updates
                    )
                if z1_mode != "off":
                    updates = jax.tree.map(
                        jax.lax.with_sharding_constraint, updates,
                        z1_grad_put,
                    )
                params = optax.apply_updates(state["params"], updates)
            if z1_mode != "off" and gather_fn is not None:
                # zero-1's second half, hierarchized: pin the summed
                # params to the zero-1 layout (the add runs on the
                # owned shard) and gather explicitly — AG over slice
                # first, so the DCN cut carries 1/dp_in of the params
                # instead of the flat gather's full (1 − 1/s) share
                params = jax.tree.map(
                    jax.lax.with_sharding_constraint, params,
                    z1_grad_put,
                )
                params = gather_fn(params)
                params = jax.tree.map(
                    jax.lax.with_sharding_constraint, params, param_put
                )
            elif z1_mode != "off":
                # zero-1's second half: the dp-sharded updates gather
                # back into the params' own layout — the param
                # all-gather that replaces the grad all-reduce's
                # broadcast half
                params = jax.tree.map(
                    jax.lax.with_sharding_constraint, params, param_put
                )
            out = {
                "params": params,
                "opt": opt_state,
                "step": state["step"] + 1,
            }
            if lr_scale is not None:
                out["lr_scale"] = lr_scale
            return out, loss_sum * scale

        # state keeps the shardings its arrays already carry (params placed
        # by the caller, opt state born sharded in init_state).
        batch_sh = NamedSharding(mesh, P(None, *batch_spec()))
        kwargs = {}
        if out_shardings is not None:
            kwargs["out_shardings"] = out_shardings
        return jax.jit(
            step,
            in_shardings=(None, batch_sh),
            donate_argnums=(0,),
            **kwargs,
        )

    # ---- warm compile (train/warm_compile.py) --------------------------
    def record_avatars(self, state: dict, batch: PyTree):
        """Capture mesh-independent shape/dtype/spec stand-ins for the
        train state and batch. Called automatically on the first
        ``step()``; call it explicitly to AOT-compile before any live
        step has run."""
        self._state_avatar = jax.tree.map(_avatar_of, state)
        self._params_avatar = jax.tree.map(_avatar_of, state["params"])
        self._batch_avatar = jax.tree.map(_avatar_of, batch)

    def _config_hash(self, mesh: Mesh) -> str:
        """Model/config identity for the compile ledger: state-avatar
        shapes+dtypes (the program's real input signature — a model
        change or dtype change re-keys it) plus the trainer knobs that
        shape the step. World-independent except for the zero-1 marker,
        which keys on what the step for ``mesh`` actually builds."""
        parts = [
            f"gb={self.tc.global_batch_size}",
            f"mb={self.tc.micro_batch_size}",
            f"lr={self.tc.learning_rate}",
            f"wd={self.tc.weight_decay}",
            f"clip={self.tc.grad_clip}",
        ]
        if self._zero1_mode(mesh) != "off":
            # asymmetric on purpose: contracts and compile-ledger keys
            # generated before zero-1 existed keep their hashes while
            # the feature is off. Keyed on the EFFECTIVE mode, not the
            # request: a mesh where zero-1 cannot apply (dp<=1, pp>1)
            # builds the replicated program and must hash like it —
            # else an exported DLROVER_TPU_ZERO1=1 makes that program
            # miss its own checked-in plain contract (a spurious
            # config_hash-mismatch failure, a veto under strict mode)
            parts.append("zero1=1")
        hier_mode = self._hier_mode(mesh)
        if hier_mode != "flat":
            # same asymmetry: the hierarchical step is a genuinely
            # different program (its own +Nslice contract); flat-path
            # hashes — including flat-on-a-multislice-mesh, the
            # kill-switch fallback — stay what they always were
            parts.append(f"hier={self._slices_for(mesh)}")
        if hier_mode == "overlap":
            # the overlap schedule lowers a different program again
            # (bucketed exchanges, peeled scan): its own +overlap
            # contract, its own hash
            parts.append("overlap=1")
        for av in jax.tree.leaves(self._state_avatar):
            parts.append(f"{av.shape}/{av.dtype}")
        return warm_compile.signature_hash(parts)

    def _step_signature(
        self, mesh: Mesh, mesh_config: MeshConfig, accum: int
    ) -> Tuple[str, str]:
        """(in-process cache key, ledger config-hash). The cache key
        pins the exact device assignment: an AOT executable only runs
        on the devices it was compiled for, so a mesh over different
        devices must miss here (and fall through to the persistent
        cache, which keys on topology, not identity)."""
        config_hash = self._config_hash(mesh)
        parts = [
            config_hash,
            str(sorted(mesh.shape.items())),
            # the resolved logical config too: two MeshConfigs resolving
            # over the same physical mesh shape must never share an
            # executable if any future knob differentiates their programs
            str(sorted(mesh_config.resolve(mesh.size).shape().items())),
            str(tuple(d.id for d in mesh.devices.flat)),
            f"accum={accum}",
            # scatter and gspmd lower different programs, and a flag
            # flip between builds must never warm-hit a stale executable
            f"zero1={self._zero1_mode(mesh)}",
            # flat and hier lower different programs too — and the SAME
            # device set re-seated as a different slice count must miss
            f"hier={self._hier_mode(mesh)}x{self._slices_for(mesh)}",
        ]
        for av in jax.tree.leaves(self._state_avatar_for(mesh)):
            parts.append(f"{av.spec}")
        for av in jax.tree.leaves(self._batch_avatar):
            parts.append(f"{av.shape[2:]}/{av.dtype}")
        return warm_compile.signature_hash(parts), config_hash

    def _avatar_args(self, mesh: Mesh, mesh_config: MeshConfig, accum: int):
        """ShapeDtypeStruct (state, batch) pair for ``jit.lower`` on a
        target mesh: state keeps its global shapes with specs re-bound
        to the target mesh; batch leading dims re-derive from the
        target's accumulation split."""
        dp = mesh_config.resolve(mesh.size).data_parallel_size
        # zero-1 aware: the optimizer-state specs re-derive against the
        # TARGET mesh (its dp size decides which dims shard), so the
        # AOT signature, the transfer target and the restore placement
        # all come from the same derivation
        avatar = self._state_avatar_for(mesh)
        state_av = jax.tree.map(
            lambda av: jax.ShapeDtypeStruct(
                av.shape, av.dtype, sharding=NamedSharding(mesh, av.spec)
            ),
            avatar,
        )
        bspec = NamedSharding(mesh, P(None, *batch_spec()))
        batch_av = jax.tree.map(
            lambda av: jax.ShapeDtypeStruct(
                (accum, self.tc.micro_batch_size * dp) + av.shape[2:],
                av.dtype,
                sharding=bspec,
            ),
            self._batch_avatar,
        )
        # output state pinned to the INPUT shardings (same keys the step
        # emits), loss replicated: keeps step N+1's input signature
        # identical to step N's — see _build_step
        out_state_sh = {
            k: jax.tree.map(
                lambda av: NamedSharding(mesh, av.spec),
                avatar[k],
            )
            for k in ("params", "opt", "step", "lr_scale")
            if k in avatar
        }
        out_sh = (out_state_sh, NamedSharding(mesh, P()))
        return state_av, batch_av, out_sh

    @_pin_zero1
    def lower_step(
        self,
        mesh: Mesh,
        mesh_config: MeshConfig,
        source: str = "cold",
    ) -> Tuple[Any, dict]:
        """AOT-build the step for ``(mesh, mesh_config)`` — which need
        not be live — via ``jit.lower(avatars).compile()``. Returns
        ``(compiled, info)``; ``info`` records cache disposition and
        compile seconds, which also land in the compile ledger. The
        compiled executable is cached in-process so a later remesh to
        this signature (or a repeat call) is a warm hit; with the
        persistent compilation cache enabled the XLA compile itself is
        also a disk hit across process restarts.

        Requires avatars (one live ``step()`` or ``record_avatars``)."""
        if self._state_avatar is None or self._batch_avatar is None:
            raise RuntimeError(
                "lower_step needs state/batch avatars: run one step() or "
                "call record_avatars(state, batch) first"
            )
        accum = self._accum_for(mesh, mesh_config)
        sig, config_hash = self._step_signature(mesh, mesh_config, accum)
        cached = self.warm.get(sig)
        if cached is not None:
            warm_compile.compile_ledger.record(
                mesh.size, config_hash, 0.0, "warm"
            )
            return cached, {
                "cache": "warm", "compile_s": 0.0,
                "world": mesh.size, "config_hash": config_hash,
            }
        state_av, batch_av, out_sh = self._avatar_args(
            mesh, mesh_config, accum
        )
        t0 = time.perf_counter()
        m0 = time.monotonic()
        lowered = self._build_step(
            mesh, mesh_config, out_shardings=out_sh
        ).lower(state_av, batch_av)
        compiled = lowered.compile()
        dt = time.perf_counter() - t0
        # trace spine: every real XLA compile (cold AND speculative) is
        # a span — warm hits returned above and cost nothing
        trace.record(
            "compile", f"lower_step.w{mesh.size}", m0, dt,
            world=mesh.size, source=source, config=config_hash,
        )
        # IR-level analysis of the program just built (lint/shardcheck),
        # opted in via DLROVER_TPU_SHARDCHECK. Runs for EVERY lowering —
        # including the speculative neighbor worlds — so a sharding
        # regression on the post-resize mesh is caught before the
        # resize happens, not at its first step. Strict mode raises
        # here, which keeps the poisoned executable out of the cache.
        self._maybe_shardcheck(lowered, compiled, mesh, mesh_config,
                               config_hash)
        # memory-side analysis of the same build (lint/memcheck.py),
        # opted in via DLROVER_TPU_MEMCHECK: the per-device memory
        # model diffed against its contract and the device-class HBM
        # budget. Strict mode raises BEFORE the cache put, like
        # shardcheck — an executable that cannot fit its budget never
        # becomes a warm hit.
        self._maybe_memcheck(compiled, mesh, mesh_config, config_hash)
        self.warm.put(sig, compiled)
        warm_compile.compile_ledger.record(mesh.size, config_hash, dt, source)
        return compiled, {
            "cache": "miss", "compile_s": dt,
            "world": mesh.size, "config_hash": config_hash,
        }

    # ---- shardcheck (lint/shardcheck.py) -------------------------------
    def _program_of(
        self, lowered, compiled, mesh, config_hash: str,
        mesh_config: Optional[MeshConfig] = None,
    ):
        """Build the shardcheck analysis context from one lowering."""
        from dlrover_tpu.lint import shardcheck

        hints = dict(self.shardcheck_hints)
        if "seq_len" not in hints and self._batch_avatar is not None:
            # token batches lead with (accum, micro*dp, seq): the
            # trailing dim of a rank-3 integer leaf is the sequence
            for av in jax.tree.leaves(self._batch_avatar):
                if len(av.shape) == 3 and np.issubdtype(
                    av.dtype, np.integer
                ):
                    hints["seq_len"] = int(av.shape[2])
                    break
        z1 = self._zero1_mode(mesh) != "off"
        overlap = self._hier_mode(mesh) == "overlap"
        return shardcheck.StepProgram(
            label="hlo:" + self._contract_spec(mesh),
            stablehlo=lowered.as_text(),
            hlo=compiled.as_text(),
            axis_sizes=dict(mesh.shape),
            seq_len=hints.get("seq_len"),
            vocab=hints.get("vocab"),
            world=mesh.size,
            config_hash=config_hash,
            zero1=z1,
            # slice topology for the per-link (ici/dcn) census
            # attribution — passed whenever the mesh is multislice, so
            # even a flat (kill-switch) program's census shows what the
            # slow link carries
            n_slices=self._slices_for(mesh),
            # overlap programs additionally carry the exposed-vs-
            # overlapped DCN-bytes contract dimension
            overlap=overlap,
            accum_steps=self._accum_for(
                mesh,
                mesh_config if mesh_config is not None
                else self.mesh_config,
            ),
            # pipeline-schedule geometry for the SC008 bubble-fraction
            # contract dimension — supplied by callers that know the
            # model's schedule knobs (contract_model, bench)
            pp_schedule=hints.get("pp_schedule"),
        )

    def world_descriptor(self, mesh: Optional[Mesh] = None) -> WorldDescriptor:
        """The ONE description of the world this trainer builds for
        ``mesh`` (default: live): resolved mesh axes x slice count x
        the effective zero-1/hier program modes
        (:class:`~dlrover_tpu.common.world.WorldDescriptor`). Contract
        specs, transfer-target checks and the planner's candidate
        vocabulary all read this instead of re-deriving world shape."""
        mesh = mesh if mesh is not None else self.mesh
        mode = self._hier_mode(mesh)
        hier = mode != "flat"
        return WorldDescriptor.from_axis_sizes(
            dict(mesh.shape),
            n_slices=self._slices_for(mesh) if hier else 1,
            zero1=self._zero1_mode(mesh) != "off",
            hier=hier,
            overlap=(mode == "overlap"),
        )

    def _contract_spec(self, mesh: Mesh) -> str:
        """The SC001 contract key for the program this trainer builds
        on ``mesh``: the mesh spec, ``+Nslice`` when the hierarchical
        strategy is active (a different program with its own census),
        ``+zero1`` when weight-update sharding is on. A multislice mesh
        running the FLAT path keys the plain spec — its census is the
        single-slice program's."""
        return self.world_descriptor(mesh).spec

    def _maybe_shardcheck(
        self, lowered, compiled, mesh, mesh_config, config_hash: str
    ):
        """Lower-time hook: ``DLROVER_TPU_SHARDCHECK`` 0=off, 1=warn,
        2=strict (raise — the build is rejected and nothing enters the
        executable cache). SC001 runs only when a contract for this
        mesh spec exists (``DLROVER_TPU_SHARDCHECK_CONTRACTS`` dir,
        default: the checked-in contracts)."""
        mode = int(flags.SHARDCHECK.get())
        if not mode:
            return
        from dlrover_tpu.lint import shardcheck

        try:
            program = self._program_of(
                lowered, compiled, mesh, config_hash, mesh_config
            )
            contracts_dir = (
                flags.SHARDCHECK_CONTRACTS.get()
                or shardcheck.DEFAULT_CONTRACTS_DIR
            )
            contract = shardcheck.load_contract(
                contracts_dir, self._contract_spec(mesh)
            )
            if (
                contract is not None
                and contract.get("config_hash")
                and contract["config_hash"] != program.config_hash
            ):
                # a contract for the same mesh but a DIFFERENT program
                # (e.g. the checked-in tiny contract-model censuses vs a
                # real model training on dp4): at lower time that means
                # "no contract for this program", not a violation — the
                # CLI, where the program is pinned, keeps the mismatch
                # loud so stale contracts get regenerated
                logger.info(
                    "shardcheck: contract for %s is for config %s (this "
                    "program: %s); SC001 skipped",
                    program.label, contract["config_hash"],
                    program.config_hash,
                )
                contract = None
            violations = shardcheck.check_program(program, contract)
        except Exception as e:
            if isinstance(e, shardcheck.ShardcheckError):
                raise
            # analysis breakage must never take down a training build
            logger.warning("shardcheck hook failed: %s", e)
            return
        if not violations:
            logger.info(
                "shardcheck: %s clean (%s contract)",
                program.label, "with" if contract else "no",
            )
            return
        if mode >= 2:
            raise shardcheck.ShardcheckError(violations)
        for v in violations:
            logger.warning("shardcheck: %s", v.format())

    # ---- memcheck (lint/memcheck.py) -----------------------------------
    def _memcheck_leaves(self, tree):
        """Flatten an avatar pytree into the plain
        :class:`~dlrover_tpu.lint.memcheck.LeafAvatar` records the
        jax-free memory model consumes: pytree path, global shape,
        dtype name, and the flattened mesh axes of the leaf's
        ``PartitionSpec``."""
        from dlrover_tpu.lint import memcheck

        records = []
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, av in flat:
            spec = getattr(getattr(av, "sharding", None), "spec", None)
            axes = []
            for entry in tuple(spec) if spec is not None else ():
                if entry is None:
                    continue
                if isinstance(entry, (tuple, list)):
                    axes.extend(str(a) for a in entry)
                else:
                    axes.append(str(entry))
            records.append(memcheck.LeafAvatar(
                path=jax.tree_util.keystr(path),
                shape=tuple(int(d) for d in av.shape),
                dtype=np.dtype(av.dtype).name,
                sharded_axes=tuple(axes),
            ))
        return records

    def _memcheck_payload_of(
        self, compiled, mesh, mesh_config, config_hash: str
    ) -> dict:
        """The static per-device memory model of one compiled build:
        guarded ``memory_analysis()`` bytes plus the analytic per-leaf
        breakdown that explains them (lint/memcheck.py)."""
        from dlrover_tpu.lint import memcheck

        accum = self._accum_for(mesh, mesh_config)
        state_av, batch_av, _ = self._avatar_args(mesh, mesh_config, accum)
        spec = self._contract_spec(mesh)
        measured = memcheck.read_memory_analysis(
            compiled, label=f"mem:{spec}"
        )
        components = memcheck.analytic_components(
            self._memcheck_leaves(state_av),
            self._memcheck_leaves(batch_av),
            dict(mesh.shape),
            measured,
        )
        payload = {
            "mesh_spec": spec,
            "config_hash": config_hash,
            "world": int(mesh.size),
            "axis_sizes": {a: int(s) for a, s in dict(mesh.shape).items()},
            "components": components,
            "peak_bytes": memcheck.analytic_peak_bytes(components),
            "measured": measured,
        }
        delta = memcheck.explain_delta_frac(components, measured)
        if delta is not None:
            payload["argument_delta_frac"] = round(delta, 4)
        return payload

    @_pin_zero1
    def memcheck_payload(self, mesh=None, mesh_config=None) -> dict:
        """Build (AOT, host-only — warm cache makes repeats free) the
        step for ``(mesh, mesh_config)`` and return its memory payload.
        The CLI ``--mem`` mode and bench ``detail.hbm`` entry point:
        like ``step_ir``, the substrate for any admissible world comes
        from the avatars, so no TPU — and no live training process —
        is needed."""
        mesh = mesh if mesh is not None else self.mesh
        mesh_config = (
            mesh_config if mesh_config is not None else self.mesh_config
        )
        compiled, info = self.lower_step(mesh, mesh_config,
                                         source="memcheck")
        return self._memcheck_payload_of(
            compiled, mesh, mesh_config, info["config_hash"]
        )

    def _headroom_oracle(
        self, device_class: str = "", budget_gb: float = 0.0
    ):
        """The live program's static headroom oracle: the analytic
        components at the CURRENT mesh lifted to global totals, so any
        candidate world prices out without compiling it
        (:class:`~dlrover_tpu.lint.memcheck.HeadroomOracle`)."""
        from dlrover_tpu.lint import memcheck

        accum = self._accum_for(self.mesh, self.mesh_config)
        state_av, batch_av, _ = self._avatar_args(
            self.mesh, self.mesh_config, accum
        )
        components = memcheck.analytic_components(
            self._memcheck_leaves(state_av),
            self._memcheck_leaves(batch_av),
            dict(self.mesh.shape),
        )
        wd = self.world_descriptor(self.mesh)
        return memcheck.HeadroomOracle.from_components(
            components, wd,
            device_class=device_class, budget_gb=budget_gb,
            # candidates run the current program family: a bare-dp
            # neighbor descriptor still packs moments like this build
            assume_zero1=wd.zero1,
        )

    def _maybe_memcheck(self, compiled, mesh, mesh_config,
                        config_hash: str):
        """Lower-time hook, fifth invariant layer:
        ``DLROVER_TPU_MEMCHECK`` 0=off, 1=warn, 2=strict (raise — the
        build is rejected and nothing enters the executable cache).
        MC001 runs only when a ``mem-<spec>`` contract for this program
        exists (``DLROVER_TPU_MEMCHECK_CONTRACTS`` dir, default: the
        checked-in contracts); MC002 only when a device class or
        explicit budget is configured."""
        mode = int(flags.MEMCHECK.get())
        if not mode:
            return
        from dlrover_tpu.lint import memcheck

        try:
            payload = self._memcheck_payload_of(
                compiled, mesh, mesh_config, config_hash
            )
            label = "mem:" + payload["mesh_spec"]
            contracts_dir = (
                flags.MEMCHECK_CONTRACTS.get()
                or memcheck.DEFAULT_CONTRACTS_DIR
            )
            contract = memcheck.load_mem_contract(
                contracts_dir, payload["mesh_spec"]
            )
            if (
                contract is not None
                and contract.get("config_hash")
                and contract["config_hash"] != payload["config_hash"]
            ):
                # same mesh, different program (the checked-in tiny
                # contract-model breakdowns vs a real model): at lower
                # time that means "no contract", not a violation —
                # mirror of the shardcheck hook's rule
                logger.info(
                    "memcheck: contract for %s is for config %s (this "
                    "program: %s); MC001 skipped",
                    label, contract["config_hash"],
                    payload["config_hash"],
                )
                contract = None
            violations = []
            if contract is not None:
                violations.extend(memcheck.check_components(
                    payload["components"], payload["peak_bytes"],
                    contract, label=label,
                ))
            violations.extend(memcheck.check_budget(
                payload["peak_bytes"],
                device_class=flags.MEMCHECK_DEVICE_CLASS.get(),
                budget_gb=float(flags.MEMCHECK_BUDGET_GB.get()),
                label=label,
            ))
        except Exception as e:
            if isinstance(e, memcheck.MemcheckError):
                raise
            # analysis breakage must never take down a training build
            logger.warning("memcheck hook failed: %s", e)
            return
        if not violations:
            logger.info(
                "memcheck: %s clean (%s contract, peak %d bytes/device)",
                label, "with" if contract else "no",
                payload["peak_bytes"],
            )
            return
        if mode >= 2:
            raise memcheck.MemcheckError(violations)
        for v in violations:
            logger.warning("memcheck: %s", v.format())

    @_pin_zero1
    def step_ir(self, mesh=None, mesh_config=None, pinned: bool = True):
        """Lower (and compile — on the host, no device execution) the
        step for ``(mesh, mesh_config)`` and return the shardcheck
        ``StepProgram`` for it. This is the CLI / bench / CI entry: the
        analysis substrate for any admissible world comes from the same
        avatars the warm-compile path lowers from, so none of it needs
        a live training process — or a TPU.

        ``pinned=False`` builds the step WITHOUT pinned out_shardings
        (the kill-switch jit path), which SC004 flags — used by tests
        to demonstrate the drift gate."""
        mesh = mesh if mesh is not None else self.mesh
        mesh_config = (
            mesh_config if mesh_config is not None else self.mesh_config
        )
        if self._state_avatar is None or self._batch_avatar is None:
            raise RuntimeError(
                "step_ir needs state/batch avatars: run one step() or "
                "call record_avatars(state, batch) first"
            )
        accum = self._accum_for(mesh, mesh_config)
        _, config_hash = self._step_signature(mesh, mesh_config, accum)
        state_av, batch_av, out_sh = self._avatar_args(
            mesh, mesh_config, accum
        )
        lowered = self._build_step(
            mesh, mesh_config, out_shardings=out_sh if pinned else None
        ).lower(state_av, batch_av)
        return self._program_of(
            lowered, lowered.compile(), mesh, config_hash, mesh_config
        )

    def _acquire_step_fn(self):
        """The step for the live mesh: plain jit when the kill-switch
        is off; otherwise the AOT path — in-process warm hit when this
        signature compiled before (speculative neighbor compile, a
        remesh back to a previous world), cold AOT compile otherwise —
        followed by a speculative kick for the neighbor worlds."""
        self._last_build_info = {"cache": "jit", "compile_s": None}
        if not warm_compile.warm_compile_enabled():
            return self._build_step()
        try:
            fn, info = self.lower_step(self.mesh, self.mesh_config)
        except Exception as e:
            # strict shardcheck/memcheck is a deliberate veto of this
            # program — falling back to plain jit would run the exact
            # program the check just rejected
            from dlrover_tpu.lint import memcheck, shardcheck

            if isinstance(
                e, (shardcheck.ShardcheckError, memcheck.MemcheckError)
            ):
                raise
            logger.exception(
                "AOT step build failed; falling back to plain jit"
            )
            return self._build_step()
        self._last_build_info = info
        if info["cache"] == "warm":
            logger.info(
                "step build: WARM (AOT cache hit, world=%d)", self.mesh.size
            )
        else:
            logger.info(
                "step build: cold compile %.2fs (world=%d config=%s)",
                info["compile_s"], self.mesh.size, info["config_hash"],
            )
        self._maybe_speculate()
        return fn

    def _descriptor_for_world(
        self, world: int, n_slices: Optional[int] = None
    ) -> Optional[WorldDescriptor]:
        """Refit this trainer's mesh config onto ``world`` devices and
        describe the result, or None when the world is inadmissible
        (model axes don't fit, global-batch invariant broken, devices
        unavailable) — the same filters ``neighbor_worlds`` applies, so
        a planner hint survives exactly when a neighbor would."""
        from dlrover_tpu.parallel.mesh import remesh as remesh_config

        if world <= 0 or world > jax.device_count():
            return None
        slices = (
            max(1, int(n_slices)) if n_slices is not None
            else self._slices_for_size(world)
        )
        if slices > 1 and world % slices:
            return None
        try:
            resolved = remesh_config(self.mesh_config, world).resolve(world)
        except ValueError:
            return None
        dp = resolved.data_parallel_size
        if self.tc.global_batch_size % (self.tc.micro_batch_size * dp):
            return None
        if slices > 1 and dp % slices:
            return None
        try:
            return WorldDescriptor.from_axis_sizes(
                resolved.shape(), n_slices=slices, hier=slices > 1
            )
        except ValueError:
            return None

    def set_speculation_hint(self, hint, n_slices: Optional[int] = None):
        """Planner-directed speculation (brain/planner.py): tell the
        warm compiler which EXACT world the master's goodput planner
        intends to resize to next, so the background thread compiles
        that target first — a planner-directed resize then lands on a
        pre-compiled executable instead of hoping the blind ±node/±slice
        neighbor enumeration guessed right.

        ``hint``: a :class:`WorldDescriptor`, a device-world size (the
        caller converts the master's node-level hint via its local
        device count), or None to clear. Inadmissible hints (model axes
        don't fit, batch invariant broken) are dropped — the neighbor
        heuristic remains the fallback either way."""
        if hint is None:
            self._speculation_hint = None
            return
        if isinstance(hint, WorldDescriptor):
            wd = self._descriptor_for_world(
                hint.world_size, n_slices=hint.n_slices
            )
        else:
            wd = self._descriptor_for_world(int(hint), n_slices=n_slices)
        if wd is not None and wd.world_size == self.mesh.size:
            wd = None  # already there — nothing to pre-compile
        if wd is not None:
            logger.info(
                "speculation hint armed: planner intends world %s",
                wd.spec,
            )
        self._speculation_hint = wd

    def _maybe_speculate(self):
        """After a successful live build, compile the step for likely
        next worlds in the background (bounded daemon thread; skips
        when the kill-switch is off or no persistent cache dir is
        configured — see WarmCompiler.speculate). A planner speculation
        hint (``set_speculation_hint``) takes the FIRST slot — the
        planner said which world comes next, so that exact target gets
        compiled before any blind neighbor; without a hint the neighbor
        enumeration behaves exactly as before. Needs the factory form
        of the loss: a plain ``loss_fn`` may close over the live mesh
        and cannot be retargeted to another world."""
        if self.loss_factory is None:
            return
        try:
            targets = warm_compile.neighbor_worlds(
                self.mesh.size,
                self.mesh_config,
                n_devices_available=jax.device_count(),
                devices_per_node=jax.local_device_count(),
                global_batch_size=self.tc.global_batch_size,
                micro_batch_size=self.tc.micro_batch_size,
                n_slices=self.n_slices,
            )
        except Exception:
            return
        hint = self._speculation_hint
        if hint is not None and hint.world_size != self.mesh.size:
            targets = [hint] + [
                t for t in targets if t.world_size != hint.world_size
            ]
        targets = self._filter_speculation_targets(targets)
        if not targets:
            return

        def compile_for_world(wd: WorldDescriptor):
            from dlrover_tpu.parallel.mesh import config_for, mesh_for

            # multislice: a neighbor world is a whole number of slices
            # (the descriptor checked it) — mesh_for builds it
            # slice-major so the speculated executable IS the
            # post-slice-loss program (the hierarchical strategy and
            # the ici/dcn layout both key on it) and re-checks the
            # built mesh against the descriptor
            mesh = mesh_for(wd)
            _, info = self.lower_step(
                mesh, config_for(wd), source="speculative"
            )
            # no log once shutdown began: the interpreter may have
            # closed the log streams under this daemon thread
            if info["cache"] == "miss" and not self.warm._stop.is_set():
                logger.info(
                    "speculative compile: world=%s ready in %.2fs",
                    wd.spec, info["compile_s"],
                )

        if self.warm.speculate(targets, compile_for_world):
            logger.info(
                "speculating step compiles for worlds %s%s",
                [t.spec for t in targets],
                " (planner-hinted)" if hint is not None else "",
            )

    def _filter_speculation_targets(self, targets):
        """memcheck's static headroom oracle over the speculative
        worlds: drop neighbors whose predicted per-device peak cannot
        fit the configured device-class budget, so no AOT compile is
        wasted on a world the planner would oom-veto anyway. Unarmed
        (no ``DLROVER_TPU_MEMCHECK_DEVICE_CLASS`` / ``_BUDGET_GB``) ->
        targets pass through untouched."""
        from dlrover_tpu.lint import memcheck

        device_class = flags.MEMCHECK_DEVICE_CLASS.get()
        budget_gb = float(flags.MEMCHECK_BUDGET_GB.get())
        if memcheck.budget_bytes(device_class, budget_gb) <= 0:
            return targets
        try:
            oracle = self._headroom_oracle(
                device_class=device_class, budget_gb=budget_gb
            )
        except Exception as e:
            logger.warning("memcheck speculation oracle failed: %s", e)
            return targets
        kept = []
        for wd in targets:
            verdict = oracle.fits(wd)
            if verdict["fits"]:
                kept.append(wd)
            else:
                logger.info(
                    "speculation: skipping world %s (memcheck oom "
                    "veto: predicted %d > usable %d bytes)",
                    wd.spec, verdict["peak_bytes"],
                    verdict["usable_bytes"],
                )
        return kept

    def apply_paral_config(self, state: dict, config: dict) -> dict:
        """Apply a master-pushed runtime config to the train state: a new
        ``optimizer_learning_rate`` becomes an update multiplier relative
        to the configured base lr (the schedule shape is preserved). The
        dataloader fields are consumed by ``ElasticDataLoader``."""
        # host dict read, not a device sync  # graftlint: disable=JG002
        new_lr = float(config.get("optimizer_learning_rate", 0.0) or 0.0)
        if new_lr > 0 and self.tc.learning_rate > 0 and "lr_scale" in state:
            scale = new_lr / self.tc.learning_rate
            # intentional sync: throttled to every poll interval (~100
            # steps) by poll_runtime_config  # graftlint: disable=JG002
            if abs(scale - float(state["lr_scale"])) > 1e-9:
                state = {
                    **state,
                    "lr_scale": jax.device_put(
                        jnp.asarray(scale, jnp.float32),
                        NamedSharding(self.mesh, P()),
                    ),
                }
                from dlrover_tpu.common.log import logger as _logger

                _logger.info(
                    "runtime lr update: base=%g -> %g (scale %.4f)",
                    self.tc.learning_rate, new_lr, scale,
                )
        return state

    def poll_runtime_config(
        self, state: dict, every_steps: int = 100
    ) -> dict:
        """Cheap per-step hook: every ``every_steps`` host steps re-read
        the agent-pushed paral config file and apply optimizer changes."""
        if self._host_step % max(1, every_steps):
            return state
        from dlrover_tpu.agent.paral_config_tuner import read_paral_config

        config = read_paral_config()
        version = int(config.get("optimizer_version", 0) or
                      config.get("dataloader_version", 0) or 0)
        if config and version != self._applied_config_version:
            self._applied_config_version = version
            state = self.apply_paral_config(state, config)
        # the goodput planner's speculation hint rides the same
        # throttled cadence (brain/planner.py): one cheap membership
        # poll per ~every_steps host steps arms the warm compiler with
        # the exact world the planner intends next, so the directed
        # resize lands warm. Contexts without the helper (older stubs,
        # tests) are skipped; failures never touch the training loop.
        if self.worker_ctx is not None and hasattr(
            self.worker_ctx, "poll_speculation_hint"
        ):
            try:
                self.worker_ctx.poll_speculation_hint(self)
            except Exception:
                pass
        return state

    def eval_step(self, state: dict, batch) -> jnp.ndarray:
        """Loss of one batch WITHOUT touching the train state: jitted
        forward-only, no donation (state survives), batch shaped
        (micro*dp, ...) — one microbatch row of ``step_batch_shape``."""
        if self._eval_fn is None:
            bspec = batch_spec()
            self._eval_fn = jax.jit(
                lambda params, b: self.loss_fn(params, b),
                in_shardings=(
                    None, NamedSharding(self.mesh, P(*bspec)),
                ),
            )
        return self._eval_fn(state["params"], batch)

    def evaluate(self, state: dict, batches) -> float:
        """Mean loss over an iterable of eval batches (each shaped like
        one ``step_batch_shape`` row). The evaluator-role analogue of the
        reference's estimator evaluation: the same jitted graph and mesh
        as training, params untouched, no optimizer state involved.

        Losses accumulate ON DEVICE and convert to a host float once at
        the end: a per-batch ``float()`` would block on every batch's
        just-dispatched forward, serializing host and device (async
        dispatch is the whole point of the jitted eval)."""
        total = None
        count = 0
        with trace.span("eval", "evaluate"):
            for batch in batches:
                loss = self.eval_step(state, batch)
                total = loss if total is None else total + loss
                count += 1
        if count == 0:
            # 0.0 would read as a perfect loss to early-stopping logic
            raise ValueError(
                "evaluate() got zero batches (eval dataset smaller than "
                "one batch under drop_last?)"
            )
        return float(total) / count

    def step(self, state: dict, batch) -> Tuple[dict, jnp.ndarray]:
        """One optimizer step = ``accum_steps`` microbatches.

        ``batch``: any pytree whose leaves lead with (accum_steps,
        micro*dp, ...) — int32 token arrays for the LM families,
        (images, labels) tuples for CV."""
        first_build = self._step_fn is None
        build_t0 = time.perf_counter()
        if first_build:
            self.record_avatars(state, batch)
            self._step_fn = self._acquire_step_fn()
        if self.worker_ctx is not None:
            state = self.poll_runtime_config(state)
        # step wall clock, measured WITHOUT a device sync: dispatch of
        # step N blocks on donation until step N-1's buffers free, so in
        # steady state this converges to the device step time. Feeds the
        # per-rank digest and (when the spine is on) a `step` span.
        step_m0 = time.monotonic()
        try:
            new_state, loss = self._step_fn(state, batch)
        except (ValueError, TypeError) as e:
            # an AOT executable (warm path) is stricter than jit: a
            # committed input with a different sharding raises
            # ValueError("...does not match..."), and a batch with a
            # different shape/dtype raises TypeError("Argument types
            # differ from the types for which this computation was
            # compiled") where jit would silently recompile. Rebuild
            # via plain jit once rather than fail training over it.
            msg = str(e)
            if not warm_compile.warm_compile_enabled() or not (
                "does not match" in msg
                or "differ from the types" in msg
            ):
                raise
            logger.warning(
                "AOT step rejected input shardings (%s); rebuilding with "
                "plain jit", str(e)[:200],
            )
            # evict the poisoned executable: a later remesh back to this
            # signature must not warm-hit it and fail again
            try:
                sig, _ = self._step_signature(
                    self.mesh, self.mesh_config, self.accum_steps
                )
                self.warm.evict(sig)
            except Exception:
                pass
            # the AOT info (possibly a 0.0s warm hit) no longer describes
            # this build: route _finalize_resize to the measured branch
            self._last_build_info = {"cache": "jit", "compile_s": None}
            self._step_fn = self._build_step()
            new_state, loss = self._step_fn(state, batch)
        step_dur = time.monotonic() - step_m0
        if first_build and self._pending_resize is not None:
            self._finalize_resize(loss, build_t0)
        # host-side step counter: reading new_state["step"] would block on
        # the just-dispatched computation and kill async dispatch
        self._host_step += 1
        if not first_build:
            # the first call's wall is compile/build-dominated — keeping
            # it out of the digest stops every (re)start from feeding
            # the straggler detector one giant sample per rank
            self.step_digest.add(step_dur)
            trace.record(
                "step", "train_step", step_m0, step_dur,
                host_step=self._host_step,
            )
        if self.worker_ctx is not None:
            try:
                self.worker_ctx.report_step(
                    self._host_step, digest=self.step_digest
                )
            except TypeError:
                # digest-unaware context (older stubs): plain report
                self.worker_ctx.report_step(self._host_step)
        if self._retrace_guard is not None:
            # violations from background (speculative-compile) threads
            # can't raise in place; surface them at the step boundary
            self._retrace_guard.check()
        return new_state, loss

    def sync_host_step(self, state: dict):
        """Seed the host-side step counter from a restored train state.

        Call this from the restore path (after ``ckpt.load``): without
        it ``_host_step`` restarts at 0 and ``report_step`` feeds the
        master's SpeedMonitor a regressing global step after every
        restart, corrupting goodput accounting. The one host sync here
        is fine — restore already synchronized."""
        step = state.get("step") if isinstance(state, dict) else None
        if step is None:
            return
        self._host_step = int(jax.device_get(step))
        logger.info("host step counter seeded from restore: %d",
                    self._host_step)

    def _finalize_resize(self, loss, build_t0: float):
        """Close the resize event the last ``remesh()`` opened: stamp the
        compile half of the downtime breakdown and publish the event to
        the resize ledger (+ the master, when connected).

        The AOT path reports its exact compile seconds. The plain-jit
        path compiles lazily inside the first call — so, once per
        resize, block for the just-dispatched step and attribute the
        wall time to compile (the execute tail is noise next to a real
        model's compile; a resize boundary already synchronized for the
        state transfer, so this one sync costs nothing extra)."""
        pending, self._pending_resize = self._pending_resize, None
        info = getattr(self, "_last_build_info", None) or {}
        compile_s = info.get("compile_s")
        # ONE clock read for every synthetic span below: re-reading the
        # clock per span would let a later span's back-dated start land
        # inside an earlier one by the microseconds between the reads
        # (the job-timeline --check enforces nesting per lane). The
        # synthetic spans also live on their own "resize" lane so they
        # can never partially overlap the real thread-lane spans.
        now_m = time.monotonic()
        if compile_s is None:
            # jit (kill-switch / AOT-fallback) path; the AOT path's
            # compile span came from lower_step, this lazy-jit compile
            # only becomes measurable here
            jax.block_until_ready(loss)  # graftlint: disable=JG002
            compile_s = time.perf_counter() - build_t0
            now_m = time.monotonic()  # after the sync, before any span
            trace.record(
                "compile", "resize.first_step_compile",
                now_m - compile_s, compile_s, tid="resize",
                world=pending["to"], source="resize-jit",
            )
        # the rendezvous half was measured by the caller (remesh's
        # rendezvous_s) — lay it strictly before the transfer+compile
        # so the local timeline shows the whole downtime bracket
        # host dict reads, not device syncs  # graftlint: disable=JG002
        rdzv_s = float(pending.get("rendezvous_s", 0.0) or 0.0)
        if rdzv_s > 0:
            before = compile_s + float(  # graftlint: disable=JG002
                pending.get("state_transfer_s", 0.0) or 0.0
            )
            trace.record(
                "rendezvous", "resize.rendezvous",
                now_m - before - rdzv_s, rdzv_s, tid="resize",
                world=pending["to"],
            )
        event = live_reshard.resize_ledger.record(
            pending["from"], pending["to"],
            rendezvous_s=pending.get("rendezvous_s", 0.0),
            compile_s=compile_s,
            state_transfer_s=pending.get("state_transfer_s", 0.0),
            path=pending.get("path", "checkpoint"),
            restore_tier=pending.get("restore_tier", ""),
        )
        logger.info(
            "resize %d->%d downtime breakdown: compile=%.3fs "
            "state_transfer=%.3fs (path=%s, restore_tier=%s)",
            event["world_from"], event["world_to"], event["compile_s"],
            event["state_transfer_s"], event["path"],
            event["restore_tier"] or "?",
        )
        if self.worker_ctx is not None:
            self.worker_ctx.report_resize_breakdown(
                rendezvous_s=event["rendezvous_s"],
                compile_s=event["compile_s"],
                state_transfer_s=event["state_transfer_s"],
                restore_tier=event["restore_tier"],
            )

    def note_restore_tier(self, tier: str):
        """Stamp which checkpoint tier supplied the state for the resize
        in flight (``engine.last_restore_stats["tier"]``). Call between
        ``remesh()`` (when it returned None — the checkpoint path) and
        the first post-resize ``step()``; the breakdown event then
        attributes the downtime-ending restore to its tier."""
        if self._pending_resize is not None and tier:
            self._pending_resize["restore_tier"] = str(tier)

    # ---- elasticity ----------------------------------------------------
    def remesh(
        self,
        mesh: Mesh,
        mesh_config: MeshConfig,
        state: Optional[dict] = None,
        rendezvous_s: float = 0.0,
        n_slices: Optional[int] = None,
    ) -> Optional[dict]:
        """After a membership change: adopt the new mesh; the jitted step is
        rebuilt (recompiled) lazily; accumulation re-derives so the global
        batch is unchanged (the reference's core elasticity invariant).

        ``state`` (live-reshard path): when the old state is still on
        device — the process survived the resize — pass it here and the
        trainer moves it old-mesh→new-mesh device-to-device (batched
        ``jax.device_put`` against the avatar-derived target shardings,
        with a leaf-wise + host-bridge fallback ladder), skipping the
        checkpoint round-trip entirely. Returns the transferred state,
        or None when live reshard is off / unavailable — the caller
        then restores via the checkpoint engine exactly as before.

        ``rendezvous_s``: seconds the caller spent re-seating the world
        before calling here (the agent/worker measured the
        re-rendezvous); stamped into the pending resize event so the
        breakdown report and the trace spine carry the rendezvous half
        of the downtime bracket instead of a hardcoded zero.

        ``n_slices``: the new world's slice count (a slice loss resizes
        it). ``None`` keeps the slices-are-atomic derivation — the new
        world re-tiles into the old per-slice size where possible, else
        single-slice (a caller that knows better passes it)."""
        old = self.accum_steps
        dp = mesh_config.resolve(mesh.size).data_parallel_size
        denom = self.tc.micro_batch_size * dp
        if self.tc.global_batch_size % denom:
            raise ValueError(
                f"cannot remesh to world={mesh.size}: global_batch="
                f"{self.tc.global_batch_size} not divisible by "
                f"micro_batch*dp={denom}; trainer left on the old mesh"
            )
        old_world = self.mesh.size
        new_state: Optional[dict] = None
        transfer_info: Optional[dict] = None
        if state is not None and live_reshard.live_reshard_enabled():
            # transfer BEFORE adopting the new mesh fails nothing if the
            # ladder falls through: state stays placed for the old mesh
            # and the caller's checkpoint restore path is untouched
            try:
                if self._state_avatar is None:
                    self._state_avatar = jax.tree.map(_avatar_of, state)
                if self._params_avatar is None and "params" in state:
                    # zero-1 derives its layout from the params avatar;
                    # leaving it unseeded here would downgrade the next
                    # _build_step to the replicated path while the
                    # signature/ledger/contracts still say zero-1
                    self._params_avatar = jax.tree.map(
                        _avatar_of, state["params"]
                    )
                # zero-1 aware retarget: the new dp size (or a zero-1
                # on/off flip taking effect at this resize boundary)
                # re-derives every moment's layout, so dp-sharded
                # moments remesh device-to-device like any other leaf —
                # including the zero↔off transitions
                avatars = self._state_avatar_for(mesh)
                # check the built mesh against the descriptor derived
                # from the CONFIG (the independent source — deriving it
                # from mesh.shape would compare the mesh with itself):
                # a caller passing a mesh inconsistent with the config
                # it also passed fails here, before any state moves
                target_world = WorldDescriptor.from_axis_sizes(
                    mesh_config.resolve(mesh.size).shape()
                )
                shardings = live_reshard.state_shardings(
                    avatars, mesh, world=target_world
                )
                new_state, transfer_info = live_reshard.transfer_state(
                    state, shardings
                )
            except Exception as e:
                logger.warning(
                    "live reshard %d->%d failed (%s); caller should "
                    "restore from checkpoint", old_world, mesh.size, e,
                )
                new_state = None
        new_slices = (
            max(1, int(n_slices)) if n_slices is not None
            else self._slices_for_size(mesh.size)
        )
        self.mesh = mesh
        self.mesh_config = mesh_config
        self.n_slices = new_slices
        self._step_fn = None
        self._eval_fn = None  # its NamedSharding binds the old mesh
        if (
            self._speculation_hint is not None
            and self._speculation_hint.world_size == mesh.size
        ):
            # the hinted resize happened — the hint is consumed (the
            # next build's speculation goes back to neighbors until the
            # planner publishes a new intent)
            self._speculation_hint = None
        self._pending_resize = {
            "from": old_world,
            "to": mesh.size,
            "rendezvous_s": max(0.0, float(rendezvous_s)),
            "state_transfer_s": (
                transfer_info["transfer_s"] if transfer_info else 0.0
            ),
            "path": (
                transfer_info["path"] if transfer_info else "checkpoint"
            ),
            # "live" = no restore happened at all; the checkpoint path
            # stamps its tier via note_restore_tier once the caller's
            # engine.load() reports which rung supplied the state
            "restore_tier": "live" if transfer_info else "",
        }
        if self.loss_factory is not None:
            # re-derive the loss for the new mesh (a loss closing over
            # the old mesh would pin its sharding constraints to dead
            # devices and poison the rebuild)
            self.loss_fn = self.loss_factory(mesh)
        # refresh the comm inventory NOW: on the elastic resize path the
        # state is restored (init_state never runs again), and without
        # this /metrics keeps advertising the dead mesh's collectives
        # and accumulation count
        if self._params_avatar is not None:
            self._record_data_parallel_comm(self._params_avatar)
        warm = False
        if (
            warm_compile.warm_compile_enabled()
            and self._state_avatar is not None
            and self._batch_avatar is not None
        ):
            try:
                sig, _ = self._step_signature(
                    mesh, mesh_config, self.accum_steps
                )
                warm = self.warm.get(sig) is not None
            except Exception:
                warm = False
        logger.info(
            "remesh: world=%d accum %d→%d (global batch fixed at %d); "
            "step rebuild will be %s; state %s",
            mesh.size, old, self.accum_steps, self.tc.global_batch_size,
            "WARM (AOT executable cached)" if warm else "cold",
            (
                f"live-resharded in {transfer_info['transfer_s']:.3f}s "
                f"({transfer_info['path']})"
                if transfer_info
                else "NOT transferred (checkpoint restore path)"
            ),
        )
        if new_state is not None:
            # the transfer already synchronized; re-seeding the host
            # step counter here keeps report_step monotonic across the
            # resize without a checkpoint restore to do it
            self.sync_host_step(new_state)
        return new_state
