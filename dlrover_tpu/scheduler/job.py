"""Job arguments the master derives from the platform.

Parity: reference ``dlrover/python/scheduler/job.py:1-116`` (JobArgs) and
``kubernetes.py:400-489`` (``K8sJobArgs.initilize`` parsing the ElasticJob
CR). TPU-natively a replica group describes *hosts of a slice type*: the
chip count per host and the slice topology come from the TPU accelerator
selectors on the pod template, so plans scale host counts while topology
stays a property of the slice type.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common import flags
from dlrover_tpu.common.constants import DistributionStrategy, NodeType
from dlrover_tpu.common.global_context import parse_bool as _parse_bool
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.scheduler.k8s_client import ELASTICJOB_PLURAL, get_k8s_client


@dataclass
class ReplicaSpec:
    """One replica group (e.g. ``worker``) of the job."""

    group: NodeGroupResource = field(default_factory=NodeGroupResource)
    min_nodes: int = 0
    max_nodes: int = 0
    restart_count: int = 3
    pod_template: Dict = field(default_factory=dict)
    priority: str = ""


@dataclass
class JobArgs:
    """Everything the master needs to manage one job."""

    platform: str = "k8s"
    namespace: str = "default"
    job_name: str = ""
    job_uid: str = ""
    distribution_strategy: str = DistributionStrategy.ALLREDUCE
    replicas: Dict[str, ReplicaSpec] = field(default_factory=dict)
    node_unit: int = 1
    relaunch_on_worker_failure: int = 3
    remove_exited_node: bool = True
    cordon_fault_node: bool = False
    tpu_type: str = ""  # e.g. v5p-32; informs chips/host + topology
    scale_plan_mode: str = "direct"  # direct pod ops | "crd" (operator applies)

    @property
    def worker_spec(self) -> ReplicaSpec:
        return self.replicas.get(NodeType.WORKER, ReplicaSpec())

    @classmethod
    def from_elasticjob_cr(cls, cr: Dict) -> "JobArgs":
        meta = cr.get("metadata", {})
        spec = cr.get("spec", {})
        args = cls(
            namespace=meta.get("namespace", "default"),
            job_name=meta.get("name", ""),
            job_uid=meta.get("uid", ""),
            distribution_strategy=spec.get(
                "distributionStrategy", DistributionStrategy.ALLREDUCE
            ),
            node_unit=int(spec.get("nodeUnit", 1)),
            tpu_type=spec.get("tpuType", ""),
            scale_plan_mode=spec.get("scalePlanMode", "direct"),
            relaunch_on_worker_failure=int(
                spec.get("relaunchOnWorkerFailure", 3)
            ),
            remove_exited_node=_parse_bool(spec.get("removeExitedNode", True)),
            cordon_fault_node=_parse_bool(spec.get("cordonFaultNode", False)),
        )
        for rtype, rspec in spec.get("replicaSpecs", {}).items():
            template = rspec.get("template", {})
            resource = _resource_from_pod_template(template)
            count = int(rspec.get("replicas", 0))
            args.replicas[rtype] = ReplicaSpec(
                group=NodeGroupResource(count=count, node_resource=resource),
                min_nodes=int(rspec.get("minReplicas", count)),
                max_nodes=int(rspec.get("maxReplicas", count)),
                restart_count=int(
                    rspec.get("restartCount",
                              args.relaunch_on_worker_failure)
                ),
                pod_template=template,
                priority=rspec.get("priority", ""),
            )
        if not args.tpu_type:
            worker = args.replicas.get(NodeType.WORKER)
            if worker is not None:
                args.tpu_type = _tpu_type_from_template(worker.pod_template)
        return args

    @classmethod
    def from_k8s_env(cls, job_name: str = "", namespace: str = "") -> "JobArgs":
        """Master-pod entry: read our ElasticJob CR from the API server."""
        job_name = job_name or flags.ELASTICJOB_NAME.get()
        namespace = namespace or flags.POD_NAMESPACE.get()
        client = get_k8s_client(namespace)
        cr = client.get_custom_resource(ELASTICJOB_PLURAL, job_name)
        if cr is None:
            logger.warning(
                "elasticjob %s/%s not found; using env-only args",
                namespace,
                job_name,
            )
            return cls(namespace=namespace, job_name=job_name)
        return cls.from_elasticjob_cr(cr)


def _parse_quantity(q) -> float:
    """k8s quantity -> float (cpu cores or bytes-ish units to MB for memory
    when the caller divides). Supports m, Ki/Mi/Gi/Ti, K/M/G/T."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    try:
        return float(s)
    except ValueError:
        pass
    units = {
        "m": 1e-3,
        "Ki": 1024,
        "Mi": 1024**2,
        "Gi": 1024**3,
        "Ti": 1024**4,
        "K": 1e3,
        "M": 1e6,
        "G": 1e9,
        "T": 1e12,
    }
    for suffix in sorted(units, key=len, reverse=True):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * units[suffix]
    logger.warning("unparseable k8s quantity %r", q)
    return 0.0


def _resource_from_pod_template(template: Dict) -> NodeResource:
    containers = template.get("spec", {}).get("containers", [])
    if not containers:
        return NodeResource()
    requests = containers[0].get("resources", {}).get("requests", {})
    limits = containers[0].get("resources", {}).get("limits", {})
    merged = {**requests, **limits}
    memory = _parse_quantity(merged.get("memory", 0))
    return NodeResource(
        cpu=_parse_quantity(merged.get("cpu", 0)),
        memory_mb=memory / (1024**2) if memory else 0.0,
        tpu_chips=int(_parse_quantity(merged.get("google.com/tpu", 0))),
        tpu_type=_tpu_type_from_template(template),
    )


def _tpu_type_from_template(template: Dict) -> str:
    sel = template.get("spec", {}).get("nodeSelector", {})
    accel = sel.get("cloud.google.com/gke-tpu-accelerator", "")
    topo = sel.get("cloud.google.com/gke-tpu-topology", "")
    if accel and topo:
        return f"{accel}:{topo}"
    return accel
