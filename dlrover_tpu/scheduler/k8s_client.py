"""Minimal Kubernetes API client over the cluster REST endpoint.

Parity: reference ``dlrover/python/scheduler/kubernetes.py:122-592``
(``k8sClient`` wrapping the official python client). We talk to the API
server directly with the standard library instead: inside a pod the service
account token + CA bundle are mounted at a fixed path, and everything the
master needs (pods, services, events, our CRs, watch streams) is a handful
of REST verbs. That keeps the framework dependency-free and lets tests
inject a fake transport, mirroring the reference's mocked-client strategy
(``tests/test_utils.py:314-335``).
"""

from __future__ import annotations

import json
import os
import ssl
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Generator, List, Optional, Tuple

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
GROUP = "elastic.dlrover-tpu.org"
VERSION = "v1alpha1"
ELASTICJOB_PLURAL = "elasticjobs"
SCALEPLAN_PLURAL = "scaleplans"


class K8sApiError(Exception):
    def __init__(self, status: int, reason: str, body: str = ""):
        super().__init__(f"k8s api {status} {reason}: {body[:300]}")
        self.status = status
        self.reason = reason


class ApiServerTransport:
    """HTTPS transport to the in-cluster API server (stdlib only)."""

    def __init__(
        self,
        host: str = "",
        token: str = "",
        ca_file: str = "",
        timeout: float = 30.0,
    ):
        from dlrover_tpu.common import flags

        host = host or flags.KUBERNETES_SERVICE_HOST.get()
        port = flags.KUBERNETES_SERVICE_PORT.get()
        self.base_url = host if "://" in host else f"https://{host}:{port}"
        self._timeout = timeout
        token_file = os.path.join(SA_DIR, "token")
        if not token and os.path.exists(token_file):
            token = open(token_file).read().strip()
        self._token = token
        ca_file = ca_file or os.path.join(SA_DIR, "ca.crt")
        if os.path.exists(ca_file):
            self._ctx = ssl.create_default_context(cafile=ca_file)
        else:  # out-of-cluster dev setups
            self._ctx = ssl.create_default_context()
            # Never silently disable verification: the bearer token rides
            # this connection. Unverified TLS is an explicit opt-in.
            if flags.K8S_INSECURE_TLS.get() == "1":
                logger.warning(
                    "TLS certificate verification DISABLED for %s "
                    "(DLROVER_TPU_K8S_INSECURE_TLS=1) — cluster credentials "
                    "are exposed to MITM; dev use only",
                    self.base_url,
                )
                self._ctx.check_hostname = False
                self._ctx.verify_mode = ssl.CERT_NONE

    def request(
        self,
        method: str,
        path: str,
        body: Optional[Dict] = None,
        params: Optional[Dict] = None,
        stream: bool = False,
        timeout: Optional[float] = None,
    ):
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Authorization", f"Bearer {self._token}")
        req.add_header("Accept", "application/json")
        if data is not None:
            content_type = (
                "application/merge-patch+json"
                if method == "PATCH"
                else "application/json"
            )
            req.add_header("Content-Type", content_type)
        try:
            resp = urllib.request.urlopen(
                req, timeout=timeout or self._timeout, context=self._ctx
            )
        except urllib.error.HTTPError as e:
            raise K8sApiError(e.code, e.reason, e.read().decode(errors="replace"))
        if stream:
            return resp  # caller iterates lines
        payload = resp.read().decode()
        return json.loads(payload) if payload else {}


class K8sClient:
    """Typed operations the master/scaler/watcher need.

    ``transport`` must expose ``request(method, path, body, params, stream,
    timeout)``; tests pass a fake.
    """

    def __init__(self, namespace: str, transport=None):
        self.namespace = namespace
        self._transport = transport or ApiServerTransport()

    # -- pods ---------------------------------------------------------------

    def _pods_path(self, name: str = "") -> str:
        base = f"/api/v1/namespaces/{self.namespace}/pods"
        return f"{base}/{name}" if name else base

    def create_pod(self, pod: Dict) -> Dict:
        return self._transport.request("POST", self._pods_path(), body=pod)

    def get_pod(self, name: str) -> Optional[Dict]:
        try:
            return self._transport.request("GET", self._pods_path(name))
        except K8sApiError as e:
            if e.status == 404:
                return None
            raise

    def delete_pod(self, name: str, grace_seconds: int = 30) -> bool:
        try:
            self._transport.request(
                "DELETE",
                self._pods_path(name),
                body={"gracePeriodSeconds": grace_seconds},
            )
            return True
        except K8sApiError as e:
            if e.status == 404:
                return False
            raise

    def cordon_node(self, node_name: str, unschedulable: bool = True) -> bool:
        """Mark a cluster node (un)schedulable (``kubectl cordon`` /
        ``uncordon``) so a replacement pod cannot land back on a host the
        health machinery flagged (reference ``kubernetes.py`` cordon
        support, used with ``cordon_fault_node``)."""
        try:
            self._transport.request(
                "PATCH",
                f"/api/v1/nodes/{node_name}",
                body={"spec": {"unschedulable": unschedulable}},
            )
            return True
        except K8sApiError as e:
            if e.status == 404:
                return False
            raise

    def list_pods(self, label_selector: str = "") -> List[Dict]:
        params = {"labelSelector": label_selector} if label_selector else None
        out = self._transport.request("GET", self._pods_path(), params=params)
        return out.get("items", [])

    def _watch(
        self,
        path: str,
        label_selector: str = "",
        resource_version: str = "",
        timeout_seconds: int = 300,
    ) -> Generator[Tuple[str, Dict], None, None]:
        """Yields (event_type, object) from a chunked watch stream."""
        params = {"watch": "true", "timeoutSeconds": str(timeout_seconds)}
        if label_selector:
            params["labelSelector"] = label_selector
        if resource_version:
            params["resourceVersion"] = resource_version
        resp = self._transport.request(
            "GET",
            path,
            params=params,
            stream=True,
            timeout=timeout_seconds + 10,
        )
        for line in resp:
            if not line.strip():
                continue
            evt = json.loads(line)
            yield evt.get("type", ""), evt.get("object", {})

    def watch_pods(
        self,
        label_selector: str = "",
        resource_version: str = "",
        timeout_seconds: int = 300,
    ) -> Generator[Tuple[str, Dict], None, None]:
        return self._watch(
            self._pods_path(), label_selector, resource_version, timeout_seconds
        )

    # -- services -----------------------------------------------------------

    def create_service(self, svc: Dict) -> Dict:
        path = f"/api/v1/namespaces/{self.namespace}/services"
        return self._transport.request("POST", path, body=svc)

    def get_service(self, name: str) -> Optional[Dict]:
        path = f"/api/v1/namespaces/{self.namespace}/services/{name}"
        try:
            return self._transport.request("GET", path)
        except K8sApiError as e:
            if e.status == 404:
                return None
            raise

    # -- configmaps (master state continuity) --------------------------------

    def _cm_path(self, name: str = "") -> str:
        base = f"/api/v1/namespaces/{self.namespace}/configmaps"
        return f"{base}/{name}" if name else base

    def create_config_map(self, cm: Dict) -> Dict:
        return self._transport.request("POST", self._cm_path(), body=cm)

    def get_config_map(self, name: str) -> Optional[Dict]:
        try:
            return self._transport.request("GET", self._cm_path(name))
        except K8sApiError as e:
            if e.status == 404:
                return None
            raise

    def patch_config_map(self, name: str, patch: Dict) -> Dict:
        """Strategic-merge patch; a ``data`` key set to None deletes it."""
        return self._transport.request(
            "PATCH", self._cm_path(name), body=patch
        )

    def replace_config_map(self, name: str, cm: Dict) -> Dict:
        """PUT replace. When ``cm.metadata.resourceVersion`` is set the API
        server enforces optimistic concurrency: a stale version gets 409
        Conflict — the compare-and-swap primitive merge-patch lacks."""
        return self._transport.request("PUT", self._cm_path(name), body=cm)

    # -- events -------------------------------------------------------------

    def create_event(self, event: Dict) -> Dict:
        path = f"/api/v1/namespaces/{self.namespace}/events"
        return self._transport.request("POST", path, body=event)

    # -- custom resources (ElasticJob / ScalePlan) --------------------------

    def _cr_path(self, plural: str, name: str = "") -> str:
        base = (
            f"/apis/{GROUP}/{VERSION}/namespaces/{self.namespace}/{plural}"
        )
        return f"{base}/{name}" if name else base

    def get_custom_resource(self, plural: str, name: str) -> Optional[Dict]:
        try:
            return self._transport.request("GET", self._cr_path(plural, name))
        except K8sApiError as e:
            if e.status == 404:
                return None
            raise

    def list_custom_resources(
        self, plural: str, label_selector: str = ""
    ) -> List[Dict]:
        params = {"labelSelector": label_selector} if label_selector else None
        out = self._transport.request(
            "GET", self._cr_path(plural), params=params
        )
        return out.get("items", [])

    def create_custom_resource(self, plural: str, cr: Dict) -> Dict:
        return self._transport.request("POST", self._cr_path(plural), body=cr)

    def patch_custom_resource_status(
        self, plural: str, name: str, status: Dict
    ) -> Dict:
        return self._transport.request(
            "PATCH",
            self._cr_path(plural, name) + "/status",
            body={"status": status},
        )

    def delete_custom_resource(self, plural: str, name: str) -> bool:
        try:
            self._transport.request("DELETE", self._cr_path(plural, name))
            return True
        except K8sApiError as e:
            if e.status == 404:
                return False
            raise

    def watch_custom_resources(
        self,
        plural: str,
        label_selector: str = "",
        resource_version: str = "",
        timeout_seconds: int = 300,
    ) -> Generator[Tuple[str, Dict], None, None]:
        return self._watch(
            self._cr_path(plural),
            label_selector,
            resource_version,
            timeout_seconds,
        )


_singleton_lock = threading.Lock()
_singleton: Optional[K8sClient] = None


def get_k8s_client(namespace: str = "", transport=None) -> K8sClient:
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            namespace = namespace or flags.POD_NAMESPACE.get()
            _singleton = K8sClient(namespace, transport=transport)
        return _singleton


def reset_k8s_client():
    """Test helper: drop the singleton so fixtures can re-inject."""
    global _singleton
    with _singleton_lock:
        _singleton = None
