"""Sharding helpers: PartitionSpec pytrees → NamedShardings → device arrays.

Models in this framework publish a ``param_specs(config)`` pytree of
`PartitionSpec` mirroring their parameter pytree (see
`dlrover_tpu/models/llama.py`). These helpers turn those into
`NamedSharding`s on a mesh and move/constrain pytrees accordingly.

The reference has no analogue — parameter placement there belongs to
torch DDP/FSDP/Megatron (SURVEY.md §2.8). Here placement is explicit and
mesh-driven, which is also what makes elastic *resharded* restore possible:
the checkpoint stores the logical pytree; on resume we place it onto
whatever mesh the new world supports.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.parallel.mesh import BATCH_AXES, SP

PyTree = Any


def named_shardings(mesh: Mesh, specs: PyTree) -> PyTree:
    """PartitionSpec pytree → NamedSharding pytree on ``mesh``."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_spec(shard_sequence: bool = False) -> P:
    """Sharding for a (batch, seq, ...) input batch: batch dim over all
    data axes; sequence dim over sp when sequence parallelism is on."""
    if shard_sequence:
        return P(BATCH_AXES, SP)
    return P(BATCH_AXES)


def shard_pytree(mesh: Mesh, specs: PyTree, tree: PyTree) -> PyTree:
    """Place ``tree`` onto ``mesh`` per ``specs`` (host → device)."""
    sh = named_shardings(mesh, specs)
    return jax.device_put(tree, sh)


def with_constraints(tree: PyTree, specs: PyTree) -> PyTree:
    """Apply `lax.with_sharding_constraint` leaf-wise inside jit."""
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(x, s), tree, specs
    )


def pad_batch_to(batch: PyTree, multiple: int) -> PyTree:
    """Pad the leading dim of every leaf up to ``multiple`` (elastic worlds
    can leave batch % data_axes != 0 right after a resize).

    Integer leaves (token ids) pad with -1 — the loss-mask sentinel every
    model's ``loss_fn`` ignores — so fake rows contribute no gradient;
    float leaves pad with 0.
    """
    import jax.numpy as jnp
    import numpy as np

    def _pad(x):
        b = x.shape[0]
        rem = (-b) % multiple
        if rem == 0:
            return x
        pad = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
        fill = -1 if np.issubdtype(x.dtype, np.integer) else 0
        return jnp.pad(x, pad, constant_values=fill)

    return jax.tree.map(_pad, batch)


def spec_for_resize(
    spec: P, mesh: Mesh, shape: tuple, *, keep: Optional[set] = None
) -> P:
    """Drop mesh axes from a spec that no longer divide the array shape —
    used when restoring a checkpoint onto a smaller/odd-shaped mesh."""
    keep = keep or set(mesh.axis_names)
    out = []
    for dim, entry in enumerate(spec):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        axes = tuple(
            a for a in axes
            if a in keep and shape[dim] % mesh.shape[a] == 0
        )
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    return P(*out)
