"""Parallelism layer: device meshes, sharding rules, sequence parallelism.

TPU-native replacement for the worlds the reference delegates to
torch.distributed/NCCL (SURVEY.md §2.8): one `jax.sharding.Mesh` with
dp/fsdp/ep/sp/tp axes, XLA collectives over ICI/DCN, and elastic re-meshing
on membership change.
"""

from dlrover_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    BATCH_AXES,
    DP,
    EP,
    FSDP,
    MeshConfig,
    SP,
    TP,
    build_mesh,
    config_for,
    mesh_for,
    remesh,
    validate_divisibility,
)
from dlrover_tpu.parallel.sharding import (  # noqa: F401
    batch_spec,
    named_shardings,
    pad_batch_to,
    shard_pytree,
    spec_for_resize,
    with_constraints,
)
