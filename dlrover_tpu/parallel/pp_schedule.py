"""Static pipeline-schedule tables: interleaved (virtual-stage) 1F1B.

The reference framework is only checkpoint-aware of virtual pipeline
stages (``megatron_dist_ckpt.py:262,489`` maps Megatron's
``virtual_pipeline_model_parallel_size`` chunks into its checkpoint
layout — Megatron owns the schedule there). Here the schedule itself is
built TPU-native: this module computes, entirely in Python at trace
time, a per-tick op table that a ``lax.scan`` inside ``shard_map``
executes (`dlrover_tpu/models/llama.py`). Keeping the schedule static
is what makes it XLA-compatible — the scan body is compiled once and
every tick's work is selected by table lookup, not data-dependent
Python control flow.

Model
-----
The model is cut into ``C = pp * v`` chunks of ``n_layers / C``
consecutive layers. Chunk ``c`` lives on rank ``c % pp`` as that rank's
virtual stage ``u = c // pp`` — the Megatron placement, chosen because
it makes EVERY chunk-to-chunk activation hop a uniform +1 ring permute
(rank ``pp-1`` wraps to rank 0 for the ``u -> u+1`` transition), so the
executor needs exactly one forward and one backward ``lax.ppermute``
per tick regardless of ``v``.

Ticks are half-steps: each rank performs at most ONE chunk op (a
forward or a backward) per tick. In these units plain 1F1B costs
``2*(n_micro + pp - 1)`` slab-ticks = ``2*v*(n_micro + pp - 1)``
chunk-ticks, with a bubble of ``2*v*(pp-1)`` chunk-ticks per rank.
Interleaving fills the warmup/cooldown with other chunks' work, cutting
the bubble toward ``2*(pp-1)`` — a factor ``v`` — which is the whole
point (Megatron-LM interleaved schedule; "Efficient Large-Scale
Language Model Training on GPU Clusters").

Scheduling policy: each rank executes the Megatron interleaved op
ORDER (warmup of ``2*(pp-r-1) + (v-1)*pp`` forwards cycling chunks in
groups of ``pp`` microbatches, then strict 1F1B alternation, then
cooldown backwards) in-order, advancing at a tick only when the op's
inputs have arrived and its output buffer slot is free. The resulting
makespan is verified in tests against the closed-form plain-1F1B count.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np


def plain_1f1b_ticks(pp: int, n_micro: int) -> int:
    """Half-tick makespan of the non-interleaved 1F1B schedule
    (``_pp_1f1b_run``): warmup pp-1, steady 2*n_micro, cooldown pp-1."""
    return 2 * (n_micro + pp - 1)


def plain_1f1b_chunk_ticks(pp: int, v: int, n_micro: int) -> int:
    """Plain 1F1B expressed in CHUNK ticks (each slab op = v chunk ops),
    the unit interleaved tables use — the fair comparison baseline."""
    return v * plain_1f1b_ticks(pp, n_micro)


@dataclasses.dataclass(frozen=True)
class PPScheduleTables:
    """Per-(tick, rank) op tables, all shape (T, pp), plus derived stats.

    ``f_*``/``b_*``: the forward/backward chunk op a rank runs that tick
    (microbatch ``i``, virtual stage ``u``; ``*_do`` gates). ``rf_*``/
    ``rb_*``: where to store the activation/gradient arriving on the
    ring wire at the START of that tick (written by the neighbour's op
    at tick-1). Buffer slots are ``(u, i % pp)``; the builder PROVES
    slot liveness never overlaps, so the executor needs no tags.
    """

    pp: int
    v: int
    n_micro: int
    T: int
    n_slots: int  # buffer slots per virtual stage (keyed i % n_slots)
    f_do: np.ndarray
    f_i: np.ndarray
    f_u: np.ndarray
    b_do: np.ndarray
    b_i: np.ndarray
    b_u: np.ndarray
    rf_do: np.ndarray
    rf_u: np.ndarray
    rf_s: np.ndarray
    rb_do: np.ndarray
    rb_u: np.ndarray
    rb_s: np.ndarray
    max_live_acts: int  # peak saved-activation slots on any rank

    @property
    def bubble_ticks(self) -> int:
        """Idle chunk-ticks per rank (uniform: every rank runs
        2*n_micro*v ops in T ticks)."""
        return self.T - 2 * self.n_micro * self.v

    def as_device_tables(self) -> Dict[str, np.ndarray]:
        """int32/bool arrays ready to be scan xs."""
        out = {}
        for f in ("f_do", "b_do", "rf_do", "rb_do"):
            out[f] = getattr(self, f).astype(np.bool_)
        for f in ("f_i", "f_u", "b_i", "b_u", "rf_u", "rf_s", "rb_u",
                  "rb_s"):
            out[f] = getattr(self, f).astype(np.int32)
        return out


def interleave_layer_perm(n_layers: int, pp: int, v: int) -> np.ndarray:
    """Canonical -> rank-major layer order. With the stacked layer axis
    sharded ``P(pp)``, rank ``r``'s contiguous slab must hold chunks
    ``{u*pp + r : u in [0, v)}``; this permutation lines that up, and
    ``np.argsort`` of it maps gradients back to canonical order."""
    if n_layers % (pp * v):
        raise ValueError(
            f"n_layers={n_layers} not divisible by pp*v={pp * v}"
        )
    lc = n_layers // (pp * v)
    perm = np.empty(n_layers, dtype=np.int64)
    pos = 0
    for r in range(pp):
        for u in range(v):
            c = u * pp + r
            perm[pos:pos + lc] = np.arange(c * lc, (c + 1) * lc)
            pos += lc
    return perm


class _Builder:
    """Event-driven greedy scheduler with slot backpressure."""

    def __init__(self, pp: int, v: int, n_micro: int, n_slots: int):
        self.pp, self.v, self.n = pp, v, n_micro
        self.S = n_slots  # buffer slots per (chunk) — key is i % S
        self.C = pp * v
        self.t_f: Dict[Tuple[int, int], int] = {}  # (i, c) -> tick
        self.t_b: Dict[Tuple[int, int], int] = {}

    # -- dependency / backpressure predicates ---------------------------

    def _fwd_ready(self, i: int, c: int, t: int) -> bool:
        pp, v, C = self.pp, self.v, self.C
        u = c // pp
        if c > 0:
            tf_prev = self.t_f.get((i, c - 1))
            if tf_prev is None or t < tf_prev + 1:
                return False  # input not yet arrived over the ring
        # saved-activation slot (u, i%pp) free? previous occupant is
        # microbatch i-pp at the same chunk; its backward consumes it
        prev = (i - self.S, c)
        if i - self.S >= 0:
            tb_prev = self.t_b.get(prev)
            if tb_prev is None or t <= tb_prev:
                return False
        # output destination free at store time t+1?
        if c < C - 1:
            nxt_prev = (i - self.S, c + 1)  # prior occupant of recv slot
            if i - self.S >= 0:
                tf_next_prev = self.t_f.get(nxt_prev)
                if tf_next_prev is None or t + 1 <= tf_next_prev:
                    return False
        else:
            # head grad lands in recv_grad[(v-1, i%pp)] this same tick
            hb_prev = (i - self.S, C - 1)
            if i - self.S >= 0:
                tb_hprev = self.t_b.get(hb_prev)
                if tb_hprev is None or t <= tb_hprev:
                    return False
        return True

    def _bwd_ready(self, i: int, c: int, t: int) -> bool:
        pp, C = self.pp, self.C
        if c == C - 1:
            tf = self.t_f.get((i, c))
            if tf is None or t <= tf:
                return False
        else:
            tb_next = self.t_b.get((i, c + 1))
            if tb_next is None or t < tb_next + 1:
                return False
        # output destination (grad wire) free at t+1?
        if c > 0:
            dst_prev = (i - self.S, c - 1)
            if i - self.S >= 0:
                tb_dprev = self.t_b.get(dst_prev)
                if tb_dprev is None or t + 1 <= tb_dprev:
                    return False
        return True

    # -- Megatron interleaved op order ----------------------------------

    def _op_sequence(self, r: int):
        """Rank r's fixed op order. Forwards cycle chunks in groups of
        ``pp`` microbatches: (i=0..pp-1, u=0), (i=0..pp-1, u=1), ...,
        then the next group of pp microbatches; backwards mirror it from
        the deepest chunk. Warmup runs ``2*(pp-r-1) + (v-1)*pp``
        forwards, then strict fwd/bwd alternation, then the backward
        tail (Megatron-LM interleaved schedule structure)."""
        pp, v, n = self.pp, self.v, self.n
        total = n * v
        group = pp * v

        def fwd_op(k):
            i = (k // group) * pp + (k % pp)
            u = (k % group) // pp
            return ("F", i, u * pp + r)

        def bwd_op(k):
            i = (k // group) * pp + (k % pp)
            u = v - 1 - (k % group) // pp
            return ("B", i, u * pp + r)

        warmup = min(2 * (pp - r - 1) + (v - 1) * pp, total)
        seq = [fwd_op(k) for k in range(warmup)]
        f, b = warmup, 0
        while f < total:
            seq.append(fwd_op(f))
            f += 1
            seq.append(bwd_op(b))
            b += 1
        while b < total:
            seq.append(bwd_op(b))
            b += 1
        return seq

    # -- main loop ------------------------------------------------------

    def build(self) -> PPScheduleTables:
        pp, v, n = self.pp, self.v, self.n
        seqs = {r: self._op_sequence(r) for r in range(pp)}
        cursor = {r: 0 for r in range(pp)}
        f_sched: list = []  # rows of dicts rank -> (i, u)
        b_sched: list = []
        total = 2 * n * v * pp
        done = 0
        t = 0
        max_ticks = 8 * (n * v + pp) + 64  # deadlock guard
        while done < total:
            if t > max_ticks:
                stuck = {r: seqs[r][cursor[r]] for r in range(pp)
                         if cursor[r] < len(seqs[r])}
                raise RuntimeError(
                    f"pp schedule deadlock: pp={pp} v={v} n_micro={n} "
                    f"stuck at tick {t} on {stuck}"
                )
            frow: Dict[int, Tuple[int, int]] = {}
            brow: Dict[int, Tuple[int, int]] = {}
            for r in range(pp):
                if cursor[r] >= len(seqs[r]):
                    continue
                kind, i, c = seqs[r][cursor[r]]
                if kind == "F" and self._fwd_ready(i, c, t):
                    frow[r] = (i, c // pp)
                    self.t_f[(i, c)] = t
                elif kind == "B" and self._bwd_ready(i, c, t):
                    brow[r] = (i, c // pp)
                    self.t_b[(i, c)] = t
                else:
                    continue
                cursor[r] += 1
                done += 1
            f_sched.append(frow)
            b_sched.append(brow)
            t += 1
        T = t
        return self._tables(T, f_sched, b_sched)

    def _tables(self, T, f_sched, b_sched) -> PPScheduleTables:
        pp, v, n, C = self.pp, self.v, self.n, self.C
        z = lambda: np.zeros((T, pp), dtype=np.int64)  # noqa: E731
        f_do, f_i, f_u = z(), z(), z()
        b_do, b_i, b_u = z(), z(), z()
        rf_do, rf_u, rf_s = z(), z(), z()
        rb_do, rb_u, rb_s = z(), z(), z()
        for t in range(T):
            for r, (i, u) in f_sched[t].items():
                f_do[t, r], f_i[t, r], f_u[t, r] = 1, i, u
                c = u * pp + r
                if c < C - 1 and t + 1 < T:
                    r2 = (r + 1) % pp
                    u2 = u + (1 if r == pp - 1 else 0)
                    rf_do[t + 1, r2] = 1
                    rf_u[t + 1, r2] = u2
                    rf_s[t + 1, r2] = i % self.S
            for r, (i, u) in b_sched[t].items():
                b_do[t, r], b_i[t, r], b_u[t, r] = 1, i, u
                c = u * pp + r
                if c > 0 and t + 1 < T:
                    r2 = (r - 1) % pp
                    u2 = u - (1 if r == 0 else 0)
                    rb_do[t + 1, r2] = 1
                    rb_u[t + 1, r2] = u2
                    rb_s[t + 1, r2] = i % self.S
        self._check_slots()
        max_live = self._max_live_acts()
        return PPScheduleTables(
            pp=pp, v=v, n_micro=n, T=T, n_slots=self.S,
            f_do=f_do, f_i=f_i, f_u=f_u,
            b_do=b_do, b_i=b_i, b_u=b_u,
            rf_do=rf_do, rf_u=rf_u, rf_s=rf_s,
            rb_do=rb_do, rb_u=rb_u, rb_s=rb_s,
            max_live_acts=max_live,
        )

    def _check_slots(self):
        """Prove no (u, i%pp) buffer slot is double-booked: for every
        consecutive pair of microbatches i, i+pp at the same chunk, the
        earlier one's consumer must run strictly before the later one's
        producer (the backpressure predicates enforce this — verify)."""
        S, C, n = self.S, self.C, self.n
        for i in range(n - S):
            for c in range(C):
                # act_saved: [t_f(i,c) .. t_b(i,c)] vs write at t_f(i+pp,c)
                assert self.t_b[(i, c)] < self.t_f[(i + S, c)], (
                    "act_saved slot collision", i, c)
                if c > 0:
                    # recv_act slot for chunk c: stored t_f(i,c-1)+1,
                    # consumed t_f(i,c)
                    assert self.t_f[(i, c)] < self.t_f[(i + S, c - 1)] + 1, (
                        "recv_act slot collision", i, c)
                if c < C - 1:
                    # recv_grad for chunk c: stored t_b(i,c+1)+1, consumed
                    # t_b(i,c)
                    assert self.t_b[(i, c)] < self.t_b[(i + S, c + 1)] + 1, (
                        "recv_grad slot collision", i, c)
                else:
                    # head-grad store at t_f(i,C-1), consumed t_b(i,C-1)
                    assert self.t_b[(i, c)] < self.t_f[(i + S, c)], (
                        "head-grad slot collision", i, c)

    def _max_live_acts(self) -> int:
        """Peak count of simultaneously saved activations on any rank —
        the executor's act_saved buffer is (v, pp) slots; report actual
        peak occupancy for the memory model."""
        pp, C, n = self.pp, self.C, self.n
        peak = 0
        for r in range(pp):
            events = []
            for i in range(n):
                for c in range(r, C, pp):
                    events.append((self.t_f[(i, c)], 1))
                    events.append((self.t_b[(i, c)], -1))
            live = 0
            for _, d in sorted(events):
                live += d
                peak = max(peak, live)
        return peak


import functools


@functools.lru_cache(maxsize=64)
def build_interleaved_tables(
    pp: int, v: int, n_micro: int
) -> PPScheduleTables:
    """Build (and verify) the interleaved-1F1B op tables. Cached: the
    loss entry reads the tick count and the executor replays the same
    tables, and both re-run on every trace."""
    if pp < 2:
        raise ValueError("interleaved schedule needs pp >= 2")
    if v < 2:
        raise ValueError(
            "pp_virtual_stages must be >= 2 for the interleaved schedule "
            "(v=1 is plain 1f1b)"
        )
    if n_micro % pp:
        raise ValueError(
            f"interleaved 1f1b needs n_micro % pp == 0 "
            f"(n_micro={n_micro}, pp={pp}): the schedule issues "
            f"microbatches in groups of pp"
        )
    # smallest slot count that admits the Megatron op order without a
    # buffer collision: warmup holds up to 2(pp-1) + (v-1)*pp live
    # activations per rank, so pp slots per chunk rarely suffice; grow
    # until the schedule completes and the collision proof passes
    last_err: Optional[Exception] = None
    for n_slots in range(pp, n_micro + 1):
        try:
            return _Builder(pp, v, n_micro, n_slots).build()
        except (RuntimeError, AssertionError) as e:
            last_err = e
    raise RuntimeError(
        f"no collision-free slot count <= n_micro for pp={pp} v={v} "
        f"n_micro={n_micro}: {last_err}"
    )
