"""Device-mesh construction for elastic TPU training.

The reference (DLRover) never owns a parallelism mesh — it manages
torch.distributed worlds formed by NCCL (SURVEY.md §2.8). TPU-native, the
mesh IS the world: every parallel strategy (dp / fsdp / sp / tp / ep) is an
axis of one `jax.sharding.Mesh`, XLA inserts the collectives, and an elastic
membership change means *re-building the mesh* and resharding state.

Axis convention (outermost → innermost):

    dp    pure data parallelism (gradient psum; rides DCN across slices)
    pp    pipeline parallelism (layer stages; point-to-point ppermute)
    fsdp  data parallelism with parameter/optimizer sharding (ZeRO-3 style)
    ep    expert parallelism for MoE layers (experts split across this axis)
    sp    sequence/context parallelism (ring attention over this axis)
    tp    tensor parallelism (innermost — highest-bandwidth ICI neighbors)

Innermost axes map to physically adjacent TPU cores (JAX device order is
torus-major), so tp/sp collectives ride single-hop ICI while dp gradient
reductions tolerate DCN latency. This mirrors the reference's ASW/PSW
topology sort (`net_topology.py:22-79` there) at mesh-construction time
instead of rendezvous time.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names, outermost first.
DP = "dp"
PP = "pp"
FSDP = "fsdp"
EP = "ep"
SP = "sp"
TP = "tp"
AXIS_ORDER = (DP, PP, FSDP, EP, SP, TP)

# Axes over which a data batch is split (sharding of the batch dimension).
BATCH_AXES = (DP, FSDP, EP)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``-1`` for dp means "absorb remaining devices"
    so the same config survives elastic resizes: tp/sp/ep/fsdp are model
    properties, dp is whatever the current world provides."""

    dp: int = -1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.pp * self.fsdp * self.ep * self.sp * self.tp
        if self.dp == -1:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"pp*fsdp*ep*sp*tp={fixed}"
                )
            return dataclasses.replace(self, dp=n_devices // fixed)
        if self.dp * fixed != n_devices:
            raise ValueError(
                f"mesh {self.shape()} wants {self.dp * fixed} devices, "
                f"got {n_devices}"
            )
        return self

    def shape(self) -> dict:
        return {
            DP: self.dp,
            PP: self.pp,
            FSDP: self.fsdp,
            EP: self.ep,
            SP: self.sp,
            TP: self.tp,
        }

    @property
    def data_parallel_size(self) -> int:
        """Number of independent batch shards (for global-batch math)."""
        return self.dp * self.fsdp * self.ep

    @staticmethod
    def auto(
        n_devices: int,
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        pp: int = 1,
        prefer_fsdp: bool = True,
    ) -> "MeshConfig":
        """Pick a mesh for ``n_devices``: model axes given, the data axes
        inferred. With ``prefer_fsdp`` the whole data dimension is fsdp
        (ZeRO-style, the usual choice for large models); otherwise pure dp."""
        model = tp * sp * ep * pp
        if n_devices % model:
            raise ValueError(
                f"{n_devices} devices not divisible by tp*sp*ep*pp={model}"
            )
        data = n_devices // model
        if prefer_fsdp:
            return MeshConfig(dp=1, pp=pp, fsdp=data, ep=ep, sp=sp, tp=tp)
        return MeshConfig(dp=data, pp=pp, fsdp=1, ep=ep, sp=sp, tp=tp)


def build_mesh(
    config: MeshConfig,
    devices: Optional[Sequence[jax.Device]] = None,
    n_slices: int = 1,
) -> Mesh:
    """Build the Mesh. Uses `mesh_utils.create_device_mesh` when the whole
    process's device set is used (it knows TPU torus topology); falls back
    to a plain reshape for explicit device subsets.

    ``n_slices > 1`` builds a **multislice** mesh: the outermost slab of
    the ``dp`` axis spans slices, so only pure-data-parallel gradient
    reductions cross DCN while every other collective (fsdp gathers, tp/sp/
    ep) stays on a single slice's ICI — the layout
    ``mesh_utils.create_hybrid_device_mesh`` produces on real multislice
    TPU, reproduced manually for virtual/partial device sets. Devices are
    grouped by their ``slice_index`` attribute when present (real TPU
    multislice), else split into ``n_slices`` equal contiguous chunks
    (CPU dryruns)."""
    if devices is None:
        devices = jax.devices()
    config = config.resolve(len(devices))
    shape = tuple(config.shape()[a] for a in AXIS_ORDER)
    if n_slices > 1:
        return _build_multislice_mesh(config, list(devices), n_slices)
    try:
        from jax.experimental import mesh_utils

        if len(devices) == len(jax.devices()):
            arr = mesh_utils.create_device_mesh(shape, devices=list(devices))
        else:
            arr = np.array(list(devices)).reshape(shape)
    except Exception:
        arr = np.array(list(devices)).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def _build_multislice_mesh(
    config: MeshConfig, devices: list, n_slices: int
) -> Mesh:
    n = len(devices)
    if n % n_slices:
        raise ValueError(f"{n} devices not divisible by {n_slices} slices")
    per_slice = n // n_slices
    # canonical DCN placement (WorldDescriptor.pp_spans_slices): dp
    # spans the slices when it decomposes, else whole pp stages are
    # pinned per slice — activations ride DCN on the stage boundary
    # ppermute while fsdp/ep/sp/tp collectives stay on one slice's ICI
    pp_spans = config.dp % n_slices != 0
    if pp_spans and config.pp % n_slices:
        raise ValueError(
            f"neither dp={config.dp} nor pp={config.pp} is divisible by "
            f"n_slices={n_slices}: dp and pp are the only axes allowed "
            "to span DCN (fsdp/ep/sp/tp collectives must stay on one "
            "slice's ICI)"
        )
    if pp_spans:
        within = config.dp * (config.pp // n_slices) * config.fsdp \
            * config.ep * config.sp * config.tp
    else:
        within = (config.dp // n_slices) * config.pp * config.fsdp \
            * config.ep * config.sp * config.tp
    if within != per_slice:
        raise ValueError(
            f"per-slice mesh ({within}) != devices per slice ({per_slice})"
        )
    # group by hardware slice when the runtime exposes it
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) == n_slices:
        ordered = sorted(
            devices, key=lambda d: (d.slice_index, getattr(d, "id", 0))
        )
    else:
        ordered = list(devices)  # contiguous chunks = virtual slices
    if not pp_spans:
        try:
            from jax.experimental import mesh_utils

            if None not in slice_ids and len(slice_ids) == n_slices:
                ici = (config.dp // n_slices, config.pp, config.fsdp,
                       config.ep, config.sp, config.tp)
                dcn = (n_slices, 1, 1, 1, 1, 1)
                arr = mesh_utils.create_hybrid_device_mesh(
                    ici, dcn, devices=ordered
                )
                return Mesh(arr, AXIS_ORDER)
        except Exception:
            pass
        # manual hybrid layout: slice-major over the outer dp slab, so
        # mesh[d, ...] with d // (dp/n_slices) selecting the slice
        arr = np.array(ordered).reshape(
            (n_slices, config.dp // n_slices, config.pp, config.fsdp,
             config.ep, config.sp, config.tp)
        ).reshape(tuple(config.shape()[a] for a in AXIS_ORDER))
        return Mesh(arr, AXIS_ORDER)
    # pp-spanning layout: slice-major over the stage axis, so stage s
    # lives wholly on slice s // (pp/n_slices) (the stage map) and only
    # the stage-boundary ppermute crosses DCN
    arr = np.array(ordered).reshape(
        (n_slices, config.pp // n_slices, config.dp, config.fsdp,
         config.ep, config.sp, config.tp)
    ).reshape((config.pp, config.dp, config.fsdp, config.ep,
               config.sp, config.tp))
    arr = np.moveaxis(arr, 0, 1)  # -> (dp, pp, fsdp, ep, sp, tp)
    return Mesh(np.ascontiguousarray(arr), AXIS_ORDER)


def mesh_slice_of(mesh: Mesh, n_slices: int, dp_index: int) -> int:
    """Which slice a given dp-axis index lives on (slice-major layout).

    Fails loudly on a topology the layout cannot mean: ``n_slices < 1``
    or a dp axis that doesn't tile into whole slices (callers used to
    get a silent ``// 0`` crash or — worse — a wrong slice id from the
    floored quotient), and a dp index outside the axis."""
    if n_slices < 1:
        raise ValueError(f"n_slices={n_slices} must be >= 1")
    dp = mesh.shape[DP]
    if dp % n_slices:
        raise ValueError(
            f"dp={dp} does not tile into n_slices={n_slices} whole "
            "slices (the slice-major layout requires dp % n_slices == 0)"
        )
    if not 0 <= dp_index < dp:
        raise ValueError(f"dp_index={dp_index} outside dp axis of {dp}")
    per = dp // n_slices
    return dp_index // per


def mesh_slice_of_stage(mesh: Mesh, n_slices: int, pp_index: int) -> int:
    """Which slice a given pp-stage index lives on under the
    pp-spanning slice-major layout (``stage s -> slice s // (pp/n)``,
    the mesh-side face of ``WorldDescriptor.stage_map``)."""
    if n_slices < 1:
        raise ValueError(f"n_slices={n_slices} must be >= 1")
    pp = mesh.shape[PP]
    if pp % n_slices:
        raise ValueError(
            f"pp={pp} does not tile into n_slices={n_slices} whole "
            "slices (the stage-pinned layout requires pp % n_slices == 0)"
        )
    if not 0 <= pp_index < pp:
        raise ValueError(f"pp_index={pp_index} outside pp axis of {pp}")
    return pp_index // (pp // n_slices)


def config_for(world) -> MeshConfig:
    """The :class:`MeshConfig` a
    :class:`~dlrover_tpu.common.world.WorldDescriptor` describes —
    fully resolved (no ``-1`` dp), so resolve/build can't reinterpret
    it. The inverse of ``WorldDescriptor.from_axis_sizes(cfg.shape())``."""
    sizes = world.axis_sizes()
    cfg = MeshConfig(**{a: sizes.get(a, 1) for a in AXIS_ORDER})
    return cfg.resolve(world.world_size)


def mesh_for(world, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the Mesh a WorldDescriptor describes (slice-major when it
    is multislice) and CHECK the result against it — the one
    descriptor→mesh path, shared by the warm-compile speculation
    targets, the bench resize phase and planner-directed resizes, so a
    candidate world and the mesh built for it can never disagree."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)[: world.world_size]
    if len(devices) < world.world_size:
        raise ValueError(
            f"{world.spec} needs {world.world_size} devices; "
            f"{len(devices)} attached"
        )
    mesh = build_mesh(
        config_for(world), devices=devices, n_slices=world.n_slices
    )
    world.check_mesh(mesh)
    return mesh


def remesh(config: MeshConfig, n_devices: int) -> MeshConfig:
    """Re-fit a mesh config after an elastic membership change.

    Model axes (tp/sp/ep) are preserved — they are baked into checkpoint
    layouts and kernel choices. The data axes absorb the new world size,
    keeping the fsdp:dp preference of the original config. Raises if the
    new world cannot host the model axes at all (caller then falls back to
    a smaller tp/sp — a *resharding* restore, reference-equivalent of
    storage restore on world change, SURVEY.md §7 'hard parts')."""
    model = config.tp * config.sp * config.ep * config.pp
    if n_devices % model:
        raise ValueError(
            f"cannot remesh: {n_devices} devices vs model axes {model}"
        )
    data = n_devices // model
    if config.fsdp > 1 and config.dp > 1:
        # keep fsdp fixed if possible, scale dp
        if data % config.fsdp == 0:
            return dataclasses.replace(
                config, dp=data // config.fsdp
            )
        # else collapse to fsdp-only
        return dataclasses.replace(config, dp=1, fsdp=data)
    if config.fsdp > 1 or (config.dp == 1 and config.fsdp == 1):
        return dataclasses.replace(config, dp=1, fsdp=data)
    return dataclasses.replace(config, dp=data, fsdp=1)


def validate_divisibility(config: MeshConfig, *, n_heads: int,
                          n_kv_heads: int, seq_len: int, vocab: int,
                          n_layers: int = 0) -> None:
    """Fail fast (before tracing) on shape/mesh mismatches."""
    if n_layers and n_layers % max(config.pp, 1):
        raise ValueError(
            f"n_layers={n_layers} not divisible by pp={config.pp}"
        )
    if n_heads % config.tp:
        raise ValueError(f"n_heads={n_heads} not divisible by tp={config.tp}")
    if n_kv_heads % config.tp:
        raise ValueError(
            f"n_kv_heads={n_kv_heads} not divisible by tp={config.tp} "
            "(kv-head replication across tp is not supported)"
        )
    if seq_len % max(config.sp, 1):
        raise ValueError(f"seq_len={seq_len} not divisible by sp={config.sp}")
    if vocab % max(config.tp, 1):
        raise ValueError(f"vocab={vocab} not divisible by tp={config.tp}")
