"""Hierarchical DCN-aware collectives for multislice meshes.

The multislice mesh (``parallel/mesh.py``) guarantees that only the
``dp`` axis spans DCN — and then the gradient reduction runs as ONE
flat collective over the full dp axis, so every hop of the ring treats
the slow inter-slice link like ICI and the DCN cut carries the whole
gradient. FlexLink (arXiv:2510.15882, PAPERS.md) shows hierarchy- and
link-aware collective scheduling recovering double-digit bandwidth on
exactly this topology shape. This module is that strategy, TPU-native:

1. decompose the cross-slice ``dp`` axis into ``(slice, dp_in)`` —
   legal because the multislice layout is **slice-major** over dp
   (``_build_multislice_mesh``: dp index ``d`` lives on slice
   ``d // dp_in``), so reshaping the mesh's dp dimension into
   ``(n_slices, dp_in)`` preserves every device's position;
2. run the gradient reduction as **ICI reduce-scatter within each
   slice** (over ``dp_in``) → **DCN exchange of only the slice-local
   1/dp_in shard** (over ``slice``) → ICI all-gather to rebuild the
   full reduced gradient;
3. composed with zero-1 (``train/zero1.py``): in scatter mode the DCN
   leg is itself a reduce-scatter, so the DCN cut carries only the
   owned moment shard and the trailing all-gather is the existing
   param gather — no extra pass.

Like zero-1's scatter strategy, the engines here run the loss+backward
inside a **full-manual** ``shard_map`` — so they need the factory form
of the loss (``loss_factory(None)`` is the single-device local loss)
and a mesh where every non-dp axis is trivial. The shard_map binds a
*derived* mesh (:func:`hier_mesh`) over the SAME devices in the SAME
flat order, with dp split into the two named axes; base-mesh
``NamedSharding``s on the jit boundary and derived-mesh out_specs
describe identical placements, so GSPMD inserts no resharding between
them (pinned by tests/test_hier_collectives.py on the lowered HLO).

Zero-1 composition needs one local permutation: scattering first over
``dp_in`` then over ``slice`` would leave the dim sharded in
``(dp_in, slice)`` order, while the zero-1 layout (``P(..., "dp")``,
slice-major) is ``(slice, dp_in)``. The engine pre-permutes the
scatter dim — ``(n_slices, dp_in, rest) → (dp_in, n_slices, rest)`` —
so the two chained reduce-scatters land each rank exactly on its
zero-1 shard, bitwise contiguous (tests pin parity vs the flat
``psum_scatter``).

Strategy selection (:func:`mode_for`) is per-mesh, driven by
``TrainConfig.hier_collectives`` with the ``DLROVER_TPU_HIER_COLLECTIVES``
typed flag overriding in both directions; the flat path is the
kill-switch fallback and stays byte-identical to before.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

PyTree = Any

#: derived-mesh axis names the hier engines introduce. "slice" is the
#: DCN axis (outermost), "dp_in" the within-slice ICI remainder of dp.
SLICE_AXIS = "slice"
DP_IN_AXIS = "dp_in"

#: default size bound (MiB) of one overlap bucket — each bucket is one
#: fused DCN collective in the exchange half of the pipeline; the
#: DLROVER_TPU_OVERLAP_BUCKET_MB typed flag overrides it
DEFAULT_BUCKET_MB = 4

__all__ = [
    "SLICE_AXIS",
    "DP_IN_AXIS",
    "DEFAULT_BUCKET_MB",
    "enabled",
    "overlap_enabled",
    "overlap_bucket_bytes",
    "mode_for",
    "hier_mesh",
    "split_spec",
    "hier_value_and_grad",
    "overlap_value_and_grad",
    "hier_param_gather",
]


def enabled(train_config) -> bool:
    """Effective hier-collectives setting: the
    ``DLROVER_TPU_HIER_COLLECTIVES`` env flag when set (``0`` = off,
    anything else = on), else the ``TrainConfig.hier_collectives``
    knob."""
    flag = flags.HIER_COLLECTIVES
    if flag.present():
        return flag.get() != "0"
    return bool(getattr(train_config, "hier_collectives", True))


def overlap_enabled(train_config) -> bool:
    """Effective overlap-schedule setting: the
    ``DLROVER_TPU_OVERLAP_COLLECTIVES`` env flag when set (``0`` =
    kill-switch, anything else = on), else the
    ``TrainConfig.overlap_collectives`` knob."""
    flag = flags.OVERLAP_COLLECTIVES
    if flag.present():
        return flag.get() != "0"
    return bool(getattr(train_config, "overlap_collectives", True))


def overlap_bucket_bytes() -> int:
    """Size bound of one overlap bucket in bytes (the
    ``DLROVER_TPU_OVERLAP_BUCKET_MB`` flag, else
    :data:`DEFAULT_BUCKET_MB`)."""
    mb = flags.OVERLAP_BUCKET_MB.get()
    if mb is None or mb <= 0:
        mb = DEFAULT_BUCKET_MB
    return int(mb) << 20


#: one-time latch for the mixed-mesh silent-fallback warning (the
#: documented mode_for gap): warn the first time a genuinely multislice
#: mixed mesh falls back to flat, naming the flag, then stay quiet
_warned_mixed_flat = False


def mode_for(
    mesh,
    n_slices: int,
    train_config,
    has_factory: bool,
    zero1_mode: str = "off",
    enabled_override: Optional[bool] = None,
    overlap_override: Optional[bool] = None,
) -> str:
    """``"flat"`` | ``"hier"`` | ``"overlap"`` for this build.

    ``hier`` needs: >1 slice; a dp axis that actually decomposes
    (``dp % n_slices == 0`` with a non-trivial within-slice remainder —
    when ``dp_in == 1`` the dp axis IS the DCN axis and there is
    nothing to reduce on ICI first); every non-dp axis trivial and the
    factory form of the loss (the engines go full-manual, same
    constraint as zero-1's scatter strategy); and a zero-1 mode the
    manual engine composes with (``off`` or ``scatter`` — ``gspmd``
    zero-1 only arises on mixed meshes, which already fail the
    trivial-axes test, or without a factory).

    ``overlap`` is ``hier`` plus the latency-hiding bucketed schedule
    (:func:`overlap_value_and_grad`): same eligibility, gated by
    :func:`overlap_enabled`. It is a schedule of the SAME reduction —
    every ``mode != "flat"`` check treats the two alike.

    ``enabled_override`` / ``overlap_override`` mirror
    ``zero1.mode_for``'s: the trainer pins the flag reads once per
    build so a concurrent ``scoped`` window can never flip the answer
    between cache key and program build."""
    global _warned_mixed_flat
    on = (
        enabled(train_config)
        if enabled_override is None else enabled_override
    )
    if not on or n_slices <= 1:
        return "flat"
    shape = dict(mesh.shape)
    dp = shape.get("dp", 1)
    if dp % n_slices or dp // n_slices <= 1:
        return "flat"
    if not has_factory:
        return "flat"
    if any(s > 1 for a, s in shape.items() if a != "dp"):
        # the body is single-device model code; a non-trivial model
        # axis would need its own manual handling (or a GSPMD-level
        # schedule — docs/design/hier_collectives.md "limits" explains
        # why that stays out on this jax). Loud, once: an operator who
        # exported the flag on a mixed multislice world would otherwise
        # pay full-gradient DCN with no hint why.
        if not _warned_mixed_flat:
            _warned_mixed_flat = True
            nontrivial = {
                a: s for a, s in shape.items() if a != "dp" and s > 1
            }
            logger.warning(
                "hier collectives: multislice mesh has non-trivial "
                "model axes %s — the manual ICI-first engine needs a "
                "pure-dp mesh, running the FLAT dp reduction (full "
                "gradient on the DCN cut). DLROVER_TPU_HIER_COLLECTIVES"
                " cannot force hier here; see docs/design/"
                "hier_collectives.md (limits).", nontrivial,
            )
        return "flat"
    if zero1_mode == "gspmd":
        return "flat"
    if SLICE_AXIS in shape or DP_IN_AXIS in shape:
        logger.warning(
            "hier collectives: mesh already has a %r/%r axis; flat path",
            SLICE_AXIS, DP_IN_AXIS,
        )
        return "flat"
    ov = (
        overlap_enabled(train_config)
        if overlap_override is None else overlap_override
    )
    return "overlap" if ov else "hier"


def hier_mesh(mesh, n_slices: int):
    """The derived mesh: same devices, same flat order, with the dp
    axis split into ``(slice, dp_in)``. Because the multislice layout
    is slice-major over dp, this is a pure C-order reshape — a value
    sharded over ``dp`` on the base mesh is *identically placed* when
    sharded over ``("slice", "dp_in")`` here."""
    from jax.sharding import Mesh

    shape = dict(mesh.shape)
    dp = shape.get("dp", 1)
    if dp % n_slices:
        raise ValueError(
            f"dp={dp} not divisible by n_slices={n_slices}"
        )
    dp_in = dp // n_slices
    names, dims = [], []
    for ax in mesh.axis_names:
        if ax == "dp":
            names += [SLICE_AXIS, DP_IN_AXIS]
            dims += [n_slices, dp_in]
        else:
            names.append(ax)
            dims.append(shape[ax])
    return Mesh(mesh.devices.reshape(tuple(dims)), tuple(names))


def split_spec(spec):
    """Translate a base-mesh PartitionSpec for the derived mesh:
    every ``"dp"`` entry becomes the ``("slice", "dp_in")`` pair in
    place (order preserved inside tuple entries — slice-major, the
    same placement)."""
    from jax.sharding import PartitionSpec as P

    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        new = []
        for a in axes:
            if a == "dp":
                new += [SLICE_AXIS, DP_IN_AXIS]
            else:
                new.append(a)
        out.append(tuple(new) if len(new) > 1 else new[0])
    return P(*out)


def _first_divisible_dim(shape, k: int) -> Optional[int]:
    """Leading dim whose extent divides by ``k`` (for picking the ICI
    reduce-scatter dim of a replicated-output leaf)."""
    for dim, extent in enumerate(shape):
        if extent > 0 and extent % k == 0:
            return dim
    return None


def hier_value_and_grad(
    local_loss, mesh, n_slices: int, p_specs, params,
    zero1_scatter: bool = False,
):
    """The hierarchical grad engine: a full-manual ``shard_map`` over
    :func:`hier_mesh` whose body runs the *local* loss+backward and
    reduces each grad leaf ICI-first. Returns ``fn(params, micro) ->
    (loss, grads)`` with ``loss`` the global-mean scalar.

    ``zero1_scatter=False`` (replicated weight update): each grad leaf
    comes back FULL and replicated over dp — reduce-scatter over
    ``dp_in`` (ICI), psum over ``slice`` (DCN carries the 1/dp_in
    shard), all-gather over ``dp_in`` (ICI). Leaves with no
    dp_in-divisible dim fall back to a flat psum over both axes (DCN
    carries the whole leaf — scalars and tiny odd shapes only).

    ``zero1_scatter=True``: grads land directly in the zero-1 layout
    (``zero1.partition_spec``) — reduce-scatter over ``dp_in`` (ICI)
    then reduce-scatter over ``slice`` (the DCN cut carries only the
    slice-local 1/dp_in shard and emits the owned 1/dp moment shard);
    the trailing all-gather is the step's existing param gather. The
    scatter dim is pre-permuted ``(slice, dp_in) → (dp_in, slice)`` so
    the chained scatters land each rank on its slice-major zero-1
    shard (see module docstring). Non-divisible leaves take the
    replicated hierarchical reduce, exactly like zero-1's flat psum
    fallback.

    ``params`` may be live arrays, tracers or avatars: only ``.shape``
    is read.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.ops.shard_map_compat import shard_map
    from dlrover_tpu.parallel.sharding import batch_spec
    from dlrover_tpu.train import zero1

    hmesh = hier_mesh(mesh, n_slices)
    axis_sizes = dict(mesh.shape)
    dp = axis_sizes["dp"]
    dp_in = dp // n_slices
    inv_dp = 1.0 / dp
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    if zero1_scatter:
        dims = jax.tree.map(
            lambda s, leaf: zero1.scatter_dim(s, leaf.shape, axis_sizes),
            p_specs, params, is_leaf=is_spec,
        )
        out_grad_specs = jax.tree.map(
            lambda s, leaf: split_spec(
                zero1.partition_spec(s, leaf.shape, axis_sizes) or s
            ),
            p_specs, params, is_leaf=is_spec,
        )
    else:
        dims = jax.tree.map(lambda s: None, p_specs, is_leaf=is_spec)
        out_grad_specs = jax.tree.map(split_spec, p_specs, is_leaf=is_spec)

    def reduce_replicated(leaf):
        """full grad, replicated over dp: RS(ici) → psum(dcn) → AG(ici)."""
        d = _first_divisible_dim(leaf.shape, dp_in)
        if d is None:
            # scalars / odd tiny shapes: flat psum (whole leaf on DCN)
            return lax.psum(leaf, (DP_IN_AXIS, SLICE_AXIS)) * inv_dp
        part = lax.psum_scatter(
            leaf, DP_IN_AXIS, scatter_dimension=d, tiled=True
        )
        part = lax.psum(part, SLICE_AXIS)
        return lax.all_gather(
            part, DP_IN_AXIS, axis=d, tiled=True
        ) * inv_dp

    def reduce_scattered(d, leaf):
        """zero-1 shard, slice-major: permute → RS(ici) → RS(dcn)."""
        shp = leaf.shape
        gg = leaf.reshape(
            shp[:d] + (n_slices, dp_in, shp[d] // dp) + shp[d + 1:]
        )
        gg = jnp.swapaxes(gg, d, d + 1).reshape(shp)
        part = lax.psum_scatter(
            gg, DP_IN_AXIS, scatter_dimension=d, tiled=True
        )
        return lax.psum_scatter(
            part, SLICE_AXIS, scatter_dimension=d, tiled=True
        ) * inv_dp

    def body(p, micro):
        loss, g = jax.value_and_grad(local_loss)(p, micro)

        def reduce_leaf(dim, leaf):
            if zero1_scatter and dim is not None:
                return reduce_scattered(dim, leaf)
            return reduce_replicated(leaf)

        g = jax.tree.map(
            reduce_leaf, dims, g,
            is_leaf=lambda x: x is None or isinstance(x, int),
        )
        # global batch mean = mean of equal-sized local means (scalar:
        # the DCN half of this psum moves 4 bytes)
        return lax.psum(loss, (DP_IN_AXIS, SLICE_AXIS)) * inv_dp, g

    split_p_specs = jax.tree.map(split_spec, p_specs, is_leaf=is_spec)

    def fn(p, micro):
        micro_specs = jax.tree.map(
            lambda _: split_spec(batch_spec()), micro
        )
        return shard_map(
            body, mesh=hmesh,
            in_specs=(split_p_specs, micro_specs),
            out_specs=(P(), out_grad_specs),
            check_vma=False,
        )(p, micro)

    return fn


def _partition_buckets(items, sizes, bound: int):
    """Greedy size-bounded partition of ``items`` (kept in order) into
    buckets whose summed ``sizes`` stay under ``bound`` — an oversized
    item gets a bucket of its own. Deterministic in (items, sizes,
    bound): the bucket layout is part of the program identity."""
    buckets, cur, cur_bytes = [], [], 0
    for item, size in zip(items, sizes):
        if cur and cur_bytes + size > bound:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(item)
        cur_bytes += size
    if cur:
        buckets.append(cur)
    return buckets


def overlap_value_and_grad(
    local_loss, mesh, n_slices: int, p_specs, params,
    zero1_scatter: bool = False,
    bucket_bytes: Optional[int] = None,
):
    """The latency-hiding split of :func:`hier_value_and_grad` —
    FlexLink's second half: the same ICI-first hierarchical reduction,
    cut into a ``compute`` half and an ``exchange`` half so the trainer
    can carry the DCN leg of microbatch N through the accumulation scan
    and hide it behind the backward of microbatch N+1.

    Returns ``(compute_fn, exchange_fn)``:

    - ``compute_fn(params, micro) -> (loss, pending)`` runs the local
      loss+backward and ONLY the eager ICI leg per grad leaf
      (reduce-scatter over ``dp_in``; zero-1 leaves pre-permuted
      slice-major first, exactly like the fused engine; non-divisible
      leaves psum over ``dp_in``). ``pending`` is a flat list of
      slice-local partials — every leaf carried with a leading
      ``(slice, dp_in)``-sharded stacking axis, so it crosses the
      shard_map boundary as a global array and rides a ``lax.scan``
      carry untouched.
    - ``exchange_fn(pending) -> grads`` runs the deferred DCN leg —
      partials are grouped into size-bounded buckets
      (``DLROVER_TPU_OVERLAP_BUCKET_MB``) and each bucket is ONE fused
      DCN collective: a single ``psum`` over ``slice`` of the bucket's
      concatenated partials (replicated update + non-divisible leaves),
      or a single ``psum_scatter`` over ``slice`` straight into the
      owned zero-1 shards — then the trailing ICI all-gather per
      replicated leaf. Because the exchange consumes only the CARRIED
      pending (data-independent of the current iteration's backward),
      the scheduler is free to run the DCN transfer under compute; the
      shardcheck overlap dimension proves it from the lowered HLO.

    Addition order per element is identical to the fused engine's —
    compute+exchange back-to-back IS ``hier_value_and_grad`` (the
    bucket concat only batches independent elements through one op) —
    which is what makes the flat↔hier↔overlap parity suite tight.

    ``params`` may be live arrays, tracers or avatars: only ``.shape``
    and ``.dtype`` are read.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.ops.shard_map_compat import shard_map
    from dlrover_tpu.parallel.sharding import batch_spec
    from dlrover_tpu.train import zero1

    hmesh = hier_mesh(mesh, n_slices)
    axis_sizes = dict(mesh.shape)
    dp = axis_sizes["dp"]
    dp_in = dp // n_slices
    inv_dp = 1.0 / dp
    if bucket_bytes is None:
        bucket_bytes = overlap_bucket_bytes()
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    # flatten once; the pending list and every bucket layout follow
    # this leaf order (deterministic: part of the program identity)
    spec_leaves, treedef = jax.tree.flatten(p_specs, is_leaf=is_spec)
    param_leaves = treedef.flatten_up_to(params)

    # per-leaf plan: ("scatter", d) lands in the zero-1 layout via a
    # slice psum_scatter; ("repl", d) rebuilds the full leaf via slice
    # psum + dp_in all-gather; ("residual", None) has no dp_in- (or
    # dp-) divisible dim — eager psum(dp_in), deferred psum(slice)
    plans = []
    for spec, leaf in zip(spec_leaves, param_leaves):
        if zero1_scatter:
            d = zero1.scatter_dim(spec, leaf.shape, axis_sizes)
            plans.append(("scatter", d) if d is not None
                         else ("residual", None))
        else:
            d = _first_divisible_dim(leaf.shape, dp_in)
            plans.append(("repl", d) if d is not None
                         else ("residual", None))

    def _block_shape(kind, d, shape):
        if kind == "residual":
            return tuple(shape)
        return tuple(shape[:d]) + (shape[d] // dp_in,) + tuple(
            shape[d + 1:]
        )

    block_bytes = [
        int(np.prod(_block_shape(k, d, leaf.shape), dtype=np.int64)
            or 1) * np.dtype(leaf.dtype).itemsize
        for (k, d), leaf in zip(plans, param_leaves)
    ]
    # two bucket streams: psum-kind (repl + residual share the fused
    # slice psum; they differ only in ICI post-processing) and
    # scatter-kind (the fused op is a slice psum_scatter)
    psum_idx = [i for i, (k, _) in enumerate(plans) if k != "scatter"]
    scat_idx = [i for i, (k, _) in enumerate(plans) if k == "scatter"]
    psum_buckets = _partition_buckets(
        psum_idx, [block_bytes[i] for i in psum_idx], bucket_bytes
    )
    scat_buckets = _partition_buckets(
        scat_idx, [block_bytes[i] for i in scat_idx], bucket_bytes
    )

    if zero1_scatter:
        out_grad_specs = [
            split_spec(
                zero1.partition_spec(s, leaf.shape, axis_sizes) or s
            )
            for s, leaf in zip(spec_leaves, param_leaves)
        ]
    else:
        out_grad_specs = [split_spec(s) for s in spec_leaves]
    split_p_specs = jax.tree.map(split_spec, p_specs, is_leaf=is_spec)
    # pending leaves stack the per-slice partials on a leading axis
    # sharded over the WHOLE decomposed dp — one block per device, a
    # plain global array between the two shard_maps and in the carry
    pending_spec = P((SLICE_AXIS, DP_IN_AXIS))

    def compute_body(p, micro):
        loss, g = jax.value_and_grad(local_loss)(p, micro)
        g_leaves = treedef.flatten_up_to(g)
        pending = []
        for (kind, d), leaf in zip(plans, g_leaves):
            if kind == "residual":
                part = lax.psum(leaf, DP_IN_AXIS)
            elif kind == "scatter":
                shp = leaf.shape
                gg = leaf.reshape(
                    shp[:d] + (n_slices, dp_in, shp[d] // dp)
                    + shp[d + 1:]
                )
                gg = jnp.swapaxes(gg, d, d + 1).reshape(shp)
                part = lax.psum_scatter(
                    gg, DP_IN_AXIS, scatter_dimension=d, tiled=True
                )
            else:  # repl
                part = lax.psum_scatter(
                    leaf, DP_IN_AXIS, scatter_dimension=d, tiled=True
                )
            pending.append(part[None])  # leading (slice, dp_in) axis
        # global batch mean, reduced eagerly (4 DCN bytes — the grad
        # payload is what the pipeline defers)
        loss = lax.psum(loss, (DP_IN_AXIS, SLICE_AXIS)) * inv_dp
        return loss, pending

    def exchange_body(pending):
        blocks = [x[0] for x in pending]
        out = [None] * len(blocks)
        for bucket in psum_buckets:
            flat = jnp.concatenate(
                [blocks[i].reshape(-1) for i in bucket]
            )
            flat = lax.psum(flat, SLICE_AXIS)  # ONE fused DCN leg
            off = 0
            for i in bucket:
                size = int(np.prod(blocks[i].shape, dtype=np.int64)
                           or 1)
                piece = flat[off:off + size].reshape(blocks[i].shape)
                off += size
                kind, d = plans[i]
                if kind == "repl":
                    piece = lax.all_gather(
                        piece, DP_IN_AXIS, axis=d, tiled=True
                    )
                out[i] = piece * inv_dp
        for bucket in scat_buckets:
            rows = []
            for i in bucket:
                d = plans[i][1]
                b = blocks[i]
                pre, post = b.shape[:d], b.shape[d + 1:]
                shard = b.shape[d] // n_slices
                x = b.reshape(pre + (n_slices, shard) + post)
                x = jnp.moveaxis(x, len(pre), 0)
                rows.append(x.reshape(n_slices, -1))
            cat = jnp.concatenate(rows, axis=1)
            red = lax.psum_scatter(  # ONE fused DCN leg → owned shards
                cat, SLICE_AXIS, scatter_dimension=0, tiled=True
            )
            off = 0
            for i in bucket:
                d = plans[i][1]
                b = blocks[i]
                pre, post = b.shape[:d], b.shape[d + 1:]
                shard = b.shape[d] // n_slices
                size = int(np.prod(
                    pre + (shard,) + post, dtype=np.int64) or 1)
                piece = red[0, off:off + size].reshape(
                    pre + (shard,) + post
                )
                off += size
                out[i] = piece * inv_dp
        return out

    def compute_fn(p, micro):
        micro_specs = jax.tree.map(
            lambda _: split_spec(batch_spec()), micro
        )
        return shard_map(
            compute_body, mesh=hmesh,
            in_specs=(split_p_specs, micro_specs),
            out_specs=(P(), [pending_spec] * len(plans)),
            check_vma=False,
        )(p, micro)

    def exchange_fn(pending):
        leaves = shard_map(
            exchange_body, mesh=hmesh,
            in_specs=([pending_spec] * len(plans),),
            out_specs=out_grad_specs,
            check_vma=False,
        )(pending)
        return jax.tree.unflatten(treedef, leaves)

    return compute_fn, exchange_fn


def hier_param_gather(mesh, n_slices: int, p_specs, params):
    """Hierarchize the zero-1 trailing param all-gather on a multislice
    pure-dp mesh: instead of the flat GSPMD gather over the whole dp
    axis (whose DCN cut carries ``param_bytes × (1 − 1/s)``), gather
    the owned 1/dp shard over ``slice`` FIRST — the DCN leg moves only
    the slice-local ``1/dp_in`` of the params — then over ``dp_in`` on
    ICI, then undo the ``(dp_in, slice)`` block interleave locally (the
    zero-1 layout is slice-major; gathering slice-first brings the
    blocks back dp_in-major). Pure data movement: bitwise identical to
    the flat gather.

    Returns ``fn(params) -> params`` taking leaves in the zero-1 layout
    (``zero1.partition_spec``) and returning them in their base layout;
    leaves the sharding rule left replicated pass through untouched.
    ``params`` may be live arrays, tracers or avatars (only ``.shape``
    is read)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.ops.shard_map_compat import shard_map
    from dlrover_tpu.train import zero1

    hmesh = hier_mesh(mesh, n_slices)
    axis_sizes = dict(mesh.shape)
    dp = axis_sizes["dp"]
    dp_in = dp // n_slices
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    dims = jax.tree.map(
        lambda s, leaf: zero1.scatter_dim(s, leaf.shape, axis_sizes),
        p_specs, params, is_leaf=is_spec,
    )
    in_specs = jax.tree.map(
        lambda s, leaf: split_spec(
            zero1.partition_spec(s, leaf.shape, axis_sizes) or s
        ),
        p_specs, params, is_leaf=is_spec,
    )
    out_specs = jax.tree.map(split_spec, p_specs, is_leaf=is_spec)

    def body(p):
        def gather_leaf(d, leaf):
            if d is None:
                return leaf  # replicated fallback: nothing to gather
            x = lax.all_gather(leaf, SLICE_AXIS, axis=d, tiled=True)
            x = lax.all_gather(x, DP_IN_AXIS, axis=d, tiled=True)
            shp = x.shape
            xx = x.reshape(
                shp[:d] + (dp_in, n_slices, shp[d] // dp) + shp[d + 1:]
            )
            return jnp.swapaxes(xx, d, d + 1).reshape(shp)

        return jax.tree.map(
            gather_leaf, dims, p,
            is_leaf=lambda x: x is None or isinstance(x, int),
        )

    def fn(p):
        return shard_map(
            body, mesh=hmesh,
            in_specs=(in_specs,),
            out_specs=out_specs,
            check_vma=False,
        )(p)

    return fn
