"""Attention: jnp reference + Pallas TPU flash-attention forward AND backward.

Layout convention everywhere: ``(batch, seq, n_heads, head_dim)``; GQA via
``n_kv_heads <= n_heads`` (kv head ``h // group`` serves query head ``h``
— resolved in the kernels' BlockSpec index_maps, never materialized).

`flash_attention_with_lse` is a `jax.custom_vjp` returning ``(out, lse)``
where ``lse`` is the per-row logsumexp of the attention logits:

- **forward**: Pallas online-softmax kernel — O(seq) memory, MXU-tiled
  blocks, the s×s matrix never exists.
- **backward**: two Pallas kernels (dq, then dk/dv) that *recompute*
  probabilities blockwise from (q, k, v, lse) — also O(seq) memory. The
  ``lse`` output is differentiable: its cotangent folds into the standard
  flash-backward ``delta`` term (``ds = p * (dp - delta + g_lse)``), which
  is what lets ring attention merge per-chunk results by logsumexp and
  still get exact gradients through the merge.

On non-TPU backends both directions fall back to the jnp reference, so the
same model code runs in CPU tests; ``interpret=True`` runs the Pallas
kernels in interpreter mode for numerics tests without a TPU.

The reference framework has no attention op at all (it launches
Megatron/DeepSpeed which own the math, SURVEY.md §2.8) — this is part of
the green-field TPU compute path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas imports fail on some backends; the reference path still works
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30

# lse/delta carry a broadcast minor lane dim so TPU block shapes tile
# ((second-to-last, last) must be (divisible by 8, divisible by 128) or
# equal to the array dims — 8 lanes satisfies "equal", at 1/16th the HBM
# of upstream flash-attention's 128-lane convention)
_LSE_LANES = 8


def mha_reference_with_lse(
    q: jnp.ndarray,  # (b, sq, h, d)
    k: jnp.ndarray,  # (b, sk, hkv, d)
    v: jnp.ndarray,  # (b, sk, hkv, d)
    causal: bool = True,
    q_offset=0,
    k_offset=0,
):
    """Stable-softmax attention in float32, GQA-aware; returns
    ``(out (b,sq,h,d), lse (b,h,sq))``. ``q_offset`` / ``k_offset`` are
    *global* positions of element 0 — this is what lets ring-attention
    chunks mask causally against each other."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    if causal:
        qpos = q_offset + jnp.arange(sq)
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    lse = jax.nn.logsumexp(logits, axis=-1)  # (b, h, sq)
    probs = jnp.exp(logits - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype), lse


def mha_reference(q, k, v, causal: bool = True, q_offset=0, k_offset=0):
    return mha_reference_with_lse(
        q, k, v, causal=causal, q_offset=q_offset, k_offset=k_offset
    )[0]


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, block_q: int, block_k: int, n_kblocks: int, causal: bool, scale: float
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Entire k block above the causal diagonal → skip all compute.
    if causal:
        block_needed = ki * block_k <= qi * block_q + block_q - 1
    else:
        block_needed = qi >= 0  # always true, traced

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (bq, bk)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_ref[:, 0]                                  # (bq,)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        corr = jnp.exp(m_prev - m_cur)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, 0] = m_cur

    @pl.when(ki == n_kblocks - 1)
    def _finalize():
        l = l_ref[:, 0]
        lsafe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / lsafe[:, None]).astype(o_ref.dtype)
        # lse carries a broadcast minor lane dim for TPU block tiling
        # (see _LSE_LANES)
        lse = m_ref[:, 0] + jnp.log(lsafe)
        lse_ref[0, 0] = jnp.broadcast_to(lse[:, None], lse_ref[0, 0].shape)


def _flash_fwd_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                      interpret: bool = False):
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    group = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    n_q, n_k = sq // block_q, sk // block_k
    scale = 1.0 / math.sqrt(d)

    # (b, s, h, d) → (b, h, s, d) so the contiguous minor dims tile cleanly.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, h, n_q, n_k)
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_q=block_q, block_k=block_k, n_kblocks=n_k,
        causal=causal, scale=scale,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda bi, hi, qi, ki, _g=group: (bi, hi // _g, ki, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda bi, hi, qi, ki, _g=group: (bi, hi // _g, ki, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, _LSE_LANES),
                lambda bi, hi, qi, ki: (bi, hi, qi, 0),
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


# ---------------------------------------------------------------------------
# Pallas TPU backward kernels
# ---------------------------------------------------------------------------
#
# Standard flash backward, blockwise recompute from (q, k, v, lse):
#   p  = exp(s - lse)            s = scale * q @ k^T  (+ causal mask)
#   dp = do @ v^T
#   ds = p * (dp - delta) * scale     delta = rowsum(do * o) - g_lse
#   dq = ds @ k ; dk = ds^T @ q ; dv = p^T @ do
# dq iterates k blocks per q block; dk/dv iterates q blocks per k block
# (per *query* head — the group sum down to kv heads happens outside,
# keeping the kernels free of cross-block output contention).


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    *, block_q: int, block_k: int, n_kblocks: int, causal: bool, scale: float
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if causal:
        block_needed = ki * block_k <= qi * block_q + block_q - 1
    else:
        block_needed = qi >= 0

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]                             # (bq,)
        delta = delta_ref[0, 0, :, 0]                         # (bq,)
        s = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                         # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_kblocks - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, block_q: int, block_k: int, n_qblocks: int, causal: bool, scale: float
):
    # kv-head-major: grid dim 1 is the KV head; dim 3 sweeps
    # (query_head_in_group, q_block) pairs so the group's contributions
    # accumulate in VMEM and dk/dv are written once per kv head — no
    # (b, h, sk, d) per-query-head buffers in HBM (round-2 Weak #7).
    ki = pl.program_id(2)
    j = pl.program_id(3)
    qi = j % n_qblocks

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    if causal:
        block_needed = qi * block_q + block_q - 1 >= ki * block_k
    else:
        block_needed = ki >= 0

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0]
        delta = delta_ref[0, 0, :, 0]
        s = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (bq, bk)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        # dv += p^T @ do
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        # dk += ds^T @ q
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, g_lse, causal,
                      block_q, block_k, interpret=False):
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    group = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    n_q, n_k = sq // block_q, sk // block_k
    scale = 1.0 / math.sqrt(d)

    # delta rows; the lse cotangent folds in here (see module docstring)
    delta = jnp.einsum(
        "bshd,bshd->bhs", do.astype(jnp.float32), o.astype(jnp.float32)
    )
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    # broadcast minor lane dim for TPU block tiling (see fwd kernel)
    lse4 = jnp.broadcast_to(lse[..., None], (b, h, sq, _LSE_LANES))
    delta4 = jnp.broadcast_to(delta[..., None], (b, h, sq, _LSE_LANES))

    # -- dq: grid (b, h, n_q, n_k), q block fixed per-(i), k rotates (j) --
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k,
            n_kblocks=n_k, causal=causal, scale=scale,
        ),
        grid=(b, h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda bi, hi, i, j, _g=group: (bi, hi // _g, j, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda bi, hi, i, j, _g=group: (bi, hi // _g, j, 0),
            ),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
            pl.BlockSpec(
                (1, 1, block_q, _LSE_LANES),
                lambda bi, hi, i, j: (bi, hi, i, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_q, _LSE_LANES),
                lambda bi, hi, i, j: (bi, hi, i, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, i, j: (bi, hi, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse4, delta4)

    # -- dk/dv: kv-head-major grid (b, hkv, n_k, group*n_q): the group's
    # query heads accumulate into one VMEM scratch per kv head, so HBM
    # holds (b, hkv, sk, d) outputs — group x less traffic than the
    # per-query-head form (round-2 Weak #7), which matters at 8:1 GQA.
    def _q_head(bi, hi, i, j, _g=group, _nq=n_q):
        return (bi, hi * _g + j // _nq, j % _nq, 0)

    dkh, dvh = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            n_qblocks=n_q, causal=causal, scale=scale,
        ),
        grid=(b, hkv, n_k, group * n_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), _q_head),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, block_q, d), _q_head),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES), _q_head),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES), _q_head),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, i, j: (bi, hi, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt, dot, lse4, delta4)

    dq = dq.transpose(0, 2, 1, 3)
    dk = dkh.transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dvh.transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# custom_vjp surfaces
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal: bool = True,
                             block_q: int = 128, block_k: int = 128,
                             interpret: bool = False):
    """(out (b,s,h,d), lse (b,h,s)) — both differentiable."""
    return _flash_with_lse_fwd(q, k, v, causal, block_q, block_k, interpret)[0]


def _flash_with_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    # named scope = the kernel ledger's attribution key
    # (profiler/kernel_ledger.py classifies HLO sites by op_name path)
    with jax.named_scope("attention_fwd"):
        if _HAS_PALLAS and (interpret or _on_tpu()):
            out, lse = _flash_fwd_pallas(q, k, v, causal, block_q,
                                         block_k, interpret=interpret)
        else:
            out, lse = mha_reference_with_lse(q, k, v, causal=causal)
    return (out, lse), (q, k, v, out, lse)


def _flash_with_lse_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    g_out, g_lse = g
    with jax.named_scope("attention_bwd"):
        if _HAS_PALLAS and (interpret or _on_tpu()):
            return _flash_bwd_pallas(
                q, k, v, o, lse, g_out, g_lse, causal, block_q, block_k,
                interpret=interpret,
            )
        _, vjp = jax.vjp(
            lambda q, k, v: mha_reference_with_lse(q, k, v,
                                                   causal=causal),
            q, k, v,
        )
        return vjp((g_out, g_lse))


flash_attention_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    return flash_attention_with_lse(
        q, k, v, causal, block_q, block_k, interpret
    )[0]
