"""Attention: jnp reference + Pallas TPU flash-attention forward.

Layout convention everywhere: ``(batch, seq, n_heads, head_dim)``; GQA via
``n_kv_heads <= n_heads`` (kv head ``h // group`` serves query head ``h``
— resolved in the kernel's BlockSpec index_map, never materialized).

`flash_attention` is a `jax.custom_vjp`: the forward pass runs a Pallas
online-softmax kernel on TPU (O(seq) memory, MXU-tiled 128-blocks, never
materializing the s×s matrix); the backward recomputes attention with the
jnp reference under XLA — flash-backward is a later-round kernel. On
non-TPU backends the forward falls back to the reference, so the same model
code runs in CPU tests.

The reference framework has no attention op at all (it launches
Megatron/DeepSpeed which own the math, SURVEY.md §2.8) — this is part of
the green-field TPU compute path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas imports fail on some backends; the reference path still works
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30


def mha_reference(
    q: jnp.ndarray,  # (b, sq, h, d)
    k: jnp.ndarray,  # (b, sk, hkv, d)
    v: jnp.ndarray,  # (b, sk, hkv, d)
    causal: bool = True,
    q_offset=0,
    k_offset=0,
) -> jnp.ndarray:
    """Stable-softmax attention in float32, GQA-aware. ``q_offset`` /
    ``k_offset`` are *global* positions of element 0 — this is what lets
    ring-attention chunks mask causally against each other."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    if group > 1:
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    scale = 1.0 / math.sqrt(d)
    qf = q.astype(jnp.float32) * scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
    if causal:
        qpos = q_offset + jnp.arange(sq)
        kpos = k_offset + jnp.arange(k.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, block_q: int, block_k: int, n_kblocks: int, causal: bool, scale: float
):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Entire k block above the causal diagonal → skip all compute.
    if causal:
        block_needed = ki * block_k <= qi * block_q + block_q - 1
    else:
        block_needed = qi >= 0  # always true, traced

    @pl.when(block_needed)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0, 0].astype(jnp.float32)                  # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (bq, bk)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_ref[:, 0]                                  # (bq,)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_cur[:, None])
        corr = jnp.exp(m_prev - m_cur)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, 0] = m_cur

    @pl.when(ki == n_kblocks - 1)
    def _finalize():
        l = l_ref[:, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal: bool, block_q: int, block_k: int,
                      interpret: bool = False):
    b, sq, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    group = h // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    n_q, n_k = sq // block_q, sk // block_k
    scale = 1.0 / math.sqrt(d)

    # (b, s, h, d) → (b, h, s, d) so the contiguous minor dims tile cleanly.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    grid = (b, h, n_q, n_k)
    kernel = functools.partial(
        _flash_fwd_kernel,
        block_q=block_q, block_k=block_k, n_kblocks=n_k,
        causal=causal, scale=scale,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda bi, hi, qi, ki, _g=group: (bi, hi // _g, ki, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda bi, hi, qi, ki, _g=group: (bi, hi // _g, ki, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 128, block_k: int = 128):
    return _flash_attention_fwd(q, k, v, causal, block_q, block_k)[0]


def _flash_attention_fwd(q, k, v, causal, block_q, block_k):
    if _HAS_PALLAS and _on_tpu():
        out = _flash_fwd_pallas(q, k, v, causal, block_q, block_k)
    else:
        out = mha_reference(q, k, v, causal=causal)
    return out, (q, k, v)


def _flash_attention_bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: mha_reference(q, k, v, causal=causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)
