"""Fused-CE Pallas TPU kernel: unembed matmul + softmax-CE per vocab tile
in VMEM — the kernel rung above ops/chunked_ce.py.

The chunked-CE scan (PR 1) already keeps the [B, T, V] logits out of HBM,
but each scan step still materializes a [tokens, chunk] f32 logits buffer
in HBM between the matmul and the online-softmax update. This kernel
closes that last round-trip: a vocab tile's logits live only in VMEM
registers between the MXU matmul and the streaming-lse update, exactly as
flash attention (ops/attention.py) keeps the s×s matrix out of HBM.

Structure mirrors the chunked path's custom-VJP 1:1 so the two stay
bitwise-comparable under tolerance:

- **forward** (grid token-blocks × vocab-tiles): per-tile logits
  ``x_blk @ w_tile`` with f32 MXU accumulation, online-softmax carry
  ``(m, s)`` in VMEM scratch, target-logit gather via an iota==target
  one-hot reduction (the target's column lands in exactly one tile); the
  last tile finalizes per-token ``logz`` and ``gold``. O(tokens) outputs.
- **backward**: two kernels recomputing tile logits from the saved
  ``(x, w, logz)`` residual — ``dx`` token-major (vocab tiles accumulate
  in VMEM), ``dw`` vocab-major (token blocks accumulate in VMEM, each
  vocab tile written exactly once) — the dq/dkv split from the flash
  backward, ported to the CE geometry.

Dispatch contract (``cross_entropy_sums``): the Pallas kernel runs only
on TPU (or under ``interpret=True`` for CPU numerics tests); everywhere
else — and when the ``DLROVER_TPU_FUSED_CE=0`` kill-switch is set — the
scan-based ``chunked_cross_entropy`` is the fallback, so CPU tests,
contract lowering and bisection all keep the PR 1 program. Same
``(nll_sum, n_valid)`` two-number return, same ``targets < 0`` pad
sentinel, same f32 accumulation contract.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.common import flags
from dlrover_tpu.ops.chunked_ce import (
    DEFAULT_CHUNK_SIZE,
    chunked_cross_entropy,
)

try:  # pallas imports fail on some backends; the chunked fallback remains
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30

#: Broadcast minor lane dim for per-token (1-D) kernel operands/results —
#: same convention as ops/attention.py's lse (block shapes need a minor
#: dim divisible by 128 or equal to the array dim).
_LANES = 8

#: Default tile geometry: 256 tokens × 512 vocab columns keeps the live
#: tile (256×512 f32 = 512 KB) plus the (block_t, d) / (d, block_v)
#: operand blocks comfortably inside a v5e core's VMEM at d=2048.
DEFAULT_BLOCK_T = 256
DEFAULT_BLOCK_V = 512


def fused_ce_enabled() -> bool:
    """Env kill-switch (bisection aid): ``DLROVER_TPU_FUSED_CE=0``
    restores the scan-based chunked-CE program even on TPU. Read at
    trace time — set it before the first loss call / trainer step of the
    process (the jitted step caches the trace)."""
    return flags.FUSED_CE.get()


def fused_ce_available(interpret: bool = False) -> bool:
    """True when the Pallas kernel can actually run here: Pallas
    importable AND (TPU backend or interpreter mode). The dispatcher
    below and the bench sweep both key off this."""
    return _HAS_PALLAS and (interpret or _on_tpu())


def cross_entropy_sums(
    x: jnp.ndarray,
    w_unembed: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    chunk_size: int = DEFAULT_CHUNK_SIZE,
    block_t: int = DEFAULT_BLOCK_T,
    block_v: int = DEFAULT_BLOCK_V,
    interpret: bool = False,
):
    """The models' CE entry: fused Pallas kernel when enabled AND
    runnable, else the scan-based chunked path (same math, same
    ``(nll_sum, n_valid)`` contract). ``chunk_size`` parameterizes the
    fallback only; ``block_t``/``block_v`` the kernel only."""
    if fused_ce_enabled() and fused_ce_available(interpret):
        return fused_cross_entropy(
            x, w_unembed, targets,
            block_t=block_t, block_v=block_v, interpret=interpret,
        )
    return chunked_cross_entropy(x, w_unembed, targets,
                                 chunk_size=chunk_size)


def fused_cross_entropy(
    x: jnp.ndarray,
    w_unembed: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    block_t: int = DEFAULT_BLOCK_T,
    block_v: int = DEFAULT_BLOCK_V,
    interpret: bool = False,
):
    """Fused ``softmax_ce(x @ w_unembed, targets)`` as a Pallas kernel.

    Args/returns match :func:`~dlrover_tpu.ops.chunked_ce.
    chunked_cross_entropy`: ``x (..., d)``, ``w_unembed (d, v)``,
    ``targets (...)`` with ``targets < 0`` ignored; returns f32
    ``(nll_sum, n_valid)``. Raises if Pallas cannot run here — callers
    wanting automatic fallback use :func:`cross_entropy_sums`.
    """
    if x.shape[:-1] != targets.shape:
        raise ValueError(
            f"x leading dims {x.shape[:-1]} != targets shape {targets.shape}"
        )
    if x.shape[-1] != w_unembed.shape[0]:
        raise ValueError(
            f"x feature dim {x.shape[-1]} != w_unembed rows "
            f"{w_unembed.shape[0]}"
        )
    if not fused_ce_available(interpret):
        raise RuntimeError(
            "fused_cross_entropy needs Pallas on TPU (or interpret=True); "
            "use cross_entropy_sums for automatic chunked fallback"
        )
    return _fused_ce(int(block_t), int(block_v), bool(interpret),
                     x, w_unembed, targets)


# ---------------------------------------------------------------------------
# tiling / padding helpers
# ---------------------------------------------------------------------------


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _tile_geometry(n: int, v: int, block_t: int, block_v: int):
    """Clip the requested tiles to the (8, 128)-aligned problem size and
    return ``(bt, bv, n_pad, v_pad)`` with the padded array dims exact
    tile multiples — every BlockSpec start is then in range."""
    bt = max(8, min(block_t, _round_up(n, 8)))
    bv = max(128, min(block_v, _round_up(v, 128)))
    return bt, bv, _round_up(n, bt), _round_up(v, bv)


def _pad_operands(x2, w, tgt1, n_pad: int, v_pad: int):
    """Zero-pad tokens and vocab up to tile multiples. Padded token rows
    carry the -1 target sentinel (excluded from n_valid AND given a zero
    backward row_scale); padded vocab columns are masked to -inf inside
    the kernels (exp -> 0), so neither contributes anywhere."""
    n, d = x2.shape
    v = w.shape[1]
    if n_pad != n:
        x2 = jnp.pad(x2, ((0, n_pad - n), (0, 0)))
        tgt1 = jnp.pad(tgt1, (0, n_pad - n), constant_values=-1)
    if v_pad != v:
        w = jnp.pad(w, ((0, 0), (0, v_pad - v)))
    return x2, w, tgt1


def _lanes(a):
    """(n,) -> (n, _LANES) broadcast copy (TPU minor-dim tiling)."""
    return jnp.broadcast_to(a[:, None], (a.shape[0], _LANES))


def _tile_logits(x_ref, w_ref, vi, bt: int, bv: int, v: int):
    """One tile's logits ``(bt, bv)`` f32: MXU matmul + padded-column
    -inf masking (same contract as chunked_ce._chunk_logits)."""
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    logits = lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    col = vi * bv + lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    return jnp.where(col < v, logits, _NEG_INF), col


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fused_ce_fwd_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, gold_ref, m_ref, s_ref, g_ref,
    *, block_t: int, block_v: int, n_vblocks: int, v: int
):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        s_ref[:] = jnp.zeros_like(s_ref)
        g_ref[:] = jnp.zeros_like(g_ref)

    logits, col = _tile_logits(x_ref, w_ref, vi, block_t, block_v, v)
    # online softmax: rescale the running sumexp to the new max. Fully
    # padded tiles contribute exp(-inf)=0; at least one tile holds real
    # columns, so the final s is positive for every row.
    m_prev = m_ref[:, 0]
    m_cur = jnp.maximum(m_prev, jnp.max(logits, axis=1))
    s_ref[:, 0] = s_ref[:, 0] * jnp.exp(m_prev - m_cur) + jnp.sum(
        jnp.exp(logits - m_cur[:, None]), axis=1
    )
    m_ref[:, 0] = m_cur
    # the target column lands in exactly one tile: one-hot reduction
    # instead of a gather (pad sentinel -1 matches no column)
    tgt = tgt_ref[:, 0]
    g_ref[:, 0] = g_ref[:, 0] + jnp.sum(
        jnp.where(col == tgt[:, None], logits, 0.0), axis=1
    )

    @pl.when(vi == n_vblocks - 1)
    def _finalize():
        s = s_ref[:, 0]
        logz = m_ref[:, 0] + jnp.log(jnp.where(s == 0.0, 1.0, s))
        logz_ref[...] = jnp.broadcast_to(logz[:, None], logz_ref.shape)
        gold_ref[...] = jnp.broadcast_to(
            g_ref[:, 0][:, None], gold_ref.shape
        )


def _fused_ce_fwd_pallas(x2, w, tgt1, v, bt, bv, interpret):
    """Padded-operand forward: returns (logz (n_pad,), gold (n_pad,)).
    ``v`` is the REAL vocab width — padded columns beyond it are masked
    to -inf inside the kernel."""
    n_pad, d = x2.shape
    v_pad = w.shape[1]
    n_t, n_v = n_pad // bt, v_pad // bv
    kernel = functools.partial(
        _fused_ce_fwd_kernel,
        block_t=bt, block_v=bv, n_vblocks=n_v, v=v,
    )
    logz, gold = pl.pallas_call(
        kernel,
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((d, bv), lambda ti, vi: (0, vi)),
            pl.BlockSpec((bt, _LANES), lambda ti, vi: (ti, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, _LANES), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, _LANES), lambda ti, vi: (ti, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bt, 128), jnp.float32),
            pltpu.VMEM((bt, 128), jnp.float32),
            pltpu.VMEM((bt, 128), jnp.float32),
        ],
        interpret=interpret,
    )(x2, w, _lanes(tgt1))
    return logz[:, 0], gold[:, 0]


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
#
# d(nll_sum)/d(logits_tile) = (softmax_tile - onehot_tile) * row_scale,
# recomputed tile by tile from the O(tokens) logz residual:
#   p = exp(logits - logz) ; q = (p - onehot) * row_scale
#   dx = q @ w^T   (token-major: vocab tiles accumulate per token block)
#   dw = x^T @ q   (vocab-major: token blocks accumulate per vocab tile,
#                   each dw tile written exactly once — disjoint, like
#                   the chunked path's dynamic_update_slice chunks)


def _bwd_q_tile(x_ref, w_ref, tgt_ref, logz_ref, scale_ref, vi,
                bt: int, bv: int, v: int):
    logits, col = _tile_logits(x_ref, w_ref, vi, bt, bv, v)
    logz = logz_ref[:, 0]
    p = jnp.exp(logits - logz[:, None])  # padded cols: exp(-inf)=0
    tgt = tgt_ref[:, 0]
    onehot = (col == tgt[:, None]).astype(jnp.float32)
    return (p - onehot) * scale_ref[:, 0][:, None]


def _fused_ce_dx_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, scale_ref, dx_ref, acc_ref,
    *, block_t: int, block_v: int, n_vblocks: int, v: int
):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = _bwd_q_tile(x_ref, w_ref, tgt_ref, logz_ref, scale_ref, vi,
                    block_t, block_v, v)
    acc_ref[:] = acc_ref[:] + lax.dot_general(
        q, w_ref[...].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(vi == n_vblocks - 1)
    def _finalize():
        dx_ref[...] = acc_ref[:].astype(dx_ref.dtype)


def _fused_ce_dw_kernel(
    x_ref, w_ref, tgt_ref, logz_ref, scale_ref, dw_ref, acc_ref,
    *, block_t: int, block_v: int, n_tblocks: int, v: int
):
    # vocab-major grid: program_id(0) is the vocab tile, (1) sweeps token
    # blocks so the tile's dw accumulates in VMEM and is written once
    vi = pl.program_id(0)
    ti = pl.program_id(1)

    @pl.when(ti == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = _bwd_q_tile(x_ref, w_ref, tgt_ref, logz_ref, scale_ref, vi,
                    block_t, block_v, v)
    acc_ref[:] = acc_ref[:] + lax.dot_general(
        x_ref[...].astype(jnp.float32), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ti == n_tblocks - 1)
    def _finalize():
        dw_ref[...] = acc_ref[:].astype(dw_ref.dtype)


def _fused_ce_bwd_pallas(x2, w, tgt1, logz, row_scale, v, bt, bv,
                         interpret):
    """Padded-operand backward: returns (dx (n_pad, d), dw (d, v_pad)).
    ``v`` is the REAL vocab width (padded-column mask, as in fwd)."""
    n_pad, d = x2.shape
    v_pad = w.shape[1]
    n_t, n_v = n_pad // bt, v_pad // bv
    tgt_l, logz_l, scale_l = _lanes(tgt1), _lanes(logz), _lanes(row_scale)
    lane_spec = pl.BlockSpec((bt, _LANES), lambda ti, vi: (ti, 0))
    dx = pl.pallas_call(
        functools.partial(
            _fused_ce_dx_kernel,
            block_t=bt, block_v=bv, n_vblocks=n_v, v=v,
        ),
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((d, bv), lambda ti, vi: (0, vi)),
            lane_spec, lane_spec, lane_spec,
        ],
        out_specs=pl.BlockSpec((bt, d), lambda ti, vi: (ti, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bt, d), jnp.float32)],
        interpret=interpret,
    )(x2, w, tgt_l, logz_l, scale_l)

    lane_spec_vm = pl.BlockSpec((bt, _LANES), lambda vi, ti: (ti, 0))
    dw = pl.pallas_call(
        functools.partial(
            _fused_ce_dw_kernel,
            block_t=bt, block_v=bv, n_tblocks=n_t, v=v,
        ),
        grid=(n_v, n_t),
        in_specs=[
            pl.BlockSpec((bt, d), lambda vi, ti: (ti, 0)),
            pl.BlockSpec((d, bv), lambda vi, ti: (0, vi)),
            lane_spec_vm, lane_spec_vm, lane_spec_vm,
        ],
        out_specs=pl.BlockSpec((d, bv), lambda vi, ti: (0, vi)),
        out_shape=jax.ShapeDtypeStruct((d, v_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((d, bv), jnp.float32)],
        interpret=interpret,
    )(x2, w, tgt_l, logz_l, scale_l)
    return dx, dw


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# custom_vjp surface
# ---------------------------------------------------------------------------


def _flatten(x, tgt):
    d = x.shape[-1]
    n = int(np.prod(tgt.shape)) if tgt.shape else 1
    return x.reshape(n, d), tgt.reshape(n)


def _fused_ce_run_fwd(block_t, block_v, interpret, x, w, tgt):
    """Shared fwd: returns (nll_sum, n_valid, logz (n,) f32 residual)."""
    # named scope = the kernel ledger's attribution key
    # (profiler/kernel_ledger.py classifies HLO sites by op_name path)
    with jax.named_scope("fused_ce_fwd"):
        x2, tgt1 = _flatten(x, tgt)
        n, v = x2.shape[0], w.shape[1]
        bt, bv, n_pad, v_pad = _tile_geometry(n, v, block_t, block_v)
        x2p, wp, tgt1p = _pad_operands(x2, w, tgt1, n_pad, v_pad)
        logz, gold = _fused_ce_fwd_pallas(
            x2p, wp, tgt1p, v, bt, bv, interpret
        )
        logz, gold = logz[:n], gold[:n]
        vf = (tgt1 >= 0).astype(jnp.float32)
        nll_sum = jnp.sum((logz - gold) * vf)
        n_valid = jnp.sum(vf)
    return nll_sum, n_valid, logz


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fused_ce(block_t: int, block_v: int, interpret: bool, x, w, tgt):
    nll_sum, n_valid, _ = _fused_ce_run_fwd(
        block_t, block_v, interpret, x, w, tgt
    )
    return nll_sum, n_valid


def _fused_ce_fwd(block_t, block_v, interpret, x, w, tgt):
    nll_sum, n_valid, logz = _fused_ce_run_fwd(
        block_t, block_v, interpret, x, w, tgt
    )
    return (nll_sum, n_valid), (x, w, tgt, logz)


def _fused_ce_bwd(block_t, block_v, interpret, res, cot):
    """n_valid carries no float dependence on (x, w); its cotangent is
    dropped — same contract as the chunked path."""
    x, w, tgt, logz = res
    g_nll, _g_nv = cot
    with jax.named_scope("fused_ce_bwd"):
        x2, tgt1 = _flatten(x, tgt)
        n, v = x2.shape[0], w.shape[1]
        bt, bv, n_pad, v_pad = _tile_geometry(n, v, block_t, block_v)
        x2p, wp, tgt1p = _pad_operands(x2, w, tgt1, n_pad, v_pad)
        vf = (tgt1p >= 0).astype(jnp.float32)
        row_scale = vf * g_nll.astype(jnp.float32)
        logz_p = jnp.pad(logz, (0, n_pad - n)) if n_pad != n else logz
        dx, dw = _fused_ce_bwd_pallas(
            x2p, wp, tgt1p, logz_p, row_scale, v, bt, bv, interpret
        )
        dx = dx[:n].reshape(x.shape).astype(x.dtype)
        dw = dw[:, :v].astype(w.dtype)
    dtgt = np.zeros(tgt.shape, jax.dtypes.float0)
    return dx, dw, dtgt


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)
