"""Ulysses-style (all-to-all) sequence parallelism.

The second first-class long-context strategy next to ring attention
(``ops/ring_attention.py``). Green-field relative to the reference,
which delegates sequence parallelism to the frameworks it launches
(SURVEY.md §5 "long-context — absent"); the pattern is the
DeepSpeed-Ulysses one (arXiv:2309.14509), re-done with XLA collectives.

Mechanics over an ``sp`` mesh axis of size P:

    in : (b, s/P, h,   d)  sequence-sharded (how the rest of the model
                           computes: norms/mlp are pointwise in s)
    a2a: (b, s,   h/P, d)  head-sharded — each rank now owns the FULL
                           sequence for h/P heads
    attention (any single-device kernel — the Pallas flash kernel here)
    a2a: (b, s/P, h,   d)  back to sequence-sharded

Communication is two all-to-alls moving activations once each
(O(b·s·h·d / P) per rank), versus ring's P-1 ppermute hops of K/V —
cheaper when heads divide P well and seq is only moderately long; ring
wins when s/P is large enough to hide K/V hops behind per-chunk
compute. Both ride ICI; pick per workload (``attn_impl`` in the model
configs).

Causality is preserved exactly: heads are independent in attention, so
re-partitioning heads while un-sharding the sequence computes the same
math as single-device causal attention per head.

GQA: P must divide the K/V head count too. With fewer KV heads than P,
when KV heads don't divide sp, each kv head is replicated by
sp/gcd(hkv, sp) (the DeepSpeed-Ulysses GQA treatment) so the scatter
divides — exact, at the cost of a proportionally larger kv all-to-all;
shapes where even replication can't produce a valid GQA grouping
(h % lcm(hkv, sp) != 0) raise with a pointer to ring attention.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from dlrover_tpu.ops.attention import flash_attention


def _a2a_scatter_heads(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """(b, s/P, h, d) -> (b, s, h/P, d): scatter heads, gather seq."""
    return lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def _a2a_gather_heads(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """(b, s, h/P, d) -> (b, s/P, h, d): gather heads, scatter seq."""
    return lax.all_to_all(
        x, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jnp.ndarray,  # (b, s_local, h, d)
    k: jnp.ndarray,  # (b, s_local, hkv, d)
    v: jnp.ndarray,  # (b, s_local, hkv, d)
    axis_name: str,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    """Call under ``shard_map`` with q/k/v sequence-sharded over
    ``axis_name``; returns the output in the same layout. Differentiable
    end to end (all_to_all is linear; the flash kernel carries its own
    VJP)."""
    from dlrover_tpu.ops.shard_map_compat import axis_size

    sp = axis_size(axis_name)
    if sp == 1:
        return flash_attention(q, k, v, causal=causal,
                               block_q=block_q, block_k=block_k)
    h, hkv = q.shape[2], k.shape[2]
    if h % sp:
        raise ValueError(
            f"ulysses needs query heads divisible by sp: h={h} sp={sp}"
            " (use ring attention otherwise)"
        )
    if hkv % sp:
        # GQA with fewer (or indivisible) KV heads than sp: replicate
        # each kv head so the head-scatter divides (DeepSpeed-Ulysses
        # GQA treatment). jnp.repeat keeps the q->kv group mapping of
        # the flash kernel intact ([k0,k0,k1,k1,...] with the ratio
        # halved per replica), and backward sums replica grads — exact.
        # Cost: kv all-to-all volume grows by the replication factor;
        # kv is the small side, and this unlocks ulysses for e.g.
        # 8-kv-head models on sp=16.
        import math

        rep = sp // math.gcd(hkv, sp)
        if h % (hkv * rep):
            raise ValueError(
                f"ulysses GQA replication needs h % lcm(hkv, sp) == 0: "
                f"h={h} hkv={hkv} sp={sp} (lcm={hkv * rep}); use ring "
                "attention for this shape"
            )
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        hkv *= rep
    # NB: comm attribution for the all-to-alls is recorded at the MODEL
    # layer (models/llama.py), which knows the per-step multiplicity
    # (n_layers x microbatches); this body traces once per layer scan.
    qg = _a2a_scatter_heads(q, axis_name)
    kg = _a2a_scatter_heads(k, axis_name)
    vg = _a2a_scatter_heads(v, axis_name)
    out = flash_attention(qg, kg, vg, causal=causal,
                          block_q=block_q, block_k=block_k)
    return _a2a_gather_heads(out, axis_name)
