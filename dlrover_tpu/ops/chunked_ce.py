"""Chunked fused cross-entropy: unembed matmul + softmax-CE without the
[B, T, V] logits materialization.

The dense loss path computes full f32 logits ``x @ w_unembed`` of shape
``[B, T, V]`` before logsumexp — for the bench flagship (1.2B, seq 2k,
V=32768) that is ~0.5 GB of f32 activations (plus the bwd residuals) on a
16 GB chip, capping batch size and flash-attention tile choices. This op
fuses the lm-head matmul into the loss and iterates VOCAB chunks under
``lax.scan``:

- per-chunk logits ``[tokens, chunk]`` in compute-dtype operands with f32
  MXU accumulation (``preferred_element_type``);
- a running streaming logsumexp carry ``(max, sumexp)`` — the standard
  online-softmax recurrence, so no chunk's result depends on seeing the
  whole row;
- a target-logit gather per chunk (the target's column lands in exactly
  one chunk).

Peak activation memory drops from ``O(B*T*V)`` to ``O(B*T*chunk)`` in both
fwd and bwd: the custom VJP recomputes each chunk's logits in the backward
(one extra unembed-matmul pass, the same trade rematerialization makes for
the decoder layers — and like remat, the recompute is NOT credited in the
bench's model-FLOPs accounting) and writes the ``dW`` chunks disjointly,
so no ``[tokens, V]`` intermediate ever exists in either direction.
Megatron-LM's fused vocab-parallel CE is the reference design.

Leading dims are never reshaped away — the op broadcasts over them — so
batch/sequence shardings (dp/fsdp/sp) pass straight through under SPMD
and the op composes inside shard_map manual regions (the pp head path).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.common import flags

#: Default vocab-chunk width: 16 MXU lanes of 128 — wide enough that the
#: per-chunk [tokens, chunk] matmul stays MXU-bound, narrow enough that
#: the largest live loss activation is tokens*2048*4 bytes, not tokens*V*4.
DEFAULT_CHUNK_SIZE = 2048


def chunked_ce_enabled() -> bool:
    """Env kill-switch (bisection aid): ``DLROVER_TPU_CHUNKED_CE=0``
    restores the dense [B, T, V] logits path everywhere the models route
    through this op. Read at trace time — set it before the first loss
    call / trainer step of the process (the jitted step caches the trace).
    """
    return flags.CHUNKED_CE.get()


def chunked_cross_entropy(
    x: jnp.ndarray,
    w_unembed: jnp.ndarray,
    targets: jnp.ndarray,
    *,
    chunk_size: int = DEFAULT_CHUNK_SIZE,
):
    """Fused ``softmax_ce(x @ w_unembed, targets)`` in vocab chunks.

    Args:
      x: ``(..., d)`` hidden states (post final-norm, pre-unembed).
      w_unembed: ``(d, v)`` unembedding / lm-head / classifier weights.
      targets: ``(...)`` int class ids; ``targets < 0`` are ignored
        (the repo-wide pad sentinel).
      chunk_size: vocab columns per scan step (clipped to ``v``); peak
        loss activation is ``prod(targets.shape) * chunk_size`` f32.

    Returns:
      ``(nll_sum, n_valid)`` — the f32 sum of per-token negative
      log-likelihoods over valid targets and the f32 count of valid
      targets (the caller divides; the two-number form is what psum-based
      sharded losses need).
    """
    if x.shape[:-1] != targets.shape:
        raise ValueError(
            f"x leading dims {x.shape[:-1]} != targets shape {targets.shape}"
        )
    if x.shape[-1] != w_unembed.shape[0]:
        raise ValueError(
            f"x feature dim {x.shape[-1]} != w_unembed rows "
            f"{w_unembed.shape[0]}"
        )
    v = w_unembed.shape[1]
    chunk = max(1, min(int(chunk_size), v))
    return _chunked_ce(chunk, x, w_unembed, targets)


# ---------------------------------------------------------------------------
# implementation
# ---------------------------------------------------------------------------


def _chunk_starts(v: int, chunk: int):
    n_chunks = -(-v // chunk)
    return n_chunks, jnp.arange(n_chunks, dtype=jnp.int32) * chunk


def _pad_vocab(w, n_chunks: int, chunk: int):
    """Zero-pad the vocab axis up to a chunk multiple so every
    dynamic_slice start is in range (a clamped start would silently
    overlap the previous chunk and double-count its columns)."""
    v_pad = n_chunks * chunk
    if v_pad != w.shape[1]:
        w = jnp.pad(w, ((0, 0), (0, v_pad - w.shape[1])))
    return w


def _chunk_logits(x, w_p, start, chunk: int, v: int):
    """One chunk's logits ``(..., chunk)``: compute-dtype operands, f32
    accumulation (same contract as the dense unembed); padded tail
    columns forced to -inf so they vanish from the lse (exp -> 0)."""
    w_c = lax.dynamic_slice_in_dim(w_p, start, chunk, axis=1)
    logits = lax.dot_general(
        x, w_c.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    col = start + jnp.arange(chunk, dtype=jnp.int32)
    return jnp.where(col < v, logits, -jnp.inf), w_c


def _ce_forward(chunk: int, x, w, tgt):
    """Streaming-lse forward; returns (nll_sum, n_valid, logz) with logz
    ``(...)`` kept as the bwd residual (O(tokens), not O(tokens*v))."""
    v = w.shape[1]
    n_chunks, starts = _chunk_starts(v, chunk)
    w_p = _pad_vocab(w, n_chunks, chunk)
    valid = tgt >= 0
    vf = valid.astype(jnp.float32)
    tgt_c = jnp.where(valid, tgt, 0)
    lead = tgt.shape
    f32 = jnp.float32

    def body(carry, start):
        m, s, gold = carry
        logits, _ = _chunk_logits(x, w_p, start, chunk, v)
        # online softmax: rescale the running sumexp to the new max.
        # every chunk holds >= 1 real column (n_chunks = ceil(v/chunk)),
        # so m_new is finite from the first step on and the -inf initial
        # max contributes exp(-inf) = 0, never a nan.
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[..., None]), axis=-1
        )
        # the target column lands in exactly one chunk: gather it there
        local = tgt_c - start
        in_chunk = (local >= 0) & (local < chunk)
        g = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk - 1)[..., None], axis=-1
        )[..., 0]
        gold = jnp.where(in_chunk, g, gold)
        return (m_new, s, gold), None

    init = (
        jnp.full(lead, -jnp.inf, f32),
        jnp.zeros(lead, f32),
        jnp.zeros(lead, f32),
    )
    (m, s, gold), _ = lax.scan(body, init, starts)
    logz = m + jnp.log(s)
    nll_sum = jnp.sum((logz - gold) * vf)
    n_valid = jnp.sum(vf)
    return nll_sum, n_valid, logz


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _chunked_ce(chunk: int, x, w, tgt):
    # named scope = the kernel ledger's attribution key
    # (profiler/kernel_ledger.py classifies HLO sites by op_name path)
    with jax.named_scope("chunked_ce_fwd"):
        nll_sum, n_valid, _ = _ce_forward(chunk, x, w, tgt)
    return nll_sum, n_valid


def _chunked_ce_fwd(chunk: int, x, w, tgt):
    with jax.named_scope("chunked_ce_fwd"):
        nll_sum, n_valid, logz = _ce_forward(chunk, x, w, tgt)
    return (nll_sum, n_valid), (x, w, tgt, logz)


def _chunked_ce_bwd(chunk: int, res, cot):
    """d(nll_sum)/d(logits_c) = (softmax_c - onehot_c) * valid, chunk by
    chunk: recompute the chunk's logits from the saved (x, logz), push
    one chunk of dx and one DISJOINT chunk of dw — dw slots are written
    exactly once, so the accumulator can live in w's dtype with no
    accumulation-order error. n_valid carries no float dependence on
    (x, w); its cotangent is dropped."""
    x, w, tgt, logz = res
    g_nll, _g_nv = cot
    with jax.named_scope("chunked_ce_bwd"):
        return _chunked_ce_bwd_impl(chunk, x, w, tgt, logz, g_nll)


def _chunked_ce_bwd_impl(chunk: int, x, w, tgt, logz, g_nll):
    v = w.shape[1]
    n_chunks, starts = _chunk_starts(v, chunk)
    w_p = _pad_vocab(w, n_chunks, chunk)
    valid = tgt >= 0
    vf = valid.astype(jnp.float32)
    tgt_c = jnp.where(valid, tgt, 0)
    nd = x.ndim
    lead_axes = tuple(range(nd - 1))
    f32 = jnp.float32
    row_scale = (vf * g_nll.astype(f32))[..., None]

    def body(carry, start):
        dx, dw = carry
        logits, w_c = _chunk_logits(x, w_p, start, chunk, v)
        p = jnp.exp(logits - logz[..., None])  # padded cols: exp(-inf)=0
        local = tgt_c - start
        in_chunk = (local >= 0) & (local < chunk)
        # one_hot maps the out-of-range sentinel (-1) to an all-zero row
        onehot = jax.nn.one_hot(
            jnp.where(in_chunk, local, -1), chunk, dtype=f32
        )
        q = ((p - onehot) * row_scale).astype(x.dtype)
        dx = dx + lax.dot_general(
            q, w_c.astype(x.dtype),
            (((nd - 1,), (1,)), ((), ())),
            preferred_element_type=f32,
        )
        dw_c = lax.dot_general(
            x, q,
            ((lead_axes, lead_axes), ((), ())),
            preferred_element_type=f32,
        )
        dw = lax.dynamic_update_slice_in_dim(
            dw, dw_c.astype(dw.dtype), start, axis=1
        )
        return (dx, dw), None

    init = (
        jnp.zeros(x.shape, f32),  # dx sums over chunks: f32 accumulator
        jnp.zeros((w.shape[0], n_chunks * chunk), w.dtype),
    )
    (dx, dw), _ = lax.scan(body, init, starts)
    dx = dx.astype(x.dtype)
    dw = dw[:, :v]
    # integer targets take a symbolic-zero cotangent
    dtgt = np.zeros(tgt.shape, jax.dtypes.float0)
    return dx, dw, dtgt


_chunked_ce.defvjp(_chunked_ce_fwd, _chunked_ce_bwd)
