"""Embedding lookup that partitions cleanly under SPMD.

A plain ``embed[tokens]`` gather over a tp-sharded vocab axis forces
XLA's SPMD partitioner into "involuntary full rematerialization": it
all-gathers the table, gathers, replicates the result, then re-partitions
to the activation sharding — the worst possible data movement for the
hottest lookup in the model.

The TPU-idiomatic form is a one-hot contraction: ``one_hot(tokens) @
embed``. A matmul with the vocab axis as the contraction dim partitions
like every other matmul (partial products + psum over tp), rides the MXU,
and its transpose (the embedding gradient) becomes a matmul too instead
of a scatter-add. XLA fuses the iota/compare one-hot generation into the
matmul operand read, so the (b, s, vocab) operand is never materialized
in HBM.

Green-field relative to the reference (it owns no model code,
SURVEY.md §2.8).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.parallel.mesh import BATCH_AXES, SP, TP


def embed_lookup(
    embed: jnp.ndarray,   # (vocab, dim), typically P(TP, FSDP)
    tokens: jnp.ndarray,  # (b, s) int32
    mesh: Optional[Mesh] = None,
    dtype=jnp.bfloat16,
) -> jnp.ndarray:
    """Token embedding lookup → (b, s, dim) activations sharded
    P(batch, sp, None). Uses the one-hot matmul form under a mesh; a
    plain gather otherwise (single-device: gather is cheaper)."""
    table = embed.astype(dtype)
    if mesh is None:
        return table[tokens]
    one_hot = jax.nn.one_hot(tokens, embed.shape[0], dtype=dtype)
    one_hot = lax.with_sharding_constraint(
        one_hot, NamedSharding(mesh, P(BATCH_AXES, SP, TP))
    )
    x = jnp.einsum("bsv,vd->bsd", one_hot, table)
    return lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(BATCH_AXES, SP, None))
    )
