"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context capability the reference lacks entirely (SURVEY.md §2.8 — it
delegates context parallelism to Megatron/DeepSpeed). TPU-native design:

- the sequence dim is sharded over the mesh ``sp`` axis;
- each device holds one q/k/v chunk; kv chunks rotate around the ring with
  `lax.ppermute` (single-hop ICI neighbor exchange — the torus makes this
  free-ish);
- every ring step runs the **Pallas flash kernel on the local chunk pair**
  (`flash_attention_with_lse`) — O(chunk) memory, GQA resolved in the
  kernel's index_map (never materialized), the chunk×chunk logit matrix
  never exists;
- per-chunk results merge by the standard logsumexp combine
  ``out = Σ_i exp(lse_i - lse_total) · out_i`` — exact, and exactly
  differentiable because the kernel's ``lse`` output is differentiable
  (its cotangent folds into the flash backward's delta term).

Chunk-level causality: a kv chunk strictly *after* the query chunk
contributes nothing (skipped via a zero merge-weight); the *diagonal*
chunk uses the causal kernel; chunks strictly before use the full
(non-causal) kernel — `lax.cond` picks the branch per device at runtime.

Must be called inside `shard_map` with ``axis_name`` bound (see
`models/llama.py` for the wiring). Differentiable through `lax.scan` +
`ppermute`; each step is rematerialized under `jax.checkpoint` so the
backward does not keep every rotated kv copy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.ops.attention import _NEG_INF, flash_attention_with_lse


def ring_attention(
    q: jnp.ndarray,  # (b, s_local, h, d)
    k: jnp.ndarray,  # (b, s_local, hkv, d)
    v: jnp.ndarray,  # (b, s_local, hkv, d)
    axis_name: str,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
) -> jnp.ndarray:
    b, s_local, h, d = q.shape
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    # NB: comm attribution for the ring hops is recorded at the MODEL
    # layer (models/llama.py), which knows the per-step multiplicity
    # (n_layers x microbatches); this body traces once inside lax.scan,
    # so a record here could not count executions.

    def chunk_attn(kc, vc, src):
        """(out (b,s,h,d) f32, lse (b,h,s) f32) for this kv chunk."""
        def diag(_):
            o, lse = flash_attention_with_lse(
                q, kc, vc, True, block_q, block_k
            )
            return o.astype(jnp.float32), lse

        def full(_):
            o, lse = flash_attention_with_lse(
                q, kc, vc, False, block_q, block_k
            )
            return o.astype(jnp.float32), lse

        def skip(_):
            return (
                jnp.zeros((b, s_local, h, d), jnp.float32),
                jnp.full((b, h, s_local), _NEG_INF, jnp.float32),
            )

        if not causal:
            return full(None)
        # src > my: every key is in the future of every query → skip
        return lax.cond(
            src > my_idx,
            skip,
            lambda _: lax.cond(src == my_idx, diag, full, None),
            None,
        )

    def step_fn(carry, _):
        o_acc, lse_acc, kc, vc, src = carry
        o_i, lse_i = chunk_attn(kc, vc, src)
        # logsumexp merge of two normalized partial softmaxes
        lse_new = jnp.logaddexp(lse_acc, lse_i)              # (b, h, s)
        w_acc = jnp.exp(lse_acc - lse_new)
        w_i = jnp.exp(lse_i - lse_new)
        # (b,h,s) weights → (b,s,h,1) to scale (b,s,h,d) outputs
        o_acc = (
            o_acc * w_acc.transpose(0, 2, 1)[..., None]
            + o_i * w_i.transpose(0, 2, 1)[..., None]
        )
        # rotate kv to the next ring position (device i → i+1)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        src = (src - 1) % n
        return (o_acc, lse_new, kc, vc, src), None

    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    # finite "minus infinity": logaddexp(-1e30, x) == x for any real lse,
    # and the first merge weight exp(-1e30 - lse_new) underflows to 0
    lse0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    carry0 = (o0, lse0, k, v, my_idx)
    (o, lse, *_), _ = lax.scan(
        jax.checkpoint(step_fn), carry0, None, length=n
    )
    return o.astype(q.dtype)
