"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context capability the reference lacks entirely (SURVEY.md §2.8 — it
delegates context parallelism to Megatron/DeepSpeed). TPU-native design:

- the sequence dim is sharded over the mesh ``sp`` axis;
- each device holds one q/k/v chunk; kv chunks rotate around the ring with
  `lax.ppermute` (single-hop ICI neighbor exchange — the torus makes this
  free-ish) while every device accumulates online-softmax partials;
- compute and the next kv transfer overlap naturally: XLA schedules the
  ppermute DMA concurrently with the chunk matmuls.

Must be called inside `shard_map` with ``axis_name`` bound (see
`models/llama.py` for the wiring). Differentiable through `lax.scan` +
`ppermute`; the per-step chunk attention is rematerialized under
`jax.checkpoint` so the backward does not keep every rotated kv copy.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.ops.attention import _NEG_INF


def ring_attention(
    q: jnp.ndarray,  # (b, s_local, h, d)
    k: jnp.ndarray,  # (b, s_local, hkv, d)
    v: jnp.ndarray,  # (b, s_local, hkv, d)
    axis_name: str,
    causal: bool = True,
) -> jnp.ndarray:
    b, s_local, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(d)

    qf = q.astype(jnp.float32) * scale
    # einsum layout: (b, h, sq, sk) blocks
    qb = qf.transpose(0, 2, 1, 3)  # (b, h, s, d)

    def chunk_scores(kc):  # kc: (b, s, hkv, d) → (b, h, sq, sk) f32
        kb = kc.astype(jnp.float32).transpose(0, 2, 1, 3)
        if group > 1:
            kb = jnp.repeat(kb, group, axis=1)
        return jnp.einsum("bhqd,bhkd->bhqk", qb, kb)

    def step_fn(carry, _):
        m, l, acc, kc, vc, src = carry
        s = chunk_scores(kc)
        if causal:
            qpos = my_idx * s_local + jnp.arange(s_local)
            kpos = src * s_local + jnp.arange(s_local)
            mask = qpos[:, None] >= kpos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        m_cur = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_cur[..., None])
        corr = jnp.exp(m - m_cur)
        l = l * corr + jnp.sum(p, axis=-1)
        vb = vc.astype(jnp.float32).transpose(0, 2, 1, 3)
        if group > 1:
            vb = jnp.repeat(vb, group, axis=1)
        acc = acc * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        # rotate kv to the next ring position (device i → i+1)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        src = (src - 1) % n
        return (m_cur, l, acc, kc, vc, src), None

    m0 = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    carry0 = (m0, l0, acc0, k, v, my_idx)
    (m, l, acc, *_), _ = lax.scan(
        jax.checkpoint(step_fn), carry0, None, length=n
    )
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l[..., None]).transpose(0, 2, 1, 3)  # (b, s, h, d)
    return out.astype(q.dtype)
