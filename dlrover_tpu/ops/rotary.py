"""Rotary position embeddings (RoPE), llama-3 style.

Frequencies are computed once per (seq_len, head_dim) and closed over by the
jitted step — static shapes, no per-step host work. ``positions`` is passed
explicitly so sequence-parallel shards (ring attention) can rotate with
their *global* positions.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float = 500000.0) -> jnp.ndarray:
    """Inverse frequencies, shape (head_dim // 2,), float32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)


def apply_rope(
    x: jnp.ndarray,          # (..., seq, n_heads, head_dim)
    positions: jnp.ndarray,  # (..., seq) int32 global positions
    inv_freq: jnp.ndarray,   # (head_dim // 2,)
) -> jnp.ndarray:
    angles = positions[..., :, None].astype(jnp.float32) * inv_freq  # (...,s,d/2)
    cos = jnp.cos(angles)[..., :, None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
