"""Normalization ops.

Pure jnp — XLA fuses the reduction + rescale into the surrounding matmuls'
epilogues on TPU, so a Pallas kernel buys nothing here (HBM-bound elementwise
work is exactly what the XLA fuser exists for). Computation is done in
float32 regardless of input dtype for numerical parity with the usual
bfloat16 training recipe.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    y = x32 * lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)
