"""``shard_map`` across JAX versions.

The ops/models code targets current JAX, where ``shard_map`` lives at
the top level and the replication-check kwarg is ``check_vma``. Older
jaxlibs (0.4.x, this image) ship it under ``jax.experimental`` with the
kwarg named ``check_rep``. One import point so every call site stays
written in the modern idiom.

This compat layer is why the pipeline engine (``models/llama.py`` pp
executors) is written FULL-MANUAL — every mesh axis mapped, every
collective explicit (``ppermute`` stage handoffs, megatron tp psums,
ZeRO-3 fsdp gathers). Full-manual programs lower identically on every
jax this shim spans; partial-manual (``axis_names=`` subsets) depends
on the legacy best-effort ``auto=`` translation that XLA CHECK-aborts
on for exactly those programs (see ``supports_partial_manual``).
"""

from __future__ import annotations

import functools

try:  # jax >= 0.6: top-level, check_vma
    from jax import shard_map as _shard_map

    _LEGACY_KWARG = False
except ImportError:  # jax 0.4.x: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY_KWARG = True

__all__ = ["shard_map", "axis_size", "supports_partial_manual"]


def supports_partial_manual() -> bool:
    """Whether ``axis_names`` (map a subset of mesh axes, leave the
    rest to the partitioner) works natively. The legacy ``auto=``
    translation is best-effort: some programs it cannot partition
    (XLA CHECK-aborts on PartitionId) — callers whose body only uses
    the mapped axes should drop ``axis_names`` entirely on legacy jax
    and take the full-manual map instead."""
    return not _LEGACY_KWARG


def axis_size(axis_name) -> int:
    """Static size of a mapped axis, inside shard_map. Modern JAX has
    ``lax.axis_size``; on 0.4.x ``jax.core.axis_frame(name)`` returns
    the bound size as a plain int."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.core.axis_frame(axis_name)


@functools.wraps(_shard_map)
def shard_map(f=None, **kwargs):
    if _LEGACY_KWARG:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            # modern axis_names = the axes to MAP; legacy auto = the
            # complement (mesh axes left to the partitioner)
            axis_names = kwargs.pop("axis_names")
            mesh = kwargs.get("mesh")
            if axis_names is not None and mesh is not None:
                kwargs["auto"] = (
                    frozenset(mesh.axis_names) - frozenset(axis_names)
                )
    if f is None:
        return functools.partial(shard_map, **kwargs)
    return _shard_map(f, **kwargs)
