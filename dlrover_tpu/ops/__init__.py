"""TPU compute ops: norms, rotary, flash attention (Pallas), and two
sequence-parallel strategies (ring, ulysses all-to-all).

Green-field relative to the reference, which owns no kernels (SURVEY.md
§2.8) — its compute path is whatever torch framework it launches.
"""

from dlrover_tpu.ops.attention import flash_attention, mha_reference  # noqa: F401
from dlrover_tpu.ops.chunked_ce import (  # noqa: F401
    chunked_ce_enabled,
    chunked_cross_entropy,
)
from dlrover_tpu.ops.fused_ce import (  # noqa: F401
    cross_entropy_sums,
    fused_ce_available,
    fused_ce_enabled,
    fused_cross_entropy,
)
from dlrover_tpu.ops.embedding import embed_lookup  # noqa: F401
from dlrover_tpu.ops.norms import rms_norm  # noqa: F401
from dlrover_tpu.ops.ring_attention import ring_attention  # noqa: F401
from dlrover_tpu.ops.ulysses import ulysses_attention  # noqa: F401
from dlrover_tpu.ops.rotary import apply_rope, rope_frequencies  # noqa: F401
