"""Cross-host checkpoint replicas: node-loss recovery without storage.

Parity: reference ``flash_checkpoint/replica.py:45-247``
(``ShardCkptReplicaManager.backup`` allgathers each shard into a backup
rank's memory; ``FullCkptReplicaManager`` gathers on restore). The torch
version rides NCCL/gloo collectives *inside the training processes*; the
TPU-native design moves replication into the **agent-resident saver**,
off the training critical path: after a staging event the saver streams
the local shm segments to the backup peer's saver over TCP (DCN, not
ICI), and a replacement host pulls its seat's segments back before the
workers restart. No collective, no training pause, and the backup
survives the original host's death by construction.

Placement: the backup of node_rank ``r`` lives on ``(r+1) % world`` —
deterministic, so a restored host knows exactly whom to ask.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.checkpoint.shm_handler import (
    HEADER_SPACE,
    SharedMemoryHandler,
)
from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger

_CHUNK = 1 << 20
_HDR_FMT = "<Q"  # length-prefixed JSON header


def _send_msg(sock: socket.socket, header: Dict, payload: bytes = b""):
    raw = json.dumps(header).encode()
    sock.sendall(struct.pack(_HDR_FMT, len(raw)))
    sock.sendall(raw)
    if payload:
        sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(min(_CHUNK, n - len(out)))
        if not chunk:
            raise ConnectionError("peer closed mid-message")
        out.extend(chunk)
    return bytes(out)


def _recv_header(sock: socket.socket) -> Dict:
    (hlen,) = struct.unpack(_HDR_FMT, _recv_exact(sock, 8))
    if hlen > 16 << 20:
        raise ConnectionError(f"oversized header ({hlen} bytes)")
    return json.loads(_recv_exact(sock, hlen).decode())


def _recv_msg(sock: socket.socket) -> Tuple[Dict, bytes]:
    header = _recv_header(sock)
    size = int(header.get("size", 0))
    if size > MAX_PAYLOAD_BYTES:
        raise ConnectionError(f"oversized payload ({size} bytes)")
    return header, _recv_exact(sock, size)


#: refuse absurd payloads before buffering them (memory-DoS bound)
MAX_PAYLOAD_BYTES = int(flags.REPLICA_MAX_BYTES.get())


class ReplicaServer:
    """In-memory store of peers' staged checkpoints, one slot per owner
    rank (latest step wins).

    Auth: requests must carry the job's replica token (distributed through
    the master's KV store after rendezvous — see the elastic agent). Until
    a token is set, all requests are refused: the server is reachable
    cross-host by necessity, unlike the node-local unix-socket IPC."""

    def __init__(self, port: int = 0):
        self._store: Dict[int, Tuple[int, List[Dict], bytes]] = {}
        self._lock = threading.Lock()
        self._token = ""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="ckpt-replica", daemon=True
        )
        self._thread.start()

    def set_token(self, token: str):
        self._token = token

    def stop(self):
        self._stop.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def stored_steps(self) -> Dict[int, int]:
        with self._lock:
            return {rank: v[0] for rank, v in self._store.items()}

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket):
        try:
            with conn:
                header = _recv_header(conn)
                size = int(header.get("size", 0))
                if not self._token or header.get("token") != self._token:
                    # drain nothing; refuse before buffering the payload
                    _send_msg(conn, {"ok": False, "error": "unauthorized"})
                    return
                if size > MAX_PAYLOAD_BYTES:
                    _send_msg(conn, {"ok": False, "error": "too large"})
                    return
                payload = _recv_exact(conn, size)
                op = header.get("op")
                if op == "put":
                    owner = int(header["owner_rank"])
                    step = int(header["step"])
                    with self._lock:
                        have = self._store.get(owner)
                        if have is None or have[0] <= step:
                            self._store[owner] = (
                                step,
                                header["segments"],
                                payload,
                            )
                    _send_msg(conn, {"ok": True})
                elif op == "get":
                    owner = int(header["owner_rank"])
                    with self._lock:
                        have = self._store.get(owner)
                    if have is None:
                        _send_msg(conn, {"ok": False})
                    else:
                        step, segments, payload = have
                        _send_msg(
                            conn,
                            {
                                "ok": True,
                                "step": step,
                                "segments": segments,
                                "size": len(payload),
                            },
                            payload,
                        )
                elif op == "drop":
                    with self._lock:
                        self._store.pop(int(header["owner_rank"]), None)
                    _send_msg(conn, {"ok": True})
                else:
                    _send_msg(conn, {"ok": False, "error": "bad op"})
        except (ConnectionError, json.JSONDecodeError, KeyError, OSError) as e:
            logger.warning("replica request failed: %s", e)


def _rpc(addr: Tuple[str, int], header: Dict, payload: bytes = b"",
         timeout: float = 60.0) -> Tuple[Dict, bytes]:
    with socket.create_connection(addr, timeout=timeout) as sock:
        _send_msg(sock, header, payload)
        return _recv_msg(sock)


class ReplicaManager:
    """Saver-side: push local segments to the backup peer; pull ours back
    after a relaunch."""

    def __init__(self, server: Optional[ReplicaServer] = None):
        self.server = server or ReplicaServer()
        self._peers: Dict[int, Tuple[str, int]] = {}  # node_rank -> (ip, port)
        self._self_rank = 0
        self._world = 1
        self._token = ""
        self._lock = threading.Lock()
        self.last_pushed_step = -1

    @property
    def port(self) -> int:
        return self.server.port

    def set_token(self, token: str):
        self._token = token
        self.server.set_token(token)

    def update_peers(
        self, peers: Dict[int, Tuple[str, int]], self_rank: int, world: int
    ):
        with self._lock:
            self._peers = dict(peers)
            self._self_rank = self_rank
            self._world = max(1, world)

    def _backup_peer(self) -> Optional[Tuple[str, int]]:
        with self._lock:
            if self._world < 2:
                return None
            return self._peers.get((self._self_rank + 1) % self._world)

    def _restore_peer(self) -> Optional[Tuple[str, int]]:
        return self._backup_peer()  # same deterministic placement

    # -- backup -------------------------------------------------------------

    @staticmethod
    def _segment_payload(handler: SharedMemoryHandler) -> Optional[Tuple[Dict, bytes]]:
        meta = handler.read_meta()
        if meta is None:
            return None
        used = HEADER_SPACE
        for leaf in meta.leaves:
            used = max(used, leaf.offset + leaf.nbytes)
        data = bytes(handler.buf[:used])
        return (
            {
                "size": len(data),
                "step": meta.step,
                "process_id": meta.process_id,
            },
            data,
        )

    def collect_segments(
        self, handlers: List[SharedMemoryHandler]
    ) -> Optional[Tuple[int, List[Dict], bytes]]:
        """Copy staged segments out of shm (call while holding the shm
        lock; the heap copy lets the network transfer run lock-free)."""
        segments = []
        blobs = []
        step = -1
        for h in handlers:
            if not h.attach():
                continue
            seg = self._segment_payload(h)
            if seg is None:
                continue
            segments.append(seg[0])
            blobs.append(seg[1])
            step = max(step, seg[0]["step"])
        if not segments:
            return None
        return step, segments, b"".join(blobs)

    def send_backup(
        self, step: int, segments: List[Dict], payload: bytes
    ) -> bool:
        """Stream a collected snapshot to the backup peer (no locks held)."""
        peer = self._backup_peer()
        if peer is None:
            return False
        try:
            resp, _ = _rpc(
                peer,
                {
                    "op": "put",
                    "token": self._token,
                    "owner_rank": self._self_rank,
                    "step": step,
                    "segments": segments,
                    "size": len(payload),
                },
                payload,
            )
            ok = bool(resp.get("ok"))
        except OSError as e:
            logger.warning("replica push to %s failed: %s", peer, e)
            return False
        if ok:
            self.last_pushed_step = max(self.last_pushed_step, step)
            logger.info(
                "replicated step %s (%.1f MB) to backup peer %s",
                step,
                len(payload) / 1e6,
                peer,
            )
        return ok

    def push_backup(self, handlers: List[SharedMemoryHandler]) -> bool:
        """collect + send in one call (tests / callers without a lock)."""
        snapshot = self.collect_segments(handlers)
        if snapshot is None:
            return False
        return self.send_backup(*snapshot)

    # -- restore ------------------------------------------------------------

    def fetch_backup_into_shm(self, target_names: List[str]) -> int:
        """Pull our seat's segments from the backup peer and materialize
        them as local shm under THIS node's names.

        ``target_names`` are the shm names the local engine/persister will
        look for (one per local process, in local-rank order). The pushed
        segments carry the ORIGINAL host's process ids — a replacement
        host has a new node_id and possibly new process ids, so segments
        are mapped onto targets in process-id order rather than trusting
        the dead host's names. Returns the restored step, or -1."""
        peer = self._restore_peer()
        if peer is None or not target_names:
            return -1
        try:
            resp, payload = _rpc(
                peer,
                {
                    "op": "get",
                    "token": self._token,
                    "owner_rank": self._self_rank,
                },
            )
        except OSError as e:
            logger.warning("replica fetch from %s failed: %s", peer, e)
            return -1
        if not resp.get("ok"):
            return -1
        segments = resp["segments"]
        if len(segments) != len(target_names):
            logger.warning(
                "backup has %s segments but this node runs %s processes; "
                "skipping replica restore",
                len(segments),
                len(target_names),
            )
            return -1
        # stable mapping: original process order -> local process order
        order = sorted(
            range(len(segments)), key=lambda i: segments[i]["process_id"]
        )
        offsets = []
        off = 0
        for seg in segments:
            offsets.append(off)
            off += seg["size"]
        for target, i in zip(target_names, order):
            seg = segments[i]
            data = payload[offsets[i] : offsets[i] + seg["size"]]
            handler = SharedMemoryHandler(target, create=True)
            handler.restore_segment(data)
            handler.close()
        logger.info(
            "restored step %s staged state from backup peer %s",
            resp["step"],
            peer,
        )
        return int(resp["step"])
