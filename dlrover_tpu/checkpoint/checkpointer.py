"""User-facing flash-checkpoint facade.

Parity: reference ``Checkpointer`` (``flash_checkpoint/checkpointer.py:18-65``)
with the DDP/FSDP/Megatron engine split collapsed: the JAX engine is
sharding-aware by construction (it stages addressable shards with global
indices), so one facade covers replicated (DP), FSDP-sharded, and TP/PP
states alike.

Usage::

    ckpt = Checkpointer("/nfs/job/ckpt")
    ckpt.save(step, state)                      # memory snapshot (~ms-s)
    ckpt.save(step, state, StorageType.DISK)    # + async persist
    restored = ckpt.load(target=state)          # shm, else storage
"""

from __future__ import annotations

import enum
from typing import Any, Optional, Tuple

from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.common.log import logger


class StorageType(enum.Enum):
    MEMORY = "memory"
    DISK = "disk"


class Checkpointer:
    def __init__(
        self,
        ckpt_dir: str,
        storage=None,
        master_client: Optional[object] = None,
        save_storage_interval: int = 0,
        async_staging: Optional[bool] = None,
    ):
        """``save_storage_interval > 0`` auto-upgrades every Nth memory save
        to a disk persist (so callers can save(…, MEMORY) every step and
        still get periodic durability)."""
        if master_client is None:
            try:
                from dlrover_tpu.train import get_context

                ctx = get_context()
                master_client = ctx.client if ctx else None
            except Exception:
                master_client = None
        self._engine = CheckpointEngine(
            ckpt_dir,
            storage=storage,
            master_client=master_client,
            async_staging=async_staging,
        )
        self._save_storage_interval = max(0, save_storage_interval)
        self.last_blocking_s = 0.0

    def save(
        self,
        step: int,
        state: Any,
        storage_type: StorageType = StorageType.MEMORY,
    ) -> float:
        """Returns the blocking seconds (the training pause)."""
        if (
            storage_type == StorageType.MEMORY
            and self._save_storage_interval > 0
            and step % self._save_storage_interval == 0
        ):
            storage_type = StorageType.DISK
        if storage_type == StorageType.DISK:
            blocking = self._engine.save_to_storage(step, state)
        else:
            blocking = self._engine.save_to_memory(step, state)
        self.last_blocking_s = blocking
        logger.info(
            "flash ckpt save step=%s type=%s blocking=%.3fs",
            step,
            storage_type.value,
            blocking,
        )
        return blocking

    def load(self, target: Any = None) -> Optional[Tuple[int, Any]]:
        """(step, state) from shm if fresh, else committed storage, else an
        Orbax checkpoint in the same directory (migration path from vanilla
        Orbax jobs); None if nothing exists."""
        result = self._engine.load(target)
        if result is not None:
            return result
        try:
            from dlrover_tpu.checkpoint.orbax_interop import (
                OrbaxCheckpointer,
                orbax_available,
            )

            if orbax_available():
                ckpt = OrbaxCheckpointer(self._engine.ckpt_dir)
                restored = ckpt.restore(target)
                if restored is not None:
                    logger.info(
                        "restored step %s from orbax checkpoint", restored[0]
                    )
                    return restored
        except Exception:
            logger.exception("orbax fallback restore failed")
        return None

    @property
    def last_restore_stats(self) -> dict:
        """How the last targeted restore placed its leaves — including
        ``tier`` (shm | disk | object) for tiered restores. Feed it to
        ``report_resize_breakdown(restore_tier=...)`` /
        ``trainer.note_restore_tier`` for goodput tier attribution."""
        return self._engine.last_restore_stats

    def wait_staging(self, timeout: Optional[float] = None):
        """Join any in-flight background stage (and, in bare runs without
        an agent saver, its inline persist); re-raises a staging failure."""
        self._engine.wait_staging(timeout)

    def committed_step(self) -> int:
        return self._engine.committed_step()

    def close(self, unlink_shm: bool = False):
        self._engine.close(unlink_shm=unlink_shm)
