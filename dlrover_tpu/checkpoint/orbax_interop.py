"""Orbax interop: persist/restore flash checkpoints in Orbax's format.

Parity intent (SURVEY.md §7): the reference exposes framework-native
checkpoint formats (Megatron/DeepSpeed/HF trackers) next to its own shm
staging; the JAX ecosystem's native format is Orbax. This module lets a
user (a) keep the flash path (shm staging + async persist) while ALSO
emitting Orbax-readable checkpoints, and (b) restore from checkpoints
written by vanilla Orbax jobs.

Multi-host: ``OrbaxCheckpointer`` delegates to Orbax's own collective
logic, which requires ``jax.distributed`` to be initialized — exactly what
``dlrover_tpu.train.bootstrap`` does.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

from dlrover_tpu.common.log import logger

_STEP_DIR_RE = re.compile(r"^orbax-(\d+)$")


def orbax_available() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


class OrbaxCheckpointer:
    """Thin step-dir manager over ``orbax.checkpoint.PyTreeCheckpointer``.

    Layout: ``<dir>/orbax-<step>/`` per step, readable by any Orbax
    tooling; ``latest_step`` scans the directory (no tracker file, matching
    Orbax conventions rather than ours).
    """

    def __init__(self, ckpt_dir: str):
        import orbax.checkpoint as ocp

        self.ckpt_dir = ckpt_dir
        self._ckptr = ocp.PyTreeCheckpointer()

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"orbax-{step}")

    def save(self, step: int, state: Any, force: bool = True) -> str:
        path = self._step_dir(step)
        self._ckptr.save(path, state, force=force)
        logger.info("orbax checkpoint saved: %s", path)
        return path

    def latest_step(self) -> int:
        try:
            entries = os.listdir(self.ckpt_dir)
        except FileNotFoundError:
            return -1
        steps = [
            int(m.group(1))
            for m in (_STEP_DIR_RE.match(e) for e in entries)
            if m is not None
        ]
        return max(steps, default=-1)

    def restore(
        self, target: Any = None, step: Optional[int] = None
    ) -> Optional[Any]:
        """Restore ``step`` (default: latest). With ``target`` (a pytree of
        jax.Arrays / ShapeDtypeStructs with shardings) arrays come back
        sharded per the target — Orbax handles resharding across mesh
        changes natively."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
        if step < 0:
            return None
        path = self._step_dir(step)
        if not os.path.isdir(path):
            return None
        if target is not None:
            restore_args = ocp.checkpoint_utils.construct_restore_args(target)
            restored = self._ckptr.restore(
                path, restore_args=restore_args
            )
        else:
            restored = self._ckptr.restore(path)
        return step, restored
