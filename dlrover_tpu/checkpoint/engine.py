"""Training-process side of flash checkpoint.

Parity: reference ``CheckpointEngine`` (``flash_checkpoint/engine.py:155-502``)
+ the sharded FSDP/Megatron engines, unified for JAX: every process stages
its *addressable unique shards* (with global index metadata) into its own
shm segment — the blocking cost of a save is one ``jax.device_get`` of local
shards plus a host memcpy. Persist/commit happens asynchronously in the
agent's saver.

Replica-deduplicated staging (``DLROVER_TPU_CKPT_DEDUP``, default on):
on a dp-replicated mesh every process used to stage its full
addressable view — dp identical copies of the replicated leaves per
save. With dedup each process stages (and the saver persists) only the
pieces it OWNS under the disjoint partition ``checkpoint/ownership.py``
derives from the leaves' shardings — per-process staged+persisted
bytes drop to ~1/dp on pure-dp meshes (Orbax's replica-aware
persistence, arXiv:2605.23066).

Restore is a tier ladder whose rungs UNION (each adds the pieces the
previous rungs were missing, per step):
- tier 0, shm      — this process's staged segment (fast restart);
- tier 1, disk     — the node-local tier (union across this node's
  process manifests);
- tier 2, object   — the shared storage tier (union across ALL nodes'
  manifests) — so a restore survives losing any single node's shm AND
  local disk. ``last_restore_stats`` records the tier, piece count and
  bytes. Disk/object pieces are CRC-verified; a corrupt piece demotes
  to the next tier instead of restoring garbage. Incomplete coverage
  after the last rung fails loudly (None + error log), never a
  silently zero-filled state.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.checkpoint import ownership
from dlrover_tpu.checkpoint.saver import (
    CKPT_EVENT_QUEUE,
    PERSIST_STATE_DICT,
    SHM_LOCK,
    CheckpointEvent,
    TRACKER_FILE,
    local_tier_dir,
    step_dir,
)
from dlrover_tpu.checkpoint.shm_handler import (
    CheckpointMeta,
    SharedMemoryHandler,
    flatten_state,
    resolve_dtype,
    shm_name,
    unflatten_state,
)
from dlrover_tpu.common import flags
from dlrover_tpu.observability import trace
from dlrover_tpu.common.ipc import (
    SharedDict,
    SharedLock,
    SharedQueue,
    default_socket_path,
)
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.storage import CheckpointStorage, PosixDiskStorage


# save-side region keys and the ownership plan's must stay
# byte-identical (staging matches one against the other) — single impl
_index_to_ranges = ownership.index_to_ranges


def _slice_pieces(
    plist, idx, shape: Tuple[int, ...], dtype, stats: Dict[str, int]
) -> np.ndarray:
    """Materialize exactly the requested region of a leaf from its
    staged pieces — the shard-wise restore callback. Never assembles
    the full array: either one piece CONTAINS the region (a contiguous
    slice of it comes back — the common case, since restore targets
    re-slice the same or a coarser grid than the save staged), or the
    region is assembled from the overlapping pieces at the region's
    extent (world-resize storage restores, where old-world shards tile
    differently). Uncovered gaps zero-fill, matching the historical
    full-array assembly (``np.zeros`` + piece copies)."""
    ranges = _index_to_ranges(idx, shape)
    extent = tuple(e - s for s, e in ranges)
    for p_index, arr, _ in plist:
        if all(
            ps <= ns and ne <= pe
            for (ns, ne), (ps, pe) in zip(ranges, p_index)
        ):
            rel = tuple(
                slice(ns - ps, ne - ps)
                for (ns, ne), (ps, pe) in zip(ranges, p_index)
            )
            stats["sliced"] = stats.get("sliced", 0) + 1
            # copy=True even when the slice is already contiguous: the
            # piece may be a VIEW into the shm segment, and the CPU
            # backend zero-copy-aliases host buffers into jax arrays —
            # an aliased restore would be silently overwritten by the
            # next staged save (and pins the segment against close())
            return np.array(arr[rel], dtype=dtype, copy=True)
    out = np.zeros(extent, dtype=dtype)
    for p_index, arr, _ in plist:
        inter = [
            (max(ns, ps), min(ne, pe))
            for (ns, ne), (ps, pe) in zip(ranges, p_index)
        ]
        if any(s >= e for s, e in inter):
            continue
        dst = tuple(
            slice(s - ns, e - ns) for (s, e), (ns, _) in zip(inter, ranges)
        )
        src = tuple(
            slice(s - ps, e - ps) for (s, e), (ps, _) in zip(inter, p_index)
        )
        out[dst] = arr[src]
    stats["region_assembled"] = stats.get("region_assembled", 0) + 1
    return out


#: live engines whose in-flight background stage must be drained at
#: teardown. Module-level (one atexit hook + one SIGTERM chain link per
#: PROCESS, not per engine) so repeatedly built engines — benches,
#: elastic rebuilds — neither grow the handler chain nor stay pinned
#: after close(). Weak refs: an engine abandoned without close() is
#: GC-collectable, not pinned (and not serially drained) forever.
_DRAIN_REGISTRY = weakref.WeakSet()
_drain_hooks_installed = False


def _drain_all_engines():
    for eng in list(_DRAIN_REGISTRY):
        try:
            eng._drain_at_exit()
        except BaseException as e:  # never let one engine's failure (or
            # a SystemExit smuggled out of a staging thread) skip the
            # remaining drains or the SIGTERM re-kill chain
            logger.warning("drain of %r at teardown failed: %s", eng, e)


def _install_drain_hooks():
    global _drain_hooks_installed
    if _drain_hooks_installed:
        return
    _drain_hooks_installed = True
    import atexit
    import signal

    atexit.register(_drain_all_engines)
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            _drain_all_engines()
            if callable(prev):
                prev(signum, frame)
            else:
                # prev is SIG_DFL/SIG_IGN — or None for a handler some C
                # extension installed, which Python cannot re-invoke; the
                # best available behavior is default-action re-kill
                signal.signal(signum, prev or signal.SIG_DFL)
                os.kill(os.getpid(), signum)
                # Reached only when the re-raise did not terminate us —
                # prev was SIG_IGN (the kill was ignored). Reinstall this
                # handler so LATER SIGTERMs still drain: leaving SIG_IGN
                # installed would let one survived SIGTERM permanently
                # disable crash-drain for the rest of the process.
                signal.signal(signum, _on_term)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # not the main thread: atexit alone still covers exits


class CheckpointEngine:
    def __init__(
        self,
        ckpt_dir: str,
        job_name: str = "",
        node_id: Optional[int] = None,
        process_id: Optional[int] = None,
        storage: Optional[CheckpointStorage] = None,
        socket_path: str = "",
        master_client=None,
        async_staging: Optional[bool] = None,
        dedup: Optional[bool] = None,
        ownership_world: Optional[Tuple[int, int]] = None,
    ):
        from dlrover_tpu.common.constants import NodeEnv

        self.ckpt_dir = ckpt_dir
        # warm-path elasticity: the checkpoint dir is the one path the
        # deployment already persists across pod restarts, so when no
        # explicit compile-cache dir was configured, default JAX's
        # persistent compilation cache under it — a restarted worker
        # then rebuilds its train step from cache (never overrides a
        # dir jax already has; no-op under DLROVER_TPU_WARM_COMPILE=0)
        try:
            from dlrover_tpu.train.warm_compile import default_cache_under

            default_cache_under(ckpt_dir)
        except Exception:
            pass  # cache is an optimization, never a ckpt failure
        self.job_name = job_name or flags.JOB_NAME.get()
        self.node_id = (
            node_id
            if node_id is not None
            else int(flags.NODE_ID.get())
        )
        self.process_id = (
            process_id
            if process_id is not None
            else int(flags.PROCESS_ID.get())
        )
        self._storage = storage or PosixDiskStorage()
        self._shm = SharedMemoryHandler(
            shm_name(self.job_name, self.node_id, self.process_id), create=True
        )
        self._socket_path = socket_path or default_socket_path(
            self.job_name, self.node_id
        )
        self._event_queue: Optional[SharedQueue] = None
        self._shm_lock: Optional[SharedLock] = None
        self._persist_state: Optional[SharedDict] = None
        self._awaiting_persist = -1
        self._master_client = master_client
        self.latest_saved_step = -1
        # Async staging (default ON): the training pause is one jitted
        # device-side copy of the state into fresh (non-donated) HBM
        # buffers — milliseconds, independent of the d2h link — after
        # which the d2h transfer and the host->shm memcpy both run in a
        # background thread against the snapshot. Donation safety: the
        # trainer's jitted step donates state buffers via donate_argnums,
        # which invalidates the source arrays the moment the next step
        # runs; the snapshot's buffers are XLA outputs with no
        # input-output aliasing, so they survive any later donation.
        # When HBM headroom cannot fit a second copy of the state the
        # stage degrades to blocking for the d2h transfer (the round-3
        # behavior); torch engines block for the whole shm stage
        # (reference blocks ~0.5 s, flash_checkpoint.md:362-415).
        if async_staging is None:
            async_staging = flags.ASYNC_STAGING.get()
        self._async_staging = bool(async_staging)
        self._device_snapshot_enabled = flags.DEVICE_SNAPSHOT.get()
        self._snap_fn = None
        self._staging_thread: Optional[threading.Thread] = None
        self._staging_error: Optional[BaseException] = None
        self._crash_drain_installed = False
        #: how the last save staged: "device_snapshot" (pause = HBM copy),
        #: "host_gather" (pause = d2h transfer), or "sync"
        self.last_stage_mode = ""
        #: how the last targeted restore placed its leaves: counts of
        #: "sliced" (single containing piece — zero assembly),
        #: "region_assembled" (requested extent built from overlapping
        #: pieces) and "full_assembled" (host-target fallback); tiered
        #: restores add "tier" (shm|disk|object — the deepest rung that
        #: had to contribute pieces), "tiers_read", "pieces" and "bytes"
        self.last_restore_stats: Dict[str, Any] = {}
        #: what the last stage kept vs skipped (replica-deduplicated
        #: staging): staged_bytes / skipped_replica_bytes / dedup
        self.last_stage_stats: Dict[str, Any] = {}
        # Replica-deduplicated tiered checkpointing (ownership.py):
        # `dedup` overrides the DLROVER_TPU_CKPT_DEDUP kill-switch;
        # `ownership_world` = (rank, world) simulates an N-process world
        # from one process (tests / the bench dedup leg) — the device
        # list is split into `world` contiguous virtual nodes.
        self._dedup = dedup
        self._ownership_world = ownership_world
        # the local tier is node-local disk by definition — plain posix,
        # independent of the configurable object-tier storage
        self._local_tier_storage = PosixDiskStorage()
        # lazy; lives as long as the engine so its pending-fanout retry
        # state survives across bare-run saves (_persist_inline)
        self._inline_persister = None

    # -- IPC (lazy: standalone use without an agent works too) --------------

    def _ipc_available(self) -> bool:
        return os.path.exists(self._socket_path)

    def _queue(self) -> Optional[SharedQueue]:
        if self._event_queue is None and self._ipc_available():
            self._event_queue = SharedQueue(CKPT_EVENT_QUEUE, self._socket_path)
        return self._event_queue

    def _lock(self) -> Optional[SharedLock]:
        if self._shm_lock is None and self._ipc_available():
            self._shm_lock = SharedLock(SHM_LOCK, self._socket_path)
        return self._shm_lock

    def _persist_dict(self) -> Optional[SharedDict]:
        if self._persist_state is None and self._ipc_available():
            self._persist_state = SharedDict(
                PERSIST_STATE_DICT, self._socket_path
            )
        return self._persist_state

    def _wait_pending_persist(self, timeout: float = 120.0):
        """Back-pressure: a queued disk persist reads the CURRENT shm, so
        staging the next step before the saver's copy would silently drop
        the persisted step (the saver refuses mismatched steps). Block
        until the saver reports the copy done (reference analogue: the
        trainer's next save contends on the saver-held shm lock)."""
        if self._awaiting_persist < 0:
            return
        state = self._persist_dict()
        if state is None:
            self._awaiting_persist = -1
            return
        deadline = time.time() + timeout
        key = f"copied-{self.process_id}"
        while time.time() < deadline:
            try:
                copied = state.get(key)
            except Exception:
                break
            if copied is not None and int(copied) >= self._awaiting_persist:
                self._awaiting_persist = -1
                return
            time.sleep(0.02)
        logger.warning(
            "persist of step %s still pending after %.0fs; staging anyway "
            "(that step may not reach storage)",
            self._awaiting_persist,
            timeout,
        )
        self._awaiting_persist = -1

    # -- save ---------------------------------------------------------------

    def _ownership_info(self):
        """(rank, world, device->rank) when replica-deduplicated staging
        applies, else None. Real worlds partition by process; the
        ``ownership_world`` ctor override simulates N virtual nodes from
        one process (tests, the bench dedup leg)."""
        enabled = (
            bool(self._dedup) if self._dedup is not None
            else flags.CKPT_DEDUP.get()
        )
        if not enabled:
            return None
        if self._ownership_world is not None:
            rank, world = self._ownership_world
            if world <= 1:
                return None
            return int(rank), int(world), ownership.virtual_proc_of(world)
        import jax

        world = jax.process_count()
        if world <= 1:
            return None
        return jax.process_index(), world, ownership.real_proc_of()

    def _tiering_enabled(self) -> bool:
        """Tiered restore rides the same kill-switch as dedup staging —
        but unlike staging it applies at ANY world size (a 1-process
        world still restores through shm -> local disk -> object)."""
        if self._dedup is not None:
            return bool(self._dedup)
        return flags.CKPT_DEDUP.get()

    def _gather_local_shards(self, state):
        """device_get each leaf's unique addressable shards — under
        replica-deduplicated staging, only the shards this process OWNS
        (ownership.py's disjoint partition; replicated leaves round-robin
        across the dp replicas so each stages ~1/dp of them).

        Returns (named_leaves, shard_info, treedef_bytes, leaf_paths)
        where named_leaves are (path#k, np array) entries for the shm
        segment and leaf_paths is the FULL flattened leaf set (restore
        uses it to tell "never saved" from "piece missing").
        """
        import jax

        flat, treedef_bytes = flatten_state_lazy(state)
        leaf_paths = [p for p, _ in flat]
        own = self._ownership_info()
        # one round-robin stream per stage, advanced in flatten order —
        # identical on every process (ownership.py's determinism rule)
        rr = ownership.RoundRobin() if own is not None else None
        skipped_bytes = 0
        # Pass 1: select each leaf's unique addressable shards (replicated
        # duplicates are skipped, never transferred) and issue all their
        # device->host transfers together, so the copies overlap on the
        # transfer engine instead of serializing behind np.asarray.
        # Plan entries: (name, data, extent, shard ranges, global shape,
        # owned sub-pieces). ``subs`` None stages the whole shard; a
        # list means the shard region was dp-round-robin SPLIT and only
        # the listed owned chunks are staged (sliced on host in pass 2).
        plan: List[Tuple[str, Any, Tuple[int, ...], Tuple,
                         Tuple[int, ...], Optional[list]]] = []

        def _owned_vol(pieces) -> int:
            total = 0
            for a in pieces:
                v = 1
                for s, e in a.ranges:
                    v *= max(0, e - s)
                total += v
            return total

        for path, leaf in flat:
            if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
                owned_by_parent = None
                if own is not None:
                    try:
                        assigns = ownership.assign_leaf(
                            tuple(leaf.shape), leaf.sharding, own[2], rr
                        )
                        owned_by_parent = {}
                        for a in assigns:
                            if a.owner == own[0]:
                                owned_by_parent.setdefault(
                                    a.parent_ranges, []
                                ).append(a)
                    except Exception as e:
                        # degrade to staging every unique shard: a leaf we
                        # cannot partition must never be silently dropped
                        logger.warning(
                            "ownership derivation failed for %s (%s); "
                            "staging all unique shards", path, e,
                        )
                seen = set()
                k = 0
                for shard in leaf.addressable_shards:
                    ranges = _index_to_ranges(shard.index, leaf.shape)
                    if ranges in seen:
                        continue
                    seen.add(ranges)
                    shard_bytes = int(
                        np.prod(shard.data.shape, dtype=np.int64)
                        * shard.data.dtype.itemsize
                    )
                    subs = None
                    if owned_by_parent is not None:
                        mine = owned_by_parent.get(ranges, [])
                        if not mine:
                            skipped_bytes += shard_bytes
                            continue
                        if len(mine) > 1 or mine[0].ranges != ranges:
                            subs = mine
                            skipped_bytes += shard_bytes - (
                                _owned_vol(mine)
                                * shard.data.dtype.itemsize
                            )
                    try:
                        shard.data.copy_to_host_async()
                    except Exception:
                        pass
                    extent = tuple(e - s for s, e in ranges)
                    plan.append(
                        (f"{path}#s{k}", shard.data, extent, ranges,
                         tuple(leaf.shape), subs)
                    )
                    k += 1
            else:
                arr = np.asarray(leaf)
                full = tuple((0, d) for d in arr.shape)
                subs = None
                if own is not None:
                    mine = [
                        a
                        for a in ownership.assign_host_leaf(
                            tuple(arr.shape), own[1], rr
                        )
                        if a.owner == own[0]
                    ]
                    if not mine:
                        skipped_bytes += int(arr.nbytes)
                        continue
                    if len(mine) > 1 or mine[0].ranges != full:
                        subs = mine
                        skipped_bytes += int(arr.nbytes) - (
                            _owned_vol(mine) * arr.dtype.itemsize
                        )
                plan.append(
                    (f"{path}#s0", arr, tuple(arr.shape), full,
                     tuple(arr.shape), subs)
                )
        # Pass 2: consume (np.asarray reuses the host literal the async
        # copy produced, so this is a wait + memcpy, not a transfer).
        # Split shards stage only their owned chunks — sliced views of
        # the host shard, materialized by the shm memcpy.
        named_leaves: List[Tuple[str, np.ndarray]] = []
        shard_info: Dict[str, Tuple[Tuple[int, ...], Tuple]] = {}
        staged_bytes = 0
        for name, data, extent, ranges, gshape, subs in plan:
            host = np.asarray(data).reshape(extent)
            if subs is None:
                staged_bytes += int(host.nbytes)
                named_leaves.append((name, host))
                shard_info[name] = (gshape, ranges)
                continue
            for j, a in enumerate(subs):
                rel = tuple(
                    slice(s - ps, e - ps)
                    for (s, e), (ps, _) in zip(a.ranges, ranges)
                )
                piece = np.ascontiguousarray(host[rel])
                staged_bytes += int(piece.nbytes)
                sub_name = f"{name}.{j}"
                named_leaves.append((sub_name, piece))
                shard_info[sub_name] = (gshape, a.ranges)
        # single-writer (the staging thread); readers only sample it
        self.last_stage_stats = {
            "staged_bytes": staged_bytes,
            "skipped_replica_bytes": skipped_bytes,
            "dedup": own is not None,
        }
        return named_leaves, shard_info, treedef_bytes, leaf_paths

    def save_to_memory(self, step: int, state: Any) -> float:
        """Stage into shm; returns the blocking seconds (the training pause).

        With ``async_staging`` the stage runs in a background thread and
        this returns in microseconds; a subsequent save (or load/close)
        joins the in-flight stage first.
        """
        t0 = time.time()
        m0 = time.monotonic()
        if self._async_staging:
            blocking = self._start_async_stage(t0, step, state, persist=False)
        else:
            try:
                self._stage_sync(step, state)
            except TimeoutError as e:
                logger.warning("%s; skipping memory save", e)
                return time.time() - t0
            blocking = time.time() - t0
            self._report_save(step, blocking)
        # trace spine: the training PAUSE this save cost (the background
        # stage records its own span from the staging thread)
        trace.record(
            "ckpt_save", "save.blocking", m0, blocking,
            tier="shm", step=step, mode=self.last_stage_mode,
        )
        return blocking

    def _install_crash_drain(self):
        """Join in-flight staging on every teardown the interpreter can
        see: atexit (covers normal exit AND uncaught exceptions) plus a
        chained SIGTERM handler (covers agent-driven restarts and k8s
        preemption grace windows). The device snapshot dies with the
        process, so draining at teardown is what turns "save() returned"
        into "that step is recoverable" for every crash short of SIGKILL.
        A hard kill falls back to the last drained step — or, if the kill
        lands inside the shm write itself (the header is invalidated
        before the payload memcpy and republished after, so a torn write
        can never be READ as valid), to the last disk persist; the
        master's shard queues replay the lost steps exactly
        (tests/test_ckpt_e2e.py covers both crash modes). Reference
        blocks through the shm write instead (engine.py:155-502) — zero
        window, but the pause scales with the d2h link."""
        if self._crash_drain_installed:
            return
        self._crash_drain_installed = True
        _DRAIN_REGISTRY.add(self)
        _install_drain_hooks()

    def _drain_at_exit(self):
        # Default 20 s: comfortably under Kubernetes' default 30 s
        # termination grace, leaving the previous SIGTERM handler's
        # cleanup time to run before the kubelet's SIGKILL. Raise it in
        # lockstep with terminationGracePeriodSeconds on slow d2h links
        # (deploy/k8s/README.md documents the pairing).
        timeout = float(flags.DRAIN_TIMEOUT.get())
        try:
            self.wait_staging(timeout=timeout)
        except BaseException as e:  # staging errors are stored broadly
            logger.warning("checkpoint drain at exit failed: %s", e)

    def _start_async_stage(
        self, t0: float, step: int, state: Any, persist: bool
    ) -> float:
        self._install_crash_drain()
        # Degrade, don't crash training: a failure of the PREVIOUS cycle's
        # staging (incl. its shm-lock timeout) means that step was lost —
        # log it and carry on with this one. The unbounded join means the
        # previous thread is always finished here, so the shm is free.
        try:
            self.wait_staging()
        except Exception as e:
            logger.warning(
                "previous background staging failed (%s); continuing", e
            )
        self._staging_error = None
        # Preferred: device-side snapshot — blocking cost is one HBM->HBM
        # copy; the d2h transfer moves to the background thread, so the
        # training pause is independent of the host link speed.
        payload = self._snapshot_on_device(state)
        on_device = payload is not None
        self.last_stage_mode = "device_snapshot" if on_device else "host_gather"
        if not on_device:
            # Fallback (no headroom / no device arrays / snapshot off):
            # d2h transfers happen HERE, synchronously, before the
            # caller's next (buffer-donating) train step can run. Only
            # host memory is touched after this point.
            try:
                payload = self._gather_local_shards(state)
            except Exception as e:
                logger.warning("device->host snapshot of step %s failed: %s",
                               step, e)
                # surface on the next wait_staging/load/close — a silently
                # dead snapshot path would let a job train for hours while
                # believing it is checkpointing
                self._staging_error = e
                return time.time() - t0
        pause = time.time() - t0
        self._staging_thread = threading.Thread(
            target=self._stage_in_background,
            args=(step, payload, on_device, persist, pause),
            name="ckpt-staging",
            daemon=True,
        )
        self._staging_thread.start()
        return time.time() - t0

    # -- device-side snapshot ----------------------------------------------

    def _snapshot_on_device(self, state):
        """Copy every device-array leaf into fresh HBM buffers via one
        jitted copy (milliseconds). Returns the snapshot pytree, or None
        when the engine should fall back to the blocking d2h stage
        (snapshot disabled, nothing on device, insufficient HBM headroom,
        or the copy itself failed, e.g. a racing allocation OOMed it)."""
        if not self._device_snapshot_enabled:
            return None
        import jax

        flat, treedef = jax.tree_util.tree_flatten(state)
        idx = [
            i
            for i, leaf in enumerate(flat)
            if isinstance(leaf, jax.Array)
            and hasattr(leaf, "addressable_shards")
        ]
        if not idx:
            return None
        if not self._hbm_headroom_ok([flat[i] for i in idx]):
            logger.warning(
                "insufficient HBM headroom for a device-side checkpoint "
                "snapshot; blocking for the d2h transfer instead"
            )
            return None
        if self._snap_fn is None:
            import jax.numpy as jnp

            # jnp.copy under jit lowers to a real copy op: without
            # donation XLA never aliases an entry parameter into an
            # output buffer, so the results are independent of the
            # (soon-to-be-donated) source arrays.
            self._snap_fn = jax.jit(
                lambda xs: [jnp.copy(x) for x in xs]
            )
        try:
            copies = self._snap_fn([flat[i] for i in idx])
            jax.block_until_ready(copies)
        except Exception as e:
            logger.warning(
                "device-side snapshot failed (%s); blocking for the d2h "
                "transfer instead", e
            )
            return None
        for i, c in zip(idx, copies):
            flat[i] = c
        return jax.tree_util.tree_unflatten(treedef, flat)

    @staticmethod
    def _hbm_headroom_ok(arrays, slack: float = 1.15) -> bool:
        """Check each local device can hold a second copy of its shards.
        Optimistic when the backend exposes no memory stats (CPU)."""
        need: Dict[Any, int] = {}
        for leaf in arrays:
            seen = set()
            for shard in leaf.addressable_shards:
                ranges = _index_to_ranges(shard.index, leaf.shape)
                if ranges in seen:
                    continue
                seen.add(ranges)
                nbytes = int(
                    np.prod(shard.data.shape, dtype=np.int64)
                    * shard.data.dtype.itemsize
                )
                need[shard.device] = need.get(shard.device, 0) + nbytes
        for dev, nbytes in need.items():
            try:
                stats = dev.memory_stats()
            except Exception:
                continue
            if not stats:
                continue
            limit = stats.get("bytes_limit")
            used = stats.get("bytes_in_use")
            if limit and used is not None and (limit - used) < nbytes * slack:
                return False
        return True

    def wait_staging(self, timeout: Optional[float] = None):
        """Join any in-flight background stage; re-raise its failure.
        Raises TimeoutError (keeping the thread tracked) if it is still
        running after ``timeout`` — callers must not touch the shm then."""
        thread = self._staging_thread
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                raise TimeoutError(
                    f"checkpoint staging still running after {timeout}s"
                )
            self._staging_thread = None
        if self._staging_error is not None:
            err, self._staging_error = self._staging_error, None
            raise err

    def _stage_in_background(
        self, step: int, payload, on_device: bool, persist: bool,
        pause: float
    ):
        try:
            with trace.span("ckpt_save", "stage.background", tier="shm",
                            step=step):
                if on_device:
                    # d2h off the training critical path: the source is
                    # the private device snapshot, untouchable by
                    # donation.
                    payload = self._gather_local_shards(payload)
                self._wait_pending_persist()
                self._write_shm(step, payload)
            if persist:
                self._queue_persist(step)
            self._report_save(step, pause)
        except BaseException as e:  # surfaced on the next wait_staging
            logger.exception("background staging of step %s failed", step)
            # single pointer write; the only reader (wait_staging) joins
            # this thread first, so the join IS the happens-before edge
            # a lock would add  # graftlint: disable=JG006
            self._staging_error = e
        finally:
            payload = None  # free the snapshot's HBM buffers promptly

    def _report_save(self, step: int, blocking: float):
        if self._master_client is not None:
            try:
                self._master_client.report_ckpt_step(step, blocking)
            except Exception:
                pass

    def _stage_sync(self, step: int, state: Any):
        self.last_stage_mode = "sync"
        self._wait_pending_persist()
        self._write_shm(step, self._gather_local_shards(state))

    def _write_shm(self, step: int, snapshot):
        import jax

        named_leaves, shard_info, treedef_bytes, leaf_paths = snapshot
        lock = self._lock()
        if lock is not None and not lock.acquire(timeout=120):
            raise TimeoutError(
                f"shm lock not acquired in 120s; step {step} not staged"
            )
        try:
            self._shm.save_state(
                step,
                named_leaves,
                treedef_bytes,
                shard_info=shard_info,
                world_size=jax.process_count(),
                process_id=self.process_id,
                ckpt_dir=os.path.abspath(self.ckpt_dir),
                leaf_paths=leaf_paths,
            )
        finally:
            if lock is not None:
                lock.release()
        self.latest_saved_step = step
        # replica mode (agent-set env): tell the saver to stream this staged
        # state to the backup peer, off the training critical path
        if flags.CKPT_REPLICA.get() == "1":
            q = self._queue()
            if q is not None:
                q.put(CheckpointEvent("backup", step=step).to_wire())

    def _queue_persist(self, step: int):
        q = self._queue()
        if q is not None:
            q.put(
                CheckpointEvent(
                    "save", step=step, persist=True, ckpt_dir=self.ckpt_dir
                ).to_wire()
            )
            self._awaiting_persist = step
        else:
            # no agent (bare run): persist synchronously in-process
            self._persist_inline(step)

    def save_to_storage(self, step: int, state: Any) -> float:
        """Stage + hand persistence to the agent saver (async)."""
        t0 = time.time()
        m0 = time.monotonic()
        if self._async_staging:
            blocking = self._start_async_stage(t0, step, state, persist=True)
            trace.record(
                "ckpt_save", "save.blocking", m0, blocking,
                tier="shm", step=step, mode=self.last_stage_mode,
                persist=True,
            )
            return blocking
        try:
            self._stage_sync(step, state)
        except TimeoutError as e:
            # staging was skipped (shm lock timeout): queuing a persist
            # event would make the saver persist a stale step as if it were
            # this one — surface the failure instead
            logger.error("%s; skipping persist", e)
            return time.time() - t0
        self._queue_persist(step)
        blocking = time.time() - t0
        self._report_save(step, blocking)
        trace.record(
            "ckpt_save", "save.blocking", m0, blocking,
            tier="shm", step=step, mode=self.last_stage_mode, persist=True,
        )
        return blocking

    def _persist_inline(self, step: int):
        import jax

        from dlrover_tpu.checkpoint.saver import CheckpointPersister

        # one long-lived persister per engine, NOT per save: its
        # _pending_fanout set is what lets a transiently-failed object
        # fanout retry on the next cycle (and protects those steps from
        # local-tier pruning) — a throwaway instance would discard both
        if self._inline_persister is None:
            self._inline_persister = CheckpointPersister(
                job_name=self.job_name,
                node_id=self.node_id,
                node_rank=jax.process_index(),
                num_nodes=jax.process_count(),
                local_process_ids=[self.process_id],
                storage=self._storage,
            )
        self._inline_persister.persist_step(self.ckpt_dir, step)

    # -- load ---------------------------------------------------------------

    def load(self, target: Any = None) -> Optional[Tuple[int, Any]]:
        """Restore (step, state) through the tier ladder: shm, then the
        node-local disk tier, then the shared object tier — each rung
        ADDING the pieces the previous rungs were missing (replica-
        deduplicated saves spread the pieces across processes, so no
        single rung need be complete). Kill-switch off: the legacy
        two-rung shm -> storage restore."""
        try:
            self.wait_staging()
        except Exception as e:
            logger.warning("in-flight staging failed before load: %s", e)
        m0 = time.monotonic()
        if not self._tiering_enabled():
            result = self._load_from_memory(target)
            if result is not None:
                logger.info("restored step %s from shared memory", result[0])
            else:
                result = self._load_from_storage(target)
        else:
            result = self._load_tiered(target)
        # trace spine: one restore span, stamped with the tier that
        # actually supplied the state (shm | disk | object | storage)
        trace.record(
            "ckpt_restore", "restore", m0, time.monotonic() - m0,
            tier=str((self.last_restore_stats or {}).get("tier", "")),
            step=result[0] if result is not None else -1,
            ok=result is not None,
        )
        return result

    # -- tiered load (shm -> local disk -> object) --------------------------

    def _staged_shm_meta(self):
        """This process's staged shm meta, after the ownership gate
        (a different job's Checkpointer staging under the same shm name
        is not ours to restore)."""
        meta = self._shm.read_meta()
        if (
            meta is not None and meta.ckpt_dir
            and meta.ckpt_dir != os.path.abspath(self.ckpt_dir)
        ):
            logger.info(
                "staged shm belongs to %s (this engine: %s); ignoring",
                meta.ckpt_dir, os.path.abspath(self.ckpt_dir),
            )
            return None
        return meta

    def _load_tiered(self, target: Any = None):
        import jax

        shm_meta = self._staged_shm_meta()
        shm_step = shm_meta.step if shm_meta is not None else -1
        # Restore-time consistency gate: every process must attempt the
        # SAME staged step, else one host restores step N and another
        # N-1 and the job trains from a torn state (the legacy memory
        # path's gate, kept verbatim for the shm rung).
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            steps = np.asarray(
                multihost_utils.process_allgather(np.array([shm_step]))
            ).reshape(-1)
            if not (steps == steps[0]).all():
                logger.warning(
                    "staged steps disagree across processes (%s); "
                    "ignoring the shm tier",
                    steps.tolist(),
                )
                shm_meta, shm_step = None, -1
        committed = self.committed_step()
        # Agree on the committed candidate too: the tracker is a shared
        # file a concurrent commit may be rewriting, so per-process
        # reads can return N and N-1 — candidate lists of different
        # content (torn adoption) or length (mismatched collective
        # counts below = hang). The MIN is the value every process has
        # definitely observed as committed.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            committed = int(
                np.asarray(
                    multihost_utils.process_allgather(
                        np.array([committed])
                    )
                ).min()
            )
        candidates = []
        if shm_step >= 0:
            candidates.append(shm_step)
        if committed >= 0 and committed != shm_step:
            candidates.append(committed)
        # The candidate list is now identical on every process (both
        # entries were just agreed), so the loop ITSELF needs agreement
        # only on each attempt's OUTCOME: one node may cover an
        # uncommitted staged step from its tiers while another cannot
        # (its peer's fanout died mid-write) — returning per-process
        # would resume one host at step N and another at M < N, a torn
        # state. Every process therefore votes after each attempt and a
        # candidate is adopted only unanimously.
        for step in candidates:
            result = self._restore_step_tiered(
                step, target, shm_meta if step == shm_step else None
            )
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                oks = np.asarray(
                    multihost_utils.process_allgather(
                        np.array([1 if result is not None else 0])
                    )
                ).reshape(-1)
                if not oks.all():
                    if result is not None:
                        logger.warning(
                            "step %s restorable here but not on %d peer "
                            "process(es); discarding for the next "
                            "candidate", step, int((oks == 0).sum()),
                        )
                    result = None
            if result is not None:
                return result
        if candidates:
            # fail LOUDLY: coverage gaps after the last rung mean lost
            # pieces, and a silently partial (zero-filled) state is the
            # one outcome worse than no restore at all
            logger.error(
                "tiered restore failed: no tier union covers the target "
                "for candidate steps %s (shm/local-disk/object read)",
                candidates,
            )
        return None

    def _merge_tier_pieces(
        self, storage, sdir: str, step: int, pieces, seen, expected
    ) -> Tuple[str, int, int]:
        """Merge one disk-layout tier's pieces for ``step`` into
        ``pieces``, skipping (leaf, region)s already supplied by an
        earlier rung and CRC-verifying each leaf file (a corrupt piece
        is dropped with a warning — the next rung supplies it).
        Returns (treedef_hex, pieces_added, bytes_added)."""
        added_p = added_b = 0
        tdef = ""
        for name in storage.listdir(sdir):
            if not name.startswith("proc-"):
                continue
            pdir = os.path.join(sdir, name)
            try:
                meta = CheckpointMeta.from_json(
                    storage.read(os.path.join(pdir, "meta.json")).decode()
                )
            except (FileNotFoundError, ValueError, KeyError):
                continue  # manifest-less dir = torn write; skip it
            if meta.step != step:
                continue
            tdef = tdef or meta.treedef_hex
            if not expected and meta.leaf_paths:
                # every manifest records the same complete list, in
                # flatten order — first one wins
                expected.extend(meta.leaf_paths)
            for i, lm in enumerate(meta.leaves):
                base = lm.path.rsplit("#", 1)[0]
                key = (base, lm.index)
                if key in seen:
                    continue
                try:
                    data = storage.read(os.path.join(pdir, f"leaf-{i}.bin"))
                except (FileNotFoundError, OSError):
                    continue
                if lm.crc32 and zlib.crc32(data) != (lm.crc32 & 0xFFFFFFFF):
                    logger.warning(
                        "CRC mismatch for %s piece %s under %s; dropping "
                        "the corrupt piece (a later tier supplies it)",
                        base, lm.index, pdir,
                    )
                    continue
                try:
                    arr = np.frombuffer(
                        data, dtype=resolve_dtype(lm.dtype)
                    ).reshape(lm.shape)
                except (ValueError, TypeError) as e:
                    logger.warning(
                        "unreadable piece %s under %s (%s); dropping",
                        base, pdir, e,
                    )
                    continue
                pieces.setdefault(base, []).append(
                    (lm.index, arr, lm.global_shape)
                )
                seen.add(key)
                added_p += 1
                added_b += len(data)
        return tdef, added_p, added_b

    def _restore_step_tiered(self, step: int, target, shm_meta):
        """Accumulate pieces for ``step`` rung by rung, attempting the
        assemble after every rung that contributed — the deepest rung
        actually read is the restore's tier attribution."""
        pieces: Dict[str, List[Tuple[Tuple, np.ndarray, Tuple[int, ...]]]] = {}
        seen: set = set()
        expected: List[str] = []  # full leaf list, manifest flatten order
        treedef_hex = ""
        contributed: List[str] = []
        total_p = total_b = 0

        def attempt():
            if not pieces:
                return None
            return self._assemble(
                step, (treedef_hex, pieces), target, full_data=False,
                expected_paths=expected or None,
            )

        def success(result):
            stats = (
                self.last_restore_stats if target is not None else {}
            )
            stats["tier"] = contributed[-1] if contributed else "shm"
            stats["tiers_read"] = list(contributed)
            stats["pieces"] = total_p
            stats["bytes"] = total_b
            self.last_restore_stats = stats
            logger.info(
                "restored step %s via tier %s (%d pieces, %d bytes, "
                "rungs read: %s)",
                step, stats["tier"], total_p, total_b, contributed,
            )
            return result

        # tier 0: this process's shm segment
        if shm_meta is not None and shm_meta.step == step:
            treedef_hex = shm_meta.treedef_hex
            if not expected and shm_meta.leaf_paths:
                expected.extend(shm_meta.leaf_paths)
            _, shm_pieces = self._read_pieces_from_shm(
                shm_meta, copy=target is None
            )
            added = 0
            for base, plist in shm_pieces.items():
                for idx, arr, gshape in plist:
                    key = (base, tuple(idx))
                    if key in seen:
                        continue
                    seen.add(key)
                    pieces.setdefault(base, []).append((idx, arr, gshape))
                    added += 1
                    total_b += int(arr.nbytes)
            if added:
                total_p += added
                contributed.append("shm")
                result = attempt()
                if result is not None:
                    return success(result)
        # tier 1: the node-local disk tier (union across this node's
        # process manifests)
        local_sdir = step_dir(
            local_tier_dir(self.ckpt_dir, self.node_id), step
        )
        tdef, p, b = self._merge_tier_pieces(
            self._local_tier_storage, local_sdir, step, pieces, seen,
            expected,
        )
        treedef_hex = treedef_hex or tdef
        if p:
            total_p += p
            total_b += b
            contributed.append("disk")
            result = attempt()
            if result is not None:
                return success(result)
        # tier 2: the shared object tier (union across ALL nodes)
        obj_sdir = step_dir(self.ckpt_dir, step)
        tdef, p, b = self._merge_tier_pieces(
            self._storage, obj_sdir, step, pieces, seen, expected
        )
        treedef_hex = treedef_hex or tdef
        if p:
            total_p += p
            total_b += b
            contributed.append("object")
            result = attempt()
            if result is not None:
                return success(result)
        return None

    def _load_from_memory(self, target: Any = None):
        import jax

        meta = self._shm.read_meta()
        if (
            meta is not None and meta.ckpt_dir
            and meta.ckpt_dir != os.path.abspath(self.ckpt_dir)
        ):
            # a different job's Checkpointer (same shm key: default job
            # name) staged this segment — it is not ours to restore
            logger.info(
                "staged shm belongs to %s (this engine: %s); ignoring",
                meta.ckpt_dir, os.path.abspath(self.ckpt_dir),
            )
            meta = None
        step = -1
        if meta is not None and meta.world_size == jax.process_count():
            step = meta.step
        elif meta is not None:
            # The world resized: this process's staged shards no longer
            # cover what the new mesh assigns it. Storage has all shards.
            logger.info(
                "staged shm is from a %s-process world (now %s); "
                "falling back to storage restore",
                meta.world_size,
                jax.process_count(),
            )
        # Restore-time consistency gate: every process must hold the SAME
        # staged step, else one host restores step N and another N-1 and
        # the job trains from a torn state. The reference guards this at
        # save time with a gloo allgather (engine.py:76-95); gating at
        # restore keeps the save hot path collective-free.
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            steps = np.asarray(
                multihost_utils.process_allgather(np.array([step]))
            ).reshape(-1)
            if not (steps == steps[0]).all():
                logger.warning(
                    "staged steps disagree across processes (%s); "
                    "falling back to storage restore",
                    steps.tolist(),
                )
                return None
            step = int(steps[0])
        if step < 0 or meta is None:
            return None
        # With a target the placement callback copies just the slices it
        # is asked for, so the leaves can stay VIEWS into the shm buffer
        # (no up-front whole-leaf memcpy). Without a target the restored
        # pytree itself would alias shm — copy as before.
        pieces = self._read_pieces_from_shm(meta, copy=target is None)
        result = self._assemble(meta.step, pieces, target, full_data=False)
        if result is not None and target is not None:
            stats = self.last_restore_stats
            stats["tier"] = "shm"
            stats["tiers_read"] = ["shm"]
            stats["pieces"] = len(meta.leaves)
            stats["bytes"] = int(sum(lm.nbytes for lm in meta.leaves))
        return result

    def _load_from_storage(self, target: Any = None):
        """Legacy (kill-switch-off) storage restore — one object rung
        through the same manifest reader as the ladder, so CRC
        verification and torn-dir skipping apply here too."""
        step = self.committed_step()
        if step < 0:
            return None
        sdir = step_dir(self.ckpt_dir, step)
        pieces: Dict[str, List[Tuple[Tuple, np.ndarray, Tuple[int, ...]]]] = {}
        expected: List[str] = []
        treedef_hex, _, _ = self._merge_tier_pieces(
            self._storage, sdir, step, pieces, set(), expected
        )
        if not pieces:
            return None
        result = self._assemble(
            step, (treedef_hex, pieces), target, full_data=True,
            expected_paths=expected or None,
        )
        if result is not None:
            if target is not None:
                stats = self.last_restore_stats
                stats["tier"] = "object"
                stats["tiers_read"] = ["object"]
                stats["pieces"] = sum(len(p) for p in pieces.values())
                stats["bytes"] = int(sum(
                    a.nbytes
                    for plist in pieces.values()
                    for _, a, _ in plist
                ))
            logger.info("restored step %s from storage %s", step, sdir)
        return result

    def _read_pieces_from_shm(self, meta: CheckpointMeta, copy: bool = True):
        pieces: Dict[str, List[Tuple[Tuple, np.ndarray, Tuple[int, ...]]]] = {}
        for leaf_meta in meta.leaves:
            arr = self._shm.read_leaf(leaf_meta, copy=copy)
            base = leaf_meta.path.rsplit("#", 1)[0]
            pieces.setdefault(base, []).append(
                (leaf_meta.index, arr, leaf_meta.global_shape)
            )
        return meta.treedef_hex, pieces

    def _assemble(
        self, step, treedef_and_pieces, target, full_data: bool,
        expected_paths: Optional[List[str]] = None,
    ):
        """Rebuild the pytree. With a ``target`` (pytree of jax.Arrays or
        ShapeDtypeStructs with shardings) arrays are placed per the target's
        sharding; otherwise plain numpy arrays are returned.

        ``expected_paths`` (tiered restores): the checkpoint's FULL leaf
        list from its manifests, in flatten order. A target leaf in that
        set with no pieces is MISSING DATA — return None so the caller
        reads the next tier (or fails loudly) — while a target leaf
        outside it was never saved (a new state field) and legitimately
        keeps its target value. Targetless restores also rebuild in the
        manifest's leaf order (merged multi-process pieces arrive
        grouped by process, not in flatten order)."""
        import jax

        treedef_hex, pieces = treedef_and_pieces
        expected_set = set(expected_paths) if expected_paths else None

        def build_full(path: str) -> Optional[np.ndarray]:
            plist = pieces.get(path)
            if not plist:
                return None
            _, first_arr, gshape = plist[0]
            # global_shape is always recorded at stage time; () is a
            # legitimate 0-d shape, not "absent".
            gshape = tuple(gshape)
            if len(plist) == 1 and tuple(first_arr.shape) == gshape:
                return plist[0][1]
            out = np.zeros(gshape, dtype=first_arr.dtype)
            for index, arr, _ in plist:
                sl = tuple(slice(s, e) for s, e in index)
                out[sl] = arr.reshape(tuple(e - s for s, e in index))
            return out

        def region_covered(needed, plist) -> bool:
            """The staged pieces' UNION covers the region — not just a
            single containing piece. A resize that re-tiles a leaf
            (zero-1 moments: dp4 staged quarters, dp2 target halves)
            makes each target shard span several staged pieces, which
            ``_slice_pieces`` assembles fine; requiring single-piece
            containment here would reject exactly those restores. The
            check partitions the region on the pieces' boundary grid
            and demands every cell lie inside some piece (pieces are
            per-device shards — the grid stays tiny)."""
            import itertools

            cuts = []
            for d, (ns, ne) in enumerate(needed):
                c = {ns, ne}
                for p_index, _, _ in plist:
                    ps, pe = p_index[d]
                    if ns < ps < ne:
                        c.add(ps)
                    if ns < pe < ne:
                        c.add(pe)
                edges = sorted(c)
                cuts.append(list(zip(edges, edges[1:])))
            for cell in itertools.product(*cuts):
                if not any(
                    all(
                        ps <= cs and ce <= pe
                        for (cs, ce), (ps, pe) in zip(cell, p_index)
                    )
                    for p_index, _, _ in plist
                ):
                    return False
            return True

        def covers_target(t_leaf, path: str) -> bool:
            """Partial (shm) data must cover every region the target's
            sharding assigns locally — else zero-fill would corrupt state."""
            if full_data:
                return True
            plist = pieces.get(path)
            if not plist:
                return False
            if not (isinstance(t_leaf, jax.Array) or hasattr(t_leaf, "sharding")):
                # host (unsharded) target: build_full materializes the
                # WHOLE array, so the pieces' union must tile all of it
                # — under dedup staging this process's shm holds only
                # its chunk of a split host leaf, and waving it through
                # would zero-fill the non-owned ranges
                gshape = tuple(plist[0][2])
                return region_covered(
                    tuple((0, d) for d in gshape), plist
                )
            shape = tuple(t_leaf.shape)
            # dedup via the normalized (start, stop) form: raw shard
            # indices are tuples of slice objects, which are unhashable
            # before Python 3.12 — set() over them is a TypeError here
            for needed in {
                _index_to_ranges(idx, shape)
                for idx in t_leaf.sharding.addressable_devices_indices_map(
                    shape
                ).values()
            }:
                if not region_covered(needed, plist):
                    return False
            return True

        if target is not None:
            stats: Dict[str, int] = {}
            flat_t, treedef = jax.tree_util.tree_flatten_with_path(target)
            out_leaves = []
            for path, t_leaf in flat_t:
                key = jax.tree_util.keystr(path)
                plist = pieces.get(key)
                if not plist:
                    if expected_set is not None and key in expected_set:
                        logger.warning(
                            "leaf %s is in the checkpoint manifest but no "
                            "pieces are available from the tiers read so "
                            "far", key,
                        )
                        return None
                    logger.warning("checkpoint missing leaf %s; keeping target", key)
                    out_leaves.append(t_leaf)
                    continue
                # global shape recorded at stage time; shape-gate without
                # assembling anything
                gshape = tuple(plist[0][2])
                if (
                    hasattr(t_leaf, "shape")
                    and gshape != tuple(t_leaf.shape)
                ):
                    # same leaf path but a different tensor shape: this is
                    # NOT our checkpoint (e.g. a stale shm segment from an
                    # unrelated job reusing the name) — refuse the whole
                    # restore so the caller falls through to storage/orbax
                    logger.warning(
                        "checkpoint leaf %s shape %s != target %s; "
                        "rejecting this source",
                        key, gshape, tuple(t_leaf.shape),
                    )
                    return None
                if not covers_target(t_leaf, key):
                    logger.info(
                        "staged shards do not cover leaf %s for the current "
                        "sharding; falling back to storage",
                        key,
                    )
                    return None
                if isinstance(t_leaf, jax.Array) or hasattr(
                    t_leaf, "sharding"
                ):
                    # SHARD-WISE placement: the callback materializes
                    # exactly the index each device asks for, straight
                    # from the staged pieces — the full host array is
                    # never assembled (peak restore memory = largest
                    # local shard, not largest tensor)
                    out_leaves.append(
                        _place_sharded(t_leaf, plist, stats)
                    )
                else:
                    full = build_full(key)
                    if not full_data:
                        # shm pieces are views; build_full's single-piece
                        # shortcut returns the view itself, and a host
                        # target leaf would keep it — aliasing the
                        # restored value to the segment the next save
                        # overwrites
                        full = np.array(full, copy=True)
                    stats["full_assembled"] = (
                        stats.get("full_assembled", 0) + 1
                    )
                    out_leaves.append(_place_like(t_leaf, full))
            self.last_restore_stats = stats
            return step, jax.tree_util.tree_unflatten(treedef, out_leaves)

        # no target: numpy pytree via stored treedef
        full_leaves = []
        if expected_set is not None:
            missing = [p for p in expected_paths if p not in pieces]
            if missing:
                logger.warning(
                    "checkpoint leaves %s have no pieces in the tiers "
                    "read so far", missing[:3],
                )
                return None
            paths = list(expected_paths)  # manifest flatten order
        else:
            # legacy: stored leaf order == flatten order (single-process
            # metas record paths in order)
            paths = list(pieces.keys())
        for path in paths:
            plist = pieces[path]
            if not full_data:
                # partial (shm) data: pieces must tile the whole array
                _, first_arr, gshape = plist[0]
                gvol = int(np.prod(tuple(gshape))) if gshape else first_arr.size
                vol = sum(int(a.size) for _, a, _ in plist)
                if vol < gvol:
                    logger.info(
                        "staged shards cover %s/%s of %s; need storage restore",
                        vol,
                        gvol,
                        path,
                    )
                    return None
            full = build_full(path)
            if full is None:
                return None
            full_leaves.append(full)
        try:
            state = unflatten_state(bytes.fromhex(treedef_hex), full_leaves)
        except Exception as e:
            logger.warning("treedef restore failed (%s); returning dict", e)
            state = dict(zip(paths, full_leaves))
        return step, state

    # -- misc ---------------------------------------------------------------

    def committed_step(self) -> int:
        try:
            return int(
                self._storage.read(os.path.join(self.ckpt_dir, TRACKER_FILE))
            )
        except (FileNotFoundError, ValueError):
            return -1

    def close(self, unlink_shm: bool = False):
        """``unlink_shm=True`` also removes the shm segment — for
        short-lived tools (benches, dryruns) whose staged state must not
        outlive them; training processes keep the segment so the agent's
        saver can ship it after a crash."""
        _DRAIN_REGISTRY.discard(self)
        self._crash_drain_installed = False
        try:
            self.wait_staging(timeout=300)
        except Exception as e:
            logger.warning("in-flight staging failed at close: %s", e)
        if self._event_queue is not None:
            self._event_queue.close()
        if self._shm_lock is not None:
            self._shm_lock.close()
        self._shm.close(unlink=unlink_shm)


def _place_sharded(t_leaf, plist, stats: Dict[str, int]):
    """Place a leaf per the target's sharding, shard-wise: each device's
    buffer is fed exactly its requested region sliced from the staged
    pieces (no per-host full-array assembly — Orbax-style distributed
    restore, arXiv:2605.23066). 0-d leaves short-circuit to a plain
    ``device_put`` (no index to slice)."""
    import jax

    sharding = t_leaf.sharding
    dtype = t_leaf.dtype
    shape = tuple(t_leaf.shape)
    if len(shape) == 0:
        stats["sliced"] = stats.get("sliced", 0) + 1
        # copy: the piece may be a view into shm (see _slice_pieces)
        return jax.device_put(
            np.array(plist[0][1], dtype=dtype, copy=True).reshape(()),
            sharding,
        )
    return jax.make_array_from_callback(
        shape,
        sharding,
        lambda idx: _slice_pieces(plist, idx, shape, dtype, stats),
    )


def _place_like(t_leaf, full: np.ndarray):
    """Place a host array according to the target leaf's sharding/dtype."""
    import jax

    if isinstance(t_leaf, jax.Array) or hasattr(t_leaf, "sharding"):
        sharding = t_leaf.sharding
        dtype = t_leaf.dtype
        full = full.astype(dtype) if full.dtype != dtype else full
        if full.ndim == 0:
            return jax.device_put(full, sharding)
        return jax.make_array_from_callback(
            tuple(t_leaf.shape), sharding, lambda idx: np.ascontiguousarray(full[idx])
        )
    if hasattr(t_leaf, "shape") and hasattr(t_leaf, "dtype"):
        return full.astype(t_leaf.dtype)
    return full


def flatten_state_lazy(state):
    """flatten_state but without forcing device transfer (arrays stay jax)."""
    import jax
    import pickle
    import pickletools

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(state)
    flat = [(jax.tree_util.keystr(p), leaf) for p, leaf in leaves_with_path]
    treedef_bytes = pickletools.optimize(pickle.dumps(treedef))
    return flat, treedef_bytes
