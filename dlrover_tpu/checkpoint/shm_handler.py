"""Pytree <-> POSIX shared memory staging.

Parity: reference ``SharedMemoryHandler`` (``ckpt_saver.py:219-404``), which
stages torch state-dicts; here the unit is a JAX pytree whose leaves are
host numpy arrays (produced by ``jax.device_get`` of addressable shards).

Segment layout::

    [8B header_len][header JSON][... data at HEADER_SPACE ...]

``header_len`` is written LAST so a crash mid-write leaves the previous
checkpoint readable (header_len==0 or stale header -> previous step).

The tree structure is stored as a recursive JSON skeleton for plain
containers (dict/list/tuple/None/scalars); arbitrary pytree nodes
(flax/optax states, NamedTuples) are handled via their registered pytree
flattening with a restricted-unpickler treedef fallback.
"""

from __future__ import annotations

import io
import json
import pickle
import pickletools
import struct
import time
from dataclasses import dataclass, field
from multiprocessing import shared_memory, resource_tracker
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import logger

HEADER_SPACE = 4 << 20  # 4 MiB for metadata
_LEN_FMT = "<Q"
_LEN_SIZE = 8

_SAFE_PICKLE_MODULES = (
    "jax",
    "jaxlib",
    "flax",
    "optax",
    "chex",
    "numpy",
    "builtins",
    "collections",
    "dlrover_tpu",
)


class _RestrictedUnpickler(pickle.Unpickler):
    """Treedef unpickling restricted to ML-library modules."""

    def find_class(self, module, name):
        if any(
            module == m or module.startswith(m + ".")
            for m in _SAFE_PICKLE_MODULES
        ):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"treedef references disallowed module {module}.{name}"
        )


def _loads_restricted(data: bytes):
    return _RestrictedUnpickler(io.BytesIO(data)).load()


@dataclass
class TensorMeta:
    path: str
    dtype: str
    shape: Tuple[int, ...]
    offset: int
    nbytes: int
    # sharded-save metadata: where this local shard sits in the global array
    global_shape: Tuple[int, ...] = ()
    index: Tuple[Tuple[int, int], ...] = ()  # (start, stop) per dim
    # zlib.crc32 of the persisted leaf file's bytes, filled at persist
    # time (0 = not computed — shm-only metas and legacy checkpoints);
    # disk/object-tier restores verify it and demote a corrupt piece to
    # the next tier instead of returning garbage
    crc32: int = 0

    def to_dict(self) -> Dict:
        return {
            "path": self.path,
            "dtype": self.dtype,
            "shape": list(self.shape),
            "offset": self.offset,
            "nbytes": self.nbytes,
            "global_shape": list(self.global_shape),
            "index": [list(p) for p in self.index],
            "crc32": self.crc32,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "TensorMeta":
        return cls(
            path=d["path"],
            dtype=d["dtype"],
            shape=tuple(d["shape"]),
            offset=d["offset"],
            nbytes=d["nbytes"],
            global_shape=tuple(d.get("global_shape", [])),
            index=tuple(tuple(p) for p in d.get("index", [])),
            crc32=int(d.get("crc32", 0)),
        )


@dataclass
class CheckpointMeta:
    step: int = -1
    leaves: List[TensorMeta] = field(default_factory=list)
    treedef_hex: str = ""
    timestamp: float = 0.0
    world_size: int = 1
    process_id: int = 0
    total_bytes: int = 0
    # which checkpoint DIRECTORY the staged state belongs to: shm names
    # key on (job, node, process), so two Checkpointers with the default
    # job name but different directories would otherwise cross-restore
    ckpt_dir: str = ""
    # the FULL flattened leaf-path set of the saved state (base paths,
    # no "#sK" suffix). Under replica-deduplicated staging this process
    # holds only its owned subset in `leaves`; restore uses this list to
    # tell "leaf the checkpoint never had" (keep the target, warn) from
    # "leaf whose pieces are missing" (demote to the next tier / fail
    # loudly). Empty for legacy checkpoints.
    leaf_paths: List[str] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "step": self.step,
                "leaves": [m.to_dict() for m in self.leaves],
                "treedef_hex": self.treedef_hex,
                "timestamp": self.timestamp,
                "world_size": self.world_size,
                "process_id": self.process_id,
                "total_bytes": self.total_bytes,
                "ckpt_dir": self.ckpt_dir,
                "leaf_paths": list(self.leaf_paths),
            }
        )

    @classmethod
    def from_json(cls, content: str) -> "CheckpointMeta":
        d = json.loads(content)
        return cls(
            step=d["step"],
            leaves=[TensorMeta.from_dict(m) for m in d["leaves"]],
            treedef_hex=d.get("treedef_hex", ""),
            timestamp=d.get("timestamp", 0.0),
            world_size=d.get("world_size", 1),
            process_id=d.get("process_id", 0),
            total_bytes=d.get("total_bytes", 0),
            ckpt_dir=d.get("ckpt_dir", ""),
            leaf_paths=list(d.get("leaf_paths", [])),
        )


def resolve_dtype(name: str) -> np.dtype:
    """Dtype from its string name, including extended ml_dtypes (bfloat16,
    float8_*…) that plain ``np.dtype(name)`` cannot parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _keystr(path) -> str:
    import jax

    return jax.tree_util.keystr(path)


def flatten_state(state) -> Tuple[List[Tuple[str, np.ndarray]], bytes]:
    """Flatten a pytree into (path, host-array) leaves + pickled treedef."""
    import jax

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(state)
    out = []
    for path, leaf in leaves_with_path:
        arr = np.asarray(leaf)
        out.append((_keystr(path), arr))
    treedef_bytes = pickletools.optimize(pickle.dumps(treedef))
    return out, treedef_bytes


def unflatten_state(treedef_bytes: bytes, leaves: List[np.ndarray]):
    treedef = _loads_restricted(treedef_bytes)
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)


def shm_name(job_name: str, node_id: int, process_id: int) -> str:
    safe_job = job_name.replace("/", "_")
    return f"dlrover_tpu_ckpt_{safe_job}_{node_id}_{process_id}"


class SharedMemoryHandler:
    """One shm segment per training process, reused across steps."""

    def __init__(self, name: str, create: bool = False, size: int = 0):
        self.name = name
        self._create = create
        self._size = size
        self._shm: Optional[shared_memory.SharedMemory] = None

    # -- lifecycle ----------------------------------------------------------

    def _ensure(self, needed_bytes: int = 0):
        total = HEADER_SPACE + needed_bytes
        if self._shm is not None and self._shm.size >= total:
            return
        if self._shm is not None:
            self._shm.close()
            if self._create:
                try:
                    self._shm.unlink()
                except FileNotFoundError:
                    pass
            self._shm = None
        if self._create:
            size = max(total, self._size)
            try:
                self._shm = shared_memory.SharedMemory(
                    name=self.name, create=True, size=size
                )
                # zero the length word so readers see "empty"
                struct.pack_into(_LEN_FMT, self._shm.buf, 0, 0)
            except FileExistsError:
                # A previous (restarted) incarnation left the segment: reuse
                # it if large enough — its staged step is still restorable —
                # else replace it.
                existing = shared_memory.SharedMemory(name=self.name)
                if existing.size >= total:
                    self._shm = existing
                else:
                    existing.close()
                    existing.unlink()
                    self._shm = shared_memory.SharedMemory(
                        name=self.name, create=True, size=size
                    )
                    struct.pack_into(_LEN_FMT, self._shm.buf, 0, 0)
            # The segment must outlive this (crashing) process: the agent's
            # saver owns cleanup, so keep python's resource tracker away.
            _unregister_from_resource_tracker(self.name)
        else:
            self._shm = shared_memory.SharedMemory(name=self.name)
            _unregister_from_resource_tracker(self.name)

    def attach(self) -> bool:
        """Attach to an existing segment (saver side). False if absent."""
        if self._shm is not None:
            return True
        try:
            self._shm = shared_memory.SharedMemory(name=self.name)
            _unregister_from_resource_tracker(self.name)
            return True
        except FileNotFoundError:
            return False

    def close(self, unlink: bool = False):
        if self._shm is not None:
            self._shm.close()
            if unlink:
                try:
                    self._shm.unlink()
                except FileNotFoundError:
                    pass
            self._shm = None

    @property
    def buf(self):
        return self._shm.buf if self._shm else None

    # -- write --------------------------------------------------------------

    def save_state(
        self,
        step: int,
        named_leaves: List[Tuple[str, np.ndarray]],
        treedef_bytes: bytes,
        shard_info: Optional[Dict[str, Tuple[Tuple[int, ...], Tuple]]] = None,
        world_size: int = 1,
        process_id: int = 0,
        ckpt_dir: str = "",
        leaf_paths: Optional[List[str]] = None,
    ):
        """Copy leaves into shm and publish the header."""
        total = sum(int(a.nbytes) for _, a in named_leaves)
        self._ensure(total)
        buf = self._shm.buf
        # invalidate while writing
        struct.pack_into(_LEN_FMT, buf, 0, 0)
        metas: List[TensorMeta] = []
        offset = HEADER_SPACE
        for path, arr in named_leaves:
            arr = np.ascontiguousarray(arr)
            n = int(arr.nbytes)
            dest = np.frombuffer(buf, dtype=np.uint8, count=n, offset=offset)
            dest[:] = arr.view(np.uint8).reshape(-1)
            gshape: Tuple[int, ...] = ()
            index: Tuple = ()
            if shard_info and path in shard_info:
                gshape, index = shard_info[path]
            metas.append(
                TensorMeta(
                    path=path,
                    dtype=str(arr.dtype),
                    shape=tuple(arr.shape),
                    offset=offset,
                    nbytes=n,
                    global_shape=tuple(gshape),
                    index=tuple(index),
                )
            )
            offset += n
        meta = CheckpointMeta(
            step=step,
            leaves=metas,
            treedef_hex=treedef_bytes.hex(),
            timestamp=time.time(),
            world_size=world_size,
            process_id=process_id,
            total_bytes=offset - HEADER_SPACE,
            ckpt_dir=ckpt_dir,
            leaf_paths=list(leaf_paths or []),
        )
        header = meta.to_json().encode()
        if _LEN_SIZE + len(header) > HEADER_SPACE:
            raise ValueError(
                f"checkpoint meta too large: {len(header)} bytes "
                f"(> {HEADER_SPACE - _LEN_SIZE})"
            )
        buf[_LEN_SIZE : _LEN_SIZE + len(header)] = header
        # publish: length word last
        struct.pack_into(_LEN_FMT, buf, 0, len(header))

    # -- read ---------------------------------------------------------------

    def read_meta(self) -> Optional[CheckpointMeta]:
        if self._shm is None and not self.attach():
            return None
        buf = self._shm.buf
        (hlen,) = struct.unpack_from(_LEN_FMT, buf, 0)
        if hlen == 0 or hlen > HEADER_SPACE - _LEN_SIZE:
            return None
        try:
            return CheckpointMeta.from_json(
                bytes(buf[_LEN_SIZE : _LEN_SIZE + hlen]).decode()
            )
        except (json.JSONDecodeError, KeyError) as e:
            logger.warning("corrupt shm checkpoint header: %s", e)
            return None

    def read_leaf(self, meta: TensorMeta, copy: bool = False) -> np.ndarray:
        buf = self._shm.buf
        # np.prod(()) == 1.0 handles scalars; 0-size arrays keep count 0.
        count = int(np.prod(meta.shape))
        arr = np.frombuffer(
            buf, dtype=resolve_dtype(meta.dtype), count=count,
            offset=meta.offset
        ).reshape(meta.shape)
        return arr.copy() if copy else arr

    def restore_segment(self, data: bytes):
        """Materialize a transferred segment (replica restore): ``data`` is
        a prefix of a valid segment (header + leaf bytes). The length word
        is written last so a concurrent reader never sees a torn header."""
        needed = max(0, len(data) - HEADER_SPACE)
        self._ensure(needed)
        buf = self._shm.buf
        struct.pack_into(_LEN_FMT, buf, 0, 0)
        buf[_LEN_SIZE : len(data)] = data[_LEN_SIZE:]
        struct.pack_into(
            _LEN_FMT, buf, 0, struct.unpack_from(_LEN_FMT, data, 0)[0]
        )

    def load_state(self, copy: bool = True):
        """Rebuild (step, pytree) from shm; None if nothing staged."""
        meta = self.read_meta()
        if meta is None:
            return None
        leaves = [self.read_leaf(m, copy=copy) for m in meta.leaves]
        state = unflatten_state(bytes.fromhex(meta.treedef_hex), leaves)
        return meta.step, state


def _unregister_from_resource_tracker(name: str):
    """Attaching processes must not let the resource tracker unlink the
    segment at their exit (reference fights the same leak, multi_process.py)."""
    try:
        resource_tracker.unregister(f"/{name}", "shared_memory")
    except Exception:
        pass
