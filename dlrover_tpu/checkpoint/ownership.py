"""Replica-deduplicated checkpoint ownership.

On a dp-replicated mesh every process used to stage (and persist) its
full addressable view of the train state — ``dp`` identical copies of
the params and any replicated optimizer moments hit shm and storage on
every save. Orbax's replica-aware persistence (arXiv:2605.23066) and
FastPersist's parallel-IO save path (arXiv:2406.13768) both partition
the state into *disjoint* per-writer shards instead: each replica
persists only the pieces it owns, and restore reassembles from the
union. This module derives that partition.

The derivation has to satisfy one invariant above all: **the save
layout and the restore target must come from the same machinery**, so
they can never disagree across resizes or zero-1 on/off flips. Both
sides therefore key on a leaf's ``(shape, NamedSharding)`` — the live
arrays at stage time, and the trainer's ``_state_avatar_for(mesh)``
avatars (the same trees AOT lowering and live-reshard transfer targets
are built from) on the planning/verification side.
:func:`plan_for_avatars` and :func:`plan_for_state` produce identical
assignments for a state placed by those avatars
(tests/test_ckpt_tiers.py pins it).

Assignment rules, deterministic across processes (no communication):

- every distinct shard *region* of a leaf (from
  ``sharding.devices_indices_map`` over the full mesh — identical on
  every process) is assigned exactly one owner among the processes
  holding a replica of it;
- a region with a single holder (a genuinely sharded piece — fsdp/sp
  shards, zero-1 moments) is owned by that holder;
- a region replicated across ``k`` processes (pure-dp params, the
  pre-zero-1 moments) is SPLIT into ``k`` contiguous chunks along its
  largest dimension, one chunk per replica — the dp-round-robin split
  — so per-node bytes land at ~1/dp regardless of how unevenly leaf
  sizes are distributed (a whole-leaf round-robin would hand whoever
  draws the embedding table several times its fair share). The
  chunk→replica pairing is rotated by a per-replica-set counter
  advanced in flatten order, so the first-chunk remainder element
  doesn't always land on the same rank. Regions too small to split
  (every dim < k, scalars) fall back to whole-region round-robin over
  the same counter.

Determinism argument: the pytree flatten order, each leaf's global
``devices_indices_map`` and the sorted region order are identical on
every process, so every process computes the same full assignment and
simply keeps its own slice of it.

Virtual worlds: single-process test/bench runs (the 8-device CPU mesh)
have ``jax.process_count() == 1``, which makes the real partition
trivial. :func:`virtual_proc_of` splits the device list into ``world``
contiguous groups so a single process can *simulate* an N-node world —
the bench's dedup-persist leg and the node-loss recovery tests stage
one virtual node at a time through it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

PyTree = Any
Ranges = Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class PieceAssignment:
    """One owned piece of one leaf. ``ranges`` is the piece itself;
    ``parent`` is the staged shard region it was cut from (equal to
    ``ranges`` for unsplit pieces) — staging matches a device shard's
    region against ``parent`` and slices ``ranges`` out of it."""

    ranges: Ranges          # (start, stop) per dim, () for 0-d
    owner: int              # owning process rank
    replicas: Tuple[int, ...]  # every rank holding parent
    parent: Optional[Ranges] = None

    @property
    def parent_ranges(self) -> Ranges:
        return self.ranges if self.parent is None else self.parent


class RoundRobin:
    """Per-replica-set round-robin counters. One instance per staging
    pass / plan; advancing it in flatten order on every process yields
    the same assignment everywhere (the module docstring's determinism
    argument)."""

    def __init__(self):
        self._counters: Dict[Tuple[int, ...], int] = {}

    def advance(self, replicas: Tuple[int, ...]) -> int:
        i = self._counters.get(replicas, 0)
        self._counters[replicas] = i + 1
        return i

    def next(self, replicas: Tuple[int, ...]) -> int:
        return replicas[self.advance(replicas) % len(replicas)]


def index_to_ranges(index, shape) -> Ranges:
    """Normalize a jax shard index (tuple of slices) to (start, stop)
    pairs — the hashable, sortable region form everything here keys on."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def virtual_proc_of(world: int) -> Callable[[Any], int]:
    """device -> virtual rank: the device list split into ``world``
    contiguous groups. Matches the dp-major device order ``build_mesh``
    lays out, so on a pure-dp mesh each virtual rank is one dp slice.
    Test/bench-only — real multi-process worlds use the device's
    ``process_index``."""
    import jax

    devs = jax.devices()
    per = max(1, (len(devs) + world - 1) // world)
    rank_of = {d.id: min(i // per, world - 1) for i, d in enumerate(devs)}
    return lambda d: rank_of.get(d.id, 0)


def real_proc_of() -> Callable[[Any], int]:
    return lambda d: d.process_index


def split_region(ranges: Ranges, k: int) -> Optional[List[Ranges]]:
    """Split a region into ``k`` contiguous chunks along its largest
    dimension (ties: the first). None when no dimension has extent
    >= k — callers fall back to whole-region round-robin."""
    if k <= 1 or not ranges:
        return None
    extents = [e - s for s, e in ranges]
    axis = max(range(len(extents)), key=lambda d: extents[d])
    n = extents[axis]
    if n < k:
        return None
    base, rem = divmod(n, k)
    out: List[Ranges] = []
    start = ranges[axis][0]
    for i in range(k):
        size = base + (1 if i < rem else 0)
        sub = list(ranges)
        sub[axis] = (start, start + size)
        out.append(tuple(sub))
        start += size
    return out


def _assign_replicated(
    region: Ranges, reps: Tuple[int, ...], rr: RoundRobin
) -> List[PieceAssignment]:
    """The dp-round-robin split of one replicated region: one chunk per
    replica, chunk→replica pairing rotated by the replica set's counter;
    unsplittable regions round-robin whole."""
    subs = split_region(region, len(reps))
    if subs is None:
        return [
            PieceAssignment(
                ranges=region, owner=rr.next(reps), replicas=reps,
                parent=region,
            )
        ]
    off = rr.advance(reps)
    return [
        PieceAssignment(
            ranges=sub, owner=reps[(i + off) % len(reps)], replicas=reps,
            parent=region,
        )
        for i, sub in enumerate(subs)
    ]


def assign_leaf(
    shape: Tuple[int, ...],
    sharding,
    proc_of: Callable[[Any], int],
    rr: RoundRobin,
) -> List[PieceAssignment]:
    """Ownership assignment for every distinct shard region of one
    leaf. ``sharding`` must expose ``devices_indices_map`` (any
    jax.sharding.Sharding). Raises whatever the sharding raises —
    callers degrade to staging everything."""
    imap = sharding.devices_indices_map(tuple(shape))
    regions: Dict[Ranges, set] = {}
    for dev, idx in imap.items():
        r = index_to_ranges(idx, shape)
        regions.setdefault(r, set()).add(proc_of(dev))
    out: List[PieceAssignment] = []
    for r in sorted(regions):
        reps = tuple(sorted(regions[r]))
        if len(reps) == 1:
            out.append(
                PieceAssignment(
                    ranges=r, owner=reps[0], replicas=reps, parent=r
                )
            )
        else:
            out.extend(_assign_replicated(r, reps, rr))
    return out


def assign_host_leaf(
    shape: Tuple[int, ...], world: int, rr: RoundRobin
) -> List[PieceAssignment]:
    """A host (non-device) leaf — python scalars, numpy arrays — is
    replicated on every process by construction; dp-round-robin-split
    it like any fully-replicated region."""
    reps = tuple(range(world))
    ranges = tuple((0, int(d)) for d in shape)
    if world == 1:
        return [
            PieceAssignment(
                ranges=ranges, owner=0, replicas=reps, parent=ranges
            )
        ]
    return _assign_replicated(ranges, reps, rr)


def plan_for_state(
    state: PyTree,
    proc_of: Optional[Callable[[Any], int]] = None,
    world: Optional[int] = None,
) -> Dict[str, List[PieceAssignment]]:
    """Full assignment keyed by leaf path, derived from the LIVE state's
    shardings — what the engine's staging pass computes. Defaults to the
    real process topology."""
    import jax

    if proc_of is None:
        proc_of = real_proc_of()
    if world is None:
        world = jax.process_count()
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    rr = RoundRobin()
    plan: Dict[str, List[PieceAssignment]] = {}
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "devices_indices_map"):
            plan[key] = assign_leaf(tuple(leaf.shape), sharding, proc_of, rr)
        else:
            shape = tuple(getattr(leaf, "shape", ()) or ())
            plan[key] = assign_host_leaf(shape, world, rr)
    return plan


def plan_for_avatars(
    avatar_tree: PyTree,
    mesh,
    proc_of: Optional[Callable[[Any], int]] = None,
    world: Optional[int] = None,
) -> Dict[str, List[PieceAssignment]]:
    """The same assignment derived from the trainer's mesh-independent
    avatars (``_state_avatar_for(mesh)``) bound to ``mesh`` — the
    restore-target side of the invariant. Identical to
    :func:`plan_for_state` of a state placed by those avatars."""
    import jax
    from jax.sharding import NamedSharding

    if proc_of is None:
        proc_of = real_proc_of()
    if world is None:
        world = jax.process_count()
    flat, _ = jax.tree_util.tree_flatten_with_path(avatar_tree)
    rr = RoundRobin()
    plan: Dict[str, List[PieceAssignment]] = {}
    for path, av in flat:
        key = jax.tree_util.keystr(path)
        spec = getattr(av, "spec", None)
        if spec is None:
            plan[key] = assign_host_leaf(
                tuple(getattr(av, "shape", ())), world, rr
            )
            continue
        sharding = NamedSharding(mesh, spec)
        plan[key] = assign_leaf(tuple(av.shape), sharding, proc_of, rr)
    return plan


def owned_bytes(
    plan: Dict[str, List[PieceAssignment]],
    sizes: Dict[str, Tuple[Tuple[int, ...], int]],
    rank: int,
) -> int:
    """Bytes of ``rank``'s owned pieces; ``sizes`` maps leaf path ->
    (global shape, itemsize). Diagnostic helper for benches/tests."""
    total = 0
    for path, assigns in plan.items():
        _, itemsize = sizes.get(path, ((), 0))
        for a in assigns:
            if a.owner != rank:
                continue
            vol = 1
            for s, e in a.ranges:
                vol *= max(0, e - s)
            total += vol * itemsize
    return total


def validate_plan(plan: Dict[str, List[PieceAssignment]]) -> None:
    """Sanity gate used by tests: every piece has exactly one owner,
    that owner is among its replicas, no piece is assigned twice, each
    piece lies inside its parent region, and the pieces cut from one
    parent tile it exactly (volumes sum to the parent's)."""
    for path, assigns in plan.items():
        by_parent: Dict[Ranges, List[PieceAssignment]] = {}
        for a in assigns:
            if a.owner not in a.replicas:
                raise AssertionError(
                    f"{path}: owner {a.owner} not a replica of {a.ranges} "
                    f"({a.replicas})"
                )
            for (s, e), (ps, pe) in zip(a.ranges, a.parent_ranges):
                if s < ps or e > pe:
                    raise AssertionError(
                        f"{path}: piece {a.ranges} outside parent "
                        f"{a.parent_ranges}"
                    )
            by_parent.setdefault(a.parent_ranges, []).append(a)
        seen = [a.ranges for a in assigns]
        if len(seen) != len(set(seen)):
            raise AssertionError(f"{path}: duplicate region assignment")
        def _vol(r: Ranges) -> int:
            v = 1
            for s, e in r:
                v *= max(0, e - s)
            return v

        for parent, group in by_parent.items():
            if parent == ():  # 0-d: one piece == the whole parent
                if len(group) != 1:
                    raise AssertionError(f"{path}: 0-d region split")
                continue
            vol = sum(_vol(a.ranges) for a in group)
            if vol != _vol(parent):
                raise AssertionError(
                    f"{path}: pieces of parent {parent} cover {vol} of "
                    f"{_vol(parent)} elements"
                )


__all__ = [
    "PieceAssignment",
    "RoundRobin",
    "index_to_ranges",
    "split_region",
    "virtual_proc_of",
    "real_proc_of",
    "assign_leaf",
    "assign_host_leaf",
    "plan_for_state",
    "plan_for_avatars",
    "owned_bytes",
    "validate_plan",
]
