"""Agent-resident async checkpoint saver.

Parity: reference ``AsyncCheckpointSaver`` (``ckpt_saver.py:406-1394``):
lives in the agent process so checkpoints survive training-process crashes;
listens for save events on a SharedQueue, copies shm -> storage, commits
via per-node done-files + a tracker file, and persists the latest staged
shm checkpoint when the node is about to die (save-on-failure /
save-on-SIGTERM).

Storage layout (mirrored by BOTH disk tiers)::

    <ckpt_dir>/                        # tier 2: shared "object" storage
      latest_step.txt                  # tracker: last committed step
      step-<N>/
        node-<node_rank>.done          # commit votes (written after fanout)
        proc-<pid>/
          meta.json                    # CheckpointMeta manifest (shard
                                       # index + per-leaf CRC32)
          leaf-<i>.bin                 # raw little-endian bytes per staged
                                       # shard (dtype/shape in meta.json —
                                       # np.save can't round-trip bfloat16)
    <local_root>/node-<id>/            # tier 1: node-local disk
      step-<N>/proc-<pid>/...          # same proc-dir layout

Tiered persist (``DLROVER_TPU_CKPT_DEDUP``, the default): the shm
copy lands on the node-LOCAL disk tier first — a parallel pool of leaf
writers (FastPersist-style, arXiv:2406.13768), per-piece manifests
with CRC32 checksums, manifest written last so a torn proc dir is
never read as valid — and only then fans out to the shared object tier
in the background, off the shm lock. The commit vote moves to the end
of the fanout: a node votes once its pieces are durable on SHARED
storage, so the tracker's committed step is restorable after full node
loss. With the kill-switch off the legacy single-hop shm->object copy
(and its vote placement) is byte-identical to before.

``CheckpointPersister`` is the storage-side logic; ``AsyncCheckpointSaver``
adds the IPC server + event loop the agent hosts.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common import flags
from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.ipc import IpcServer, SharedQueue, default_socket_path
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.storage import (
    CheckpointDeletionStrategy,
    CheckpointStorage,
    KeepLatestStepStrategy,
    PosixDiskStorage,
)
from dlrover_tpu.checkpoint.shm_handler import (
    CheckpointMeta,
    SharedMemoryHandler,
    shm_name,
)

CKPT_EVENT_QUEUE = "ckpt-events"
SHM_LOCK = "shm-ckpt-lock"
PERSIST_STATE_DICT = "ckpt-persist-state"
TRACKER_FILE = CheckpointConstant.TRACKER_FILE


@dataclass
class CheckpointEvent:
    event_type: str  # "save" | "exit"
    step: int = -1
    persist: bool = False  # False = memory-only snapshot
    ckpt_dir: str = ""

    def to_wire(self) -> Dict:
        return {
            "event_type": self.event_type,
            "step": self.step,
            "persist": self.persist,
            "ckpt_dir": self.ckpt_dir,
        }

    @classmethod
    def from_wire(cls, d: Dict) -> "CheckpointEvent":
        return cls(
            event_type=d.get("event_type", ""),
            step=d.get("step", -1),
            persist=d.get("persist", False),
            ckpt_dir=d.get("ckpt_dir", ""),
        )


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step-{step}")


def local_tier_dir(ckpt_dir: str, node_id: int) -> str:
    """This node's local-disk checkpoint tier (tier 1).

    ``DLROVER_TPU_CKPT_LOCAL_DIR`` points it at a node-local SSD /
    emptyDir volume (deploy/k8s/README.md); unset, it defaults under
    the checkpoint dir — correctness-equivalent (the tier ladder still
    works), just without the locality win. The ``node-<id>`` suffix
    keeps simulated multi-node worlds (tests, the bench dedup leg) on
    one host from sharing a tier they are supposed to lose
    independently."""
    root = flags.CKPT_LOCAL_DIR.get()
    if not root:
        root = os.path.join(os.path.abspath(ckpt_dir), "_local")
    return os.path.join(root, f"node-{node_id}")


class CheckpointPersister:
    """shm -> storage persistence + the commit/tracker protocol."""

    def __init__(
        self,
        job_name: str,
        node_id: int,
        node_rank: int = 0,
        num_nodes: int = 1,
        local_process_ids: Optional[List[int]] = None,
        storage: Optional[CheckpointStorage] = None,
        deletion_strategy: Optional[CheckpointDeletionStrategy] = None,
        commit_timeout: float = 600.0,
    ):
        self.job_name = job_name
        self.node_id = node_id
        self.node_rank = node_rank
        self.num_nodes = num_nodes
        self.local_process_ids = local_process_ids or [0]
        self._storage = storage or PosixDiskStorage()
        # the local tier is node-local disk BY DEFINITION — always posix,
        # independent of the (configurable) object-tier storage impl
        self._local_storage = PosixDiskStorage()
        self._deletion = deletion_strategy or KeepLatestStepStrategy(3)
        self._commit_timeout = commit_timeout
        self._stop_evt = threading.Event()
        self._persisted_steps: set = set()
        #: steps copied to the local tier whose object fanout (+ vote)
        #: has not run yet — fan_out_step drains it
        self._pending_fanout: set = set()
        self.last_persist_dir = ""

    def stop(self):
        self._stop_evt.set()

    def local_handlers(self) -> List[SharedMemoryHandler]:
        out = []
        for pid in self.local_process_ids:
            h = SharedMemoryHandler(shm_name(self.job_name, self.node_id, pid))
            if h.attach():
                out.append(h)
        return out

    def copy_step_to_storage(self, ckpt_dir: str, step: int = -1) -> List[int]:
        """Copy staged shm checkpoints to storage (NO commit wait).

        Groups local handlers by their staged step; a node votes "done" for
        a step only when EVERY local process has that step staged (a
        partial vote would let a step missing some processes' shards get
        committed). Returns the steps fully persisted by this node.
        """
        t0 = time.time()
        self.last_persist_dir = ckpt_dir
        handlers = self.local_handlers()
        try:
            by_step: Dict[int, List] = {}
            for h in handlers:
                meta = h.read_meta()
                if meta is None:
                    continue
                if meta.step in self._persisted_steps:
                    continue
                if step >= 0 and meta.step != step:
                    # Persist ONLY the requested step: staging (by the
                    # trainer) may already have moved on to a newer step;
                    # persisting whatever is staged would make nodes vote
                    # for different steps and no step would ever collect
                    # num_nodes votes. The newer step's own event follows.
                    logger.warning(
                        "shm %s holds step %s, requested %s; skipping",
                        h.name,
                        meta.step,
                        step,
                    )
                    continue
                by_step.setdefault(meta.step, []).append((meta, h))
            if not by_step:
                return []
            tiered = flags.CKPT_DEDUP.get()
            complete_steps = []
            for s, pairs in sorted(by_step.items()):
                for meta, h in pairs:
                    self._write_process_ckpt(ckpt_dir, meta, h, tiered)
                if len(pairs) == len(self.local_process_ids):
                    if tiered:
                        # pieces are durable on the LOCAL tier; the
                        # commit vote waits for the object fanout
                        # (fan_out_step) so a committed step survives
                        # losing this node outright
                        self._pending_fanout.add(s)
                    else:
                        done_path = os.path.join(
                            step_dir(ckpt_dir, s),
                            f"node-{self.node_rank}.done",
                        )
                        self._storage.write(b"1", done_path)
                    self._persisted_steps.add(s)
                    complete_steps.append(s)
                else:
                    logger.warning(
                        "step %s staged by %s/%s local processes; no vote yet",
                        s,
                        len(pairs),
                        len(self.local_process_ids),
                    )
            if complete_steps:
                logger.info(
                    "persisted steps %s shm->%s in %.2fs",
                    complete_steps,
                    ckpt_dir,
                    time.time() - t0,
                )
            return complete_steps
        finally:
            for h in handlers:
                h.close()

    def persist_step(
        self, ckpt_dir: str, step: int = -1,
        commit_timeout: Optional[float] = None,
    ) -> bool:
        """Copy + fan out + commit (the commit waits for other nodes;
        call off the shm lock — see AsyncCheckpointSaver's event loop)."""
        steps = self.copy_step_to_storage(ckpt_dir, step)
        # drain ALL pending fanouts (retries earlier transient object-
        # store failures), then vote-wait on every step that either was
        # just copied (legacy mode) or just cleared its fanout —
        # including earlier steps whose retry finally landed
        cleared = self.drain_fanouts(ckpt_dir)
        for s in sorted(set(steps) | set(cleared)):
            self._maybe_commit(ckpt_dir, s, timeout=commit_timeout)
        return bool(steps)

    def _persist_pool_size(self, n_files: int) -> int:
        return max(1, min(int(flags.CKPT_PERSIST_WORKERS.get()), n_files))

    def _write_process_ckpt(
        self,
        ckpt_dir: str,
        meta: CheckpointMeta,
        handler: SharedMemoryHandler,
        tiered: bool = False,
    ):
        """One process's staged pieces -> a proc dir: leaf files written
        by the parallel persist pool, then the manifest (meta.json, with
        per-leaf CRC32) LAST — a crash mid-write leaves a manifest-less
        dir that restore skips, never a torn-but-valid checkpoint.
        ``tiered`` writes to the node-local disk tier (the object copy
        is fan_out_step's job); legacy mode writes straight to the
        object storage as before."""
        from dlrover_tpu.observability import trace

        dest = self._local_storage if tiered else self._storage
        root = (
            local_tier_dir(ckpt_dir, self.node_id) if tiered else ckpt_dir
        )
        proc_dir = os.path.join(
            step_dir(root, meta.step), f"proc-{meta.process_id}"
        )
        dest.makedirs(proc_dir)
        persist_m0 = time.monotonic()

        def write_leaf(item):
            i, leaf_meta = item
            arr = handler.read_leaf(leaf_meta, copy=False)
            # raw bytes, not np.save: extended dtypes (bfloat16 etc.) do
            # not survive a .npy round-trip (they come back as void);
            # dtype and shape live in meta.json
            data = np.ascontiguousarray(arr).tobytes()
            dest.write(data, os.path.join(proc_dir, f"leaf-{i}.bin"))
            return zlib.crc32(data)

        items = list(enumerate(meta.leaves))
        workers = self._persist_pool_size(len(items))
        if workers > 1:
            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="ckpt-persist"
            ) as pool:
                crcs = list(pool.map(write_leaf, items))
        else:
            crcs = [write_leaf(it) for it in items]
        manifest = dataclasses.replace(
            meta,
            leaves=[
                dataclasses.replace(lm, crc32=crc)
                for lm, crc in zip(meta.leaves, crcs)
            ],
        )
        dest.write(
            manifest.to_json().encode(), os.path.join(proc_dir, "meta.json")
        )
        # trace spine: one per-tier persist span (disk = the node-local
        # tier; storage = the legacy direct-to-object path)
        trace.record(
            "ckpt_save", "persist.proc", persist_m0,
            time.monotonic() - persist_m0,
            tier="disk" if tiered else "storage",
            step=meta.step, leaves=len(meta.leaves),
        )

    def drain_fanouts(self, ckpt_dir: str) -> List[int]:
        """Fan out every pending step (oldest first) — the retry path:
        a step whose object fanout failed transiently stays pending and
        is re-attempted on the next persist cycle. Returns the steps
        that cleared (callers owe them a commit wait)."""
        pending = sorted(self._pending_fanout)
        for s in pending:
            self.fan_out_step(ckpt_dir, s)
        return [s for s in pending if s not in self._pending_fanout]

    def fan_out_step(self, ckpt_dir: str, step: int):
        """Background half of a tiered persist: copy the step's local
        proc dirs to the shared object tier (parallel pool, manifests
        last), then cast this node's commit vote. Runs OFF the shm lock
        — it reads local files, not shm — so a slow object store never
        stalls the trainer's next save. No-op for steps the local copy
        didn't mark pending (legacy mode, or another saver's step). On
        failure the step STAYS pending (drain_fanouts retries it);
        only a successful fanout — or the step's local dir having been
        pruned — unqueues it."""
        if step not in self._pending_fanout:
            return
        local_sdir = step_dir(local_tier_dir(ckpt_dir, self.node_id), step)
        if not self._local_storage.exists(local_sdir):
            # pruned from the local tier before the fanout ever
            # succeeded: nothing left to ship, stop retrying
            self._pending_fanout.discard(step)
            logger.warning(
                "pending fanout of step %s dropped: local dir %s is gone",
                step, local_sdir,
            )
            return
        from dlrover_tpu.observability import trace

        fanout_m0 = time.monotonic()
        obj_sdir = step_dir(ckpt_dir, step)
        copies: List[tuple] = []
        manifests: List[tuple] = []
        for proc in self._local_storage.listdir(local_sdir):
            if not proc.startswith("proc-"):
                continue
            pdir = os.path.join(local_sdir, proc)
            for name in self._local_storage.listdir(pdir):
                pair = (
                    os.path.join(pdir, name),
                    os.path.join(obj_sdir, proc, name),
                )
                (manifests if name == "meta.json" else copies).append(pair)
        try:
            workers = self._persist_pool_size(len(copies))
            if workers > 1:
                with ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="ckpt-fanout"
                ) as pool:
                    list(
                        pool.map(lambda p: self._storage.put_file(*p), copies)
                    )
            else:
                for pair in copies:
                    self._storage.put_file(*pair)
            for pair in manifests:  # manifests last: object commit marker
                self._storage.put_file(*pair)
            self._storage.write(
                b"1",
                os.path.join(obj_sdir, f"node-{self.node_rank}.done"),
            )
        except Exception:
            # the step stays restorable from the local tier AND stays
            # pending — drain_fanouts retries it next cycle; without
            # this node's vote the tracker will not advance to it
            logger.exception(
                "object-tier fanout of step %s failed; no commit vote "
                "cast (will retry)", step,
            )
            return
        self._pending_fanout.discard(step)
        trace.record(
            "ckpt_save", "fanout.object", fanout_m0,
            time.monotonic() - fanout_m0, tier="object", step=step,
            files=len(copies) + len(manifests),
        )
        # every node prunes its OWN local tier (the object tier is
        # pruned by node-rank 0 at commit time; non-rank-0 nodes would
        # otherwise grow their node-local SSD without bound)
        try:
            self._apply_local_deletion(ckpt_dir)
        except Exception:
            logger.exception("local-tier pruning failed")

    def _maybe_commit(
        self, ckpt_dir: str, step: int, timeout: Optional[float] = None
    ):
        """Node-rank-0's saver waits for all nodes' votes then commits."""
        if self.node_rank != 0:
            return
        if step in self._pending_fanout:
            # our own fanout (and so our own vote) has not landed —
            # polling for all votes would block the event loop for the
            # full commit timeout; the drain retry will bring the step
            # back through here once the vote is cast
            logger.warning(
                "step %s: fanout still pending, skipping the commit wait",
                step,
            )
            return
        sdir = step_dir(ckpt_dir, step)
        deadline = time.time() + (
            timeout if timeout is not None else self._commit_timeout
        )
        while time.time() < deadline and not self._stop_evt.is_set():
            done = [
                f
                for f in self._storage.listdir(sdir)
                if f.startswith("node-") and f.endswith(".done")
            ]
            if len(done) >= self.num_nodes:
                self._storage.write(
                    str(step).encode(), os.path.join(ckpt_dir, TRACKER_FILE)
                )
                logger.info("checkpoint step %s committed", step)
                self._apply_deletion(ckpt_dir)
                return
            time.sleep(0.5)
        logger.warning("step %s: only partial commit votes after timeout", step)

    def _prune_tier(self, store, root: str, committed: int, protect=()):
        steps = []
        for name in store.listdir(root):
            if name.startswith("step-"):
                try:
                    steps.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
        removable = [
            s
            for s in self._deletion.to_delete(steps)
            if s != committed and s not in protect
        ]
        for s in removable:
            store.delete(step_dir(root, s))
            logger.info("deleted old checkpoint step %s under %s", s, root)

    def _apply_deletion(self, ckpt_dir: str):
        """Object-tier pruning — node-rank 0 only (commit time)."""
        committed = self.committed_step(ckpt_dir)
        self._prune_tier(self._storage, ckpt_dir, committed)

    def _apply_local_deletion(self, ckpt_dir: str):
        """Local-tier pruning — EVERY node, after each successful
        fanout: the node-local SSD holds the same step dirs as the
        object tier with far less room. Steps still awaiting their
        object fanout are protected (their only durable copy is
        local)."""
        committed = self.committed_step(ckpt_dir)
        self._prune_tier(
            self._local_storage,
            local_tier_dir(ckpt_dir, self.node_id),
            committed,
            protect=frozenset(self._pending_fanout),
        )

    def save_shm_to_storage(
        self, ckpt_dir: str = "", commit_timeout: Optional[float] = None
    ) -> bool:
        """Persist whatever is staged in shm right now (failure/SIGTERM).

        The reference's save-at-breakpoint guarantee (``training.py:1098``,
        ``ckpt_saver.py:786``). Runs from failure paths and signal
        handlers, so callers pass a short ``commit_timeout`` — a dying node
        must not spend the preemption grace period polling other nodes'
        votes."""
        ckpt_dir = ckpt_dir or self.last_persist_dir
        handlers = self.local_handlers()
        try:
            metas = [h.read_meta() for h in handlers]
        finally:
            for h in handlers:
                h.close()
        steps = {m.step for m in metas if m is not None}
        if not steps:
            return False
        if not ckpt_dir:
            logger.warning(
                "staged shm checkpoint exists but no ckpt_dir known; "
                "cannot persist"
            )
            return False
        if steps <= self._persisted_steps:
            # the staged steps' local copies exist — but a step whose
            # OBJECT fanout failed transiently is still pending, and
            # this (death-path) save is its last chance to reach
            # storage that outlives the node
            if self._pending_fanout:
                self.drain_fanouts(ckpt_dir)
            return not self._pending_fanout
        return self.persist_step(ckpt_dir, commit_timeout=commit_timeout)

    def committed_step(self, ckpt_dir: str) -> int:
        try:
            return int(self._storage.read(os.path.join(ckpt_dir, TRACKER_FILE)))
        except (FileNotFoundError, ValueError):
            return -1


class AsyncCheckpointSaver:
    """One per agent/node: IPC server + async persist event loop."""

    def __init__(
        self,
        job_name: str,
        node_id: int,
        node_rank: int = 0,
        num_nodes: int = 1,
        local_process_ids: Optional[List[int]] = None,
        storage: Optional[CheckpointStorage] = None,
        deletion_strategy: Optional[CheckpointDeletionStrategy] = None,
        socket_path: str = "",
        replica: bool = False,
    ):
        self.replica_enabled = replica
        self.replica_manager = None
        self.persister = CheckpointPersister(
            job_name=job_name,
            node_id=node_id,
            node_rank=node_rank,
            num_nodes=num_nodes,
            local_process_ids=local_process_ids,
            storage=storage,
            deletion_strategy=deletion_strategy,
        )
        self.socket_path = socket_path or default_socket_path(job_name, node_id)
        self._ipc = IpcServer(self.socket_path)
        self._event_queue: Optional[SharedQueue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    def start(self):
        self._ipc.start()
        if self.replica_enabled:
            from dlrover_tpu.checkpoint.replica import ReplicaManager

            self.replica_manager = ReplicaManager()
        self._event_queue = SharedQueue(CKPT_EVENT_QUEUE, self.socket_path)
        self._thread = threading.Thread(
            target=self._event_loop, name="ckpt-saver", daemon=True
        )
        self._thread.start()
        logger.info(
            "checkpoint saver started (node %s, ipc %s)",
            self.persister.node_id,
            self.socket_path,
        )

    def stop(self):
        self._stop_evt.set()
        self.persister.stop()
        if self.replica_manager is not None:
            self.replica_manager.server.stop()
        self._ipc.stop()

    # -- replica (cross-host backup) ---------------------------------------

    @property
    def replica_port(self) -> int:
        return self.replica_manager.port if self.replica_manager else 0

    def update_replica_peers(self, peers, self_rank: int, world: int):
        if self.replica_manager is not None:
            self.replica_manager.update_peers(peers, self_rank, world)

    def set_replica_token(self, token: str):
        if self.replica_manager is not None:
            self.replica_manager.set_token(token)

    def maybe_fetch_replica(self) -> int:
        """After a relaunch: if nothing is staged locally, pull this seat's
        backup from the peer so workers restore from memory, not storage."""
        if self.replica_manager is None:
            return -1
        for h in self.persister.local_handlers():
            try:
                if h.attach() and h.read_meta() is not None:
                    return -1  # local staged state exists
            finally:
                h.close()
        targets = [
            shm_name(self.persister.job_name, self.persister.node_id, pid)
            for pid in self.persister.local_process_ids
        ]
        return self.replica_manager.fetch_backup_into_shm(targets)

    def _release_persist_waiters(self, step: int):
        """Release the trainer's persist back-pressure — but only for
        processes whose staged step has reached ``step`` (copied, or the
        trainer already moved past so waiting longer cannot help). A
        process still holding an OLDER step keeps waiting for its own
        event; releasing it here would let it overwrite un-copied shards."""
        try:
            staged: Dict[int, int] = {}
            for h in self.persister.local_handlers():
                meta = h.read_meta()
                if meta is not None:
                    staged[meta.process_id] = meta.step
                h.close()
            state = self._ipc.state.get_dict(PERSIST_STATE_DICT)
            for pid in self.persister.local_process_ids:
                if staged.get(pid, -1) >= step:
                    key = f"copied-{pid}"
                    state[key] = max(int(state.get(key, -1)), step)
        except Exception:
            logger.exception("persist-state release failed")

    def _push_replica(self, step_hint: int = -1):
        """Copy segments out of shm under the lock, stream lock-free.
        Coalesced: a step already pushed (e.g. the persist path after a
        backup event) is not streamed twice."""
        if self.replica_manager is None:
            return
        if 0 <= step_hint <= self.replica_manager.last_pushed_step:
            return
        lock = self._ipc.state.get_lock(SHM_LOCK)
        if not lock.acquire(timeout=30):
            logger.warning("replica push skipped: shm lock busy")
            return
        handlers = self.persister.local_handlers()
        try:
            snapshot = self.replica_manager.collect_segments(handlers)
        finally:
            lock.release()
            for h in handlers:
                h.close()
        if snapshot is None:
            return
        step, segments, payload = snapshot
        if step <= self.replica_manager.last_pushed_step:
            return
        self.replica_manager.send_backup(step, segments, payload)

    def update_topology(self, node_rank: int, num_nodes: int, process_ids: List[int]):
        """Called by the agent after each rendezvous round."""
        self.persister.node_rank = node_rank
        self.persister.num_nodes = num_nodes
        self.persister.local_process_ids = list(process_ids)
        # a round boundary is a restart boundary: stale copied-{pid} marks
        # from a pre-restart (possibly higher) step would disarm the new
        # incarnation's persist back-pressure after a rollback restore
        try:
            self._ipc.state.get_dict(PERSIST_STATE_DICT).clear()
        except Exception:
            pass

    # Bounded commit wait for failure-path persists: a dying node writes its
    # shards + vote and gives peers only this long to show up before it gets
    # on with shutdown (GKE preemption grace is short).
    BREAKPOINT_COMMIT_TIMEOUT = 30.0

    def save_shm_to_storage(self, ckpt_dir: str = "") -> bool:
        """Breakpoint persist, guarded by the same shm lock the trainer
        takes (bounded wait: a dying trainer's connection drop auto-releases
        its lock, so this cannot wedge)."""
        lock = self._ipc.state.get_lock(SHM_LOCK)
        acquired = lock.acquire(timeout=30)
        if not acquired:
            # A trainer is (still) mid-stage after 30s: the shm region may
            # be torn mid-overwrite. Persisting it could commit garbage —
            # the previously committed step stays the restore point.
            logger.error(
                "breakpoint persist: shm lock not acquired in 30s; "
                "refusing to persist a possibly-torn checkpoint"
            )
            return False
        try:
            return self.persister.save_shm_to_storage(
                ckpt_dir, commit_timeout=self.BREAKPOINT_COMMIT_TIMEOUT
            )
        finally:
            lock.release()

    def cleanup_shm(self):
        """Unlink staged segments (only after a successful job end)."""
        for h in self.persister.local_handlers():
            h.close(unlink=True)

    def _event_loop(self):
        while not self._stop_evt.is_set():
            try:
                raw = self._event_queue.get(timeout=1.0)
            except queue.Empty:
                continue
            except Exception:
                if self._stop_evt.is_set():
                    return
                logger.exception("ckpt event queue read failed")
                time.sleep(1)
                continue
            event = CheckpointEvent.from_wire(raw)
            if event.event_type == "exit":
                return
            if event.event_type == "backup":
                try:
                    self._push_replica(step_hint=event.step)
                except Exception:
                    logger.exception("replica push failed")
                continue
            if event.event_type == "save" and event.persist:
                # Hold the shm lock only for the shm->storage copy (the
                # trainer takes the same lock for staging); the commit wait
                # on other nodes happens OUTSIDE the lock so it can never
                # stall the trainer's next save.
                lock = self._ipc.state.get_lock(SHM_LOCK)
                try:
                    with lock:
                        steps = self.persister.copy_step_to_storage(
                            event.ckpt_dir, event.step
                        )
                    # release back-pressure NOW: the copy the trainer is
                    # waiting on is done; the object fanout reads LOCAL
                    # files (not shm), and commit waits and replica pushes
                    # can take minutes — none of it may stall training
                    self._release_persist_waiters(event.step)
                    # drain retries earlier failed fanouts too; commit-
                    # wait everything that copied or newly cleared
                    cleared = self.persister.drain_fanouts(event.ckpt_dir)
                    for s in sorted(set(steps) | set(cleared)):
                        self.persister._maybe_commit(event.ckpt_dir, s)
                    if self.replica_manager is not None:
                        self._push_replica(step_hint=event.step)
                except Exception:
                    logger.exception("persist of step %s failed", event.step)
                finally:
                    # idempotent: also covers a copy that raised
                    self._release_persist_waiters(event.step)
