from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType  # noqa: F401
from dlrover_tpu.checkpoint.engine import CheckpointEngine  # noqa: F401
from dlrover_tpu.checkpoint.saver import (  # noqa: F401
    AsyncCheckpointSaver,
    CheckpointPersister,
)
