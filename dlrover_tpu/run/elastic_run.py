"""``dlrover-tpu-run`` — the elastic launcher CLI.

Parity: reference ``trainer/torch/elastic_run.py`` (torchrun-superset):
``--standalone`` spawns a local master subprocess, then runs the per-host
elastic agent which rendezvouses and supervises the JAX worker.

Usage::

    dlrover-tpu-run --standalone --nnodes=1 train.py --lr 3e-4
    dlrover-tpu-run --master_addr=10.0.0.1:5555 --nnodes=2:4 --node_id=1 train.py
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Tuple

from dlrover_tpu.agent.config import ElasticLaunchConfig
from dlrover_tpu.agent.elastic_agent import ElasticAgent
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import flags
from dlrover_tpu.common.constants import NodeEnv, TpuTimerConsts
from dlrover_tpu.common.log import logger


def parse_nnodes(value: str) -> Tuple[int, int]:
    if ":" in value:
        lo, hi = value.split(":", 1)
        return int(lo), int(hi)
    n = int(value)
    return n, n


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "dlrover-tpu-run", description="elastic launcher for JAX on TPU"
    )
    p.add_argument("--standalone", action="store_true",
                   help="spawn a local job master for single-node runs")
    p.add_argument("--master_addr", default=flags.MASTER_ADDR.get(),
                   help="host:port of the job master")
    p.add_argument("--nnodes", default="1", help="N or MIN:MAX nodes")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="JAX processes per host (1 is TPU-canonical)")
    p.add_argument("--node_id", type=int,
                   default=int(flags.NODE_ID.get()))
    p.add_argument("--job_name", default="dlrover-tpu-job")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--node_unit", type=int, default=1)
    p.add_argument("--network-check", action="store_true", dest="network_check",
                   help="run chip/ICI health check before training")
    p.add_argument("--comm-perf-test", action="store_true", dest="comm_perf_test")
    p.add_argument("--exclude-straggler", action="store_true", dest="exclude_straggler")
    p.add_argument("--accelerator", default="tpu", choices=["tpu", "cpu"])
    p.add_argument("--tpu-timer", action="store_true", dest="tpu_timer",
                   help="interpose the native PJRT profiler into workers")
    p.add_argument("--tpu-timer-port", type=int,
                   default=TpuTimerConsts.DEFAULT_PORT, dest="tpu_timer_port")
    p.add_argument("--comm-metrics", action="store_true",
                   dest="comm_metrics",
                   help="serve + scrape per-collective comm attribution "
                        "(profiler/comm.py) from every worker")
    p.add_argument("--comm-metrics-port", type=int, default=29700,
                   dest="comm_metrics_port")
    p.add_argument("--compile-cache-dir", dest="compile_cache_dir",
                   default=flags.COMPILE_CACHE_DIR.get(),
                   help="persistent XLA compile-cache dir injected into "
                        "workers (put it on the checkpoint volume so "
                        "restarts rebuild the train step from cache "
                        "instead of recompiling); empty = workers "
                        "default it under their checkpoint dir")
    p.add_argument("--no-save-at-breakpoint", action="store_false",
                   dest="save_at_breakpoint",
                   help="skip the shm->storage checkpoint persist before "
                        "restart boundaries")
    p.add_argument("--ckpt-replica", action="store_true", dest="ckpt_replica",
                   help="replicate staged checkpoints into a peer host's "
                        "memory for node-loss recovery without storage")
    p.add_argument("--monitor_interval", type=float, default=2.0)
    p.add_argument("--rdzv_join_timeout", type=float, default=600.0)
    p.add_argument("training_script", help="path to the JAX training script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def _launch_local_master(node_num: int) -> Tuple[subprocess.Popen, str]:
    """Spawn ``python -m dlrover_tpu.master.main`` and wait for its port."""
    port_file = tempfile.mktemp(prefix="dlrover_tpu_master_port_")
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "dlrover_tpu.master.main",
            "--platform",
            "local",
            "--node_num",
            str(node_num),
            "--port_file",
            port_file,
        ],
        start_new_session=True,
    )
    deadline = time.time() + 60
    while time.time() < deadline:
        if os.path.exists(port_file):
            content = open(port_file).read().strip()
            if content:
                os.unlink(port_file)
                return proc, f"127.0.0.1:{content}"
        if proc.poll() is not None:
            raise RuntimeError("local master exited during startup")
        time.sleep(0.2)
    raise RuntimeError("local master did not report its port in 60s")


def _strip_leading_separator(script_args: List[str]) -> List[str]:
    """Drop only a single leading ``--`` (launcher/script separator); any
    later ``--`` belongs to the user's script."""
    if script_args and script_args[0] == "--":
        return list(script_args[1:])
    return list(script_args)


def config_from_args(args) -> ElasticLaunchConfig:
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    config = ElasticLaunchConfig(
        min_nodes=min_nodes,
        max_nodes=max_nodes,
        nproc_per_node=args.nproc_per_node,
        node_id=args.node_id,
        job_name=args.job_name,
        master_addr=args.master_addr,
        max_restarts=args.max_restarts,
        node_unit=args.node_unit,
        network_check=args.network_check,
        comm_perf_test=args.comm_perf_test,
        exclude_straggler=args.exclude_straggler,
        accelerator=args.accelerator,
        tpu_timer=args.tpu_timer,
        tpu_timer_port=args.tpu_timer_port,
        comm_metrics=args.comm_metrics,
        comm_metrics_port=args.comm_metrics_port,
        ckpt_replica=args.ckpt_replica,
        compile_cache_dir=args.compile_cache_dir,
        save_at_breakpoint=args.save_at_breakpoint,
        monitor_interval=args.monitor_interval,
        rdzv_join_timeout=args.rdzv_join_timeout,
        entrypoint=args.training_script,
        entrypoint_args=_strip_leading_separator(args.training_script_args),
    )
    return config.auto_configure()


def run(args) -> int:
    master_proc: Optional[subprocess.Popen] = None
    min_nodes, max_nodes = parse_nnodes(args.nnodes)
    try:
        if args.standalone and not args.master_addr:
            master_proc, args.master_addr = _launch_local_master(max_nodes)
            logger.info("standalone master at %s", args.master_addr)
        if not args.master_addr:
            logger.error("--master_addr required (or use --standalone)")
            return 2
        config = config_from_args(args)
        client = MasterClient(args.master_addr, config.node_id)
        MasterClient.reset_singleton(client)
        if not client.available(timeout=30):
            logger.error("master %s not reachable", args.master_addr)
            return 3

        if config.network_check:
            from dlrover_tpu.agent.node_check import run_network_check

            ok = run_network_check(config, client)
            if not ok:
                logger.error("node failed network check; exiting for relaunch")
                return 4

        agent = ElasticAgent(config, client)
        return agent.run()
    finally:
        if master_proc is not None and master_proc.poll() is None:
            master_proc.terminate()
            try:
                master_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master_proc.kill()


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
