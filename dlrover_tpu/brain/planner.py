"""The goodput planner: the brain's observe → decide → act loop.

The reference DLRover's headline capability is *automatic* resource
optimization; until this module, our port still ran legacy CPU/memory
heuristics (``master/resource/optimizer.py``) and consumed none of the
rich signals the observability stack built: the goodput attribution
ledger, per-rank step digests, straggler flags, the resize-downtime
breakdown, per-link ``comm_links``/dcn_share. This planner closes the
loop (docs/design/brain_planner.md):

- **Observe** — every input is a *measured* quantity from the master's
  ledgers: fleet-median digest p50 step time, per-link comm bytes with
  the ICI/DCN byte model from ``ops/hier_collectives``, the
  SpeedMonitor's per-resize downtime breakdown as the amortized cost of
  acting, straggler flags and open downtime brackets as instability,
  HBM headroom as a feasibility gate.
- **Decide** — candidate worlds are
  :class:`~dlrover_tpu.common.world.WorldDescriptor`\\ s (the same
  checked vocabulary warm-compile speculation and the shardcheck
  contracts use). Each candidate is scored by *predicted productive
  seconds over a payback horizon* (ElasWave, arXiv:2510.00606): a
  resize only wins if its throughput gain amortizes its measured
  downtime cost within the horizon. Hysteresis (the same winning
  candidate for K consecutive decisions) and a post-execution cooldown
  turn storms and straggler episodes into HOLD decisions, not flapping.
- **Act** — an accepted plan flows through the existing
  ``JobAutoScaler`` → ``Scaler`` path; the planner's intent also (a)
  opens the rendezvous *growth gate* (waiting capacity is only
  advertised to the fleet when the planner decided to adopt it — scale
  out is a choice, shrink/recovery never waits for permission) and (b)
  publishes a *speculation hint* on the rendezvous world poll so
  workers warm-compile the exact target world instead of blind
  neighbors — a planner-directed resize becomes a warm cache hit.

Every decision lands in an export/import-safe ledger (inputs snapshot,
scores, verdict, payback estimate) that survives master relaunch and
feeds the goodput report. The planner is **clock-injected** and reads
NO wall clock of its own: the fleet chaos harness drives it on virtual
time and its decisions are bit-deterministic given the scenario seed
(proved by the ``autoscale_storm`` scenario).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common import flags
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.world import WorldDescriptor

HOLD = "hold"
RESIZE = "resize"

#: ledger bound: enough for a multi-day job at one decision/minute
#: windowing, small enough to ride every state snapshot
LEDGER_CAP = 512


@dataclasses.dataclass
class PlannerInputs:
    """One decision's measured observation snapshot. Node-level: the
    master plans in nodes (each node drives a fixed device count); the
    agent converts the hint to devices with its local device count."""

    ts: float = 0.0
    #: seated world size (nodes in the latest completed round)
    world: int = 0
    #: slices the seated world spans (1 = single-slice / unknown)
    n_slices: int = 1
    #: nodes waiting to (re)join — restorable capacity
    waiting: int = 0
    #: fleet-median digest p50 step seconds (0 = no digests yet)
    step_p50_s: float = 0.0
    #: per-link analytic comm bytes/step ({"ici": N, "dcn": M})
    comm_links: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: DCN overlap ratio the fleet reports (share of DCN bytes the
    #: schedule hides behind compute; −1.0 = unmeasured). Discounts the
    #: DCN cost term: overlapped bytes don't stretch the step.
    overlap_ratio: float = -1.0
    #: the seated world's layout (contract spec, e.g. "dp2xfsdp2+zero1");
    #: "" = unknown, treated as the pure-dp default layout
    layout_spec: str = ""
    #: per-operator share of step time from the kernel ledger
    #: ({"matmul": 0.6, "comm.all-reduce": 0.1, ...}; {} = unmeasured).
    #: The layout scorer reads the comm.* share — without it the
    #: planner cannot tell comm-bound from compute-bound and HOLDs on
    #: layout flips (it never guesses).
    kernel_breakdown: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    #: measured average downtime one membership change costs this job
    resize_cost_s: float = 0.0
    #: ranks the step-digest detector currently flags
    stragglers: List[int] = dataclasses.field(default_factory=list)
    #: a downtime bracket is open (failure reported, round re-forming)
    downtime_open: bool = False
    #: per-device HBM occupancy at the CURRENT world (bytes; 0=unknown)
    hbm_used_bytes: float = 0.0
    hbm_capacity_bytes: float = 0.0
    #: job bounds (rendezvous params / job args)
    min_nodes: int = 1
    max_nodes: int = 0
    node_unit: int = 1

    @property
    def dcn_share(self) -> float:
        total = sum(self.comm_links.values())
        return self.comm_links.get("dcn", 0) / total if total else 0.0

    def snapshot(self) -> Dict:
        """JSON-able copy for the decision record (rounded so the
        ledger is bit-stable given the same virtual-clock inputs)."""
        return {
            "ts": round(self.ts, 3),
            "world": self.world,
            "n_slices": self.n_slices,
            "waiting": self.waiting,
            "step_p50_s": round(self.step_p50_s, 6),
            "comm_links": {k: int(v) for k, v in self.comm_links.items()},
            "dcn_share": round(self.dcn_share, 4),
            "overlap_ratio": round(self.overlap_ratio, 4),
            "layout_spec": self.layout_spec,
            "kernel_breakdown": {
                k: round(float(v), 4)
                for k, v in sorted(self.kernel_breakdown.items())
            },
            "resize_cost_s": round(self.resize_cost_s, 3),
            "stragglers": sorted(self.stragglers),
            "downtime_open": bool(self.downtime_open),
            "hbm_used_bytes": round(self.hbm_used_bytes, 1),
            "hbm_capacity_bytes": round(self.hbm_capacity_bytes, 1),
        }


class GoodputPlanner:
    """Deterministic decision engine over measured signals.

    Construction wires the observation sources (``speed_monitor``, the
    training rendezvous manager); ``decide()`` may also be driven with
    explicit :class:`PlannerInputs` (unit tests, what-if tooling). All
    time flows through the injected ``clock`` — this module contains no
    wall-clock read, which a test pins.
    """

    def __init__(
        self,
        speed_monitor=None,
        rdzv_manager=None,
        job_context=None,
        clock: Optional[Callable[[], float]] = None,
        min_nodes: int = 1,
        max_nodes: int = 0,
        node_unit: int = 1,
        n_slices: int = 1,
        cooldown_s: Optional[float] = None,
        horizon_s: Optional[float] = None,
        hysteresis: Optional[int] = None,
        decide_interval_s: Optional[float] = None,
        min_gain_frac: float = 0.02,
        hbm_headroom_frac: float = 0.10,
        layout_cost_s: float = 5.0,
        pp_microbatches: int = 4,
        hbm_capacity_gb: Optional[float] = None,
        dcn_gbps: Optional[float] = None,
        default_resize_cost_s: float = 30.0,
        headroom_oracle=None,
    ):
        from dlrover_tpu.lint.lock_tracker import maybe_track

        self._sm = speed_monitor
        self._rdzv = rdzv_manager
        #: job context (master-side node registry): supplies the
        #: workers' reported HBM occupancy for the shrink-feasibility
        #: gate; capacity comes from DLROVER_TPU_PLANNER_HBM_GB (the
        #: deployment knows its chip; 0 = unknown, gate off)
        self._job_context = job_context
        #: the STATIC side of the same gate
        #: (:class:`dlrover_tpu.lint.memcheck.HeadroomOracle`): measured
        #: occupancy only exists for worlds that have run — the oracle
        #: prices EVERY candidate (never-visited worlds, layout flips)
        #: against its device-class budget, and a candidate that cannot
        #: fit is vetoed with decision reason ``oom_veto`` instead of
        #: ever becoming an intent. None = unarmed.
        self._oracle = headroom_oracle
        self._hbm_capacity_bytes = float(
            hbm_capacity_gb if hbm_capacity_gb is not None
            else flags.PLANNER_HBM_GB.get()
        ) * 1e9
        self._clock = clock or time.time
        self._min_nodes = max(1, int(min_nodes))
        self._max_nodes = int(max_nodes)
        self._node_unit = max(1, int(node_unit))
        self._n_slices = max(1, int(n_slices))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else flags.PLANNER_COOLDOWN_S.get()
        )
        self.horizon_s = float(
            horizon_s if horizon_s is not None
            else flags.PLANNER_HORIZON_S.get()
        )
        self.hysteresis = int(
            hysteresis if hysteresis is not None
            else flags.PLANNER_HYSTERESIS.get()
        )
        self.decide_interval_s = float(
            decide_interval_s if decide_interval_s is not None
            else flags.PLANNER_INTERVAL_S.get()
        )
        self.min_gain_frac = float(min_gain_frac)
        self.hbm_headroom_frac = float(hbm_headroom_frac)
        #: cost charged for a SAME-world layout flip: a warm in-process
        #: remesh (the target layout is speculation-hinted, so the step
        #: re-lower is a warm cache hit), not a membership change — far
        #: cheaper than resize_cost_s, but never free
        self.layout_cost_s = float(layout_cost_s)
        #: microbatch count the pp executors run (the ``m`` in the
        #: interleaved 1f1b bubble fraction (p-1)/(p*m)); the bubble is
        #: the compute-side cost a pp layout candidate is charged
        self.pp_microbatches = max(1, int(pp_microbatches))
        self._dcn_bytes_per_s = float(
            dcn_gbps if dcn_gbps is not None else flags.PLANNER_DCN_GBPS.get()
        ) * 1e9
        self.default_resize_cost_s = float(default_resize_cost_s)
        # mutable decision state — one lock; decide() gathers inputs
        # BEFORE taking it (SpeedMonitor/rendezvous reads must never
        # nest inside the planner lock: the rendezvous growth gate
        # calls INTO the planner under its own lock, so the planner
        # calling OUT while locked would be a lock-order cycle)
        self._lock = maybe_track(
            threading.Lock(), "brain.planner.GoodputPlanner._lock"
        )
        self._ledger: List[Dict] = []
        #: TRUE monotonic decision count — the ledger itself is capped
        #: at LEDGER_CAP, so consumers tracking "new decisions since"
        #: (the fleet runner's event log) must not read len(ledger)
        self._decisions_total: int = 0
        self._executed: List[Dict] = []
        self._counts: Dict[str, int] = {HOLD: 0, RESIZE: 0}
        self._intent: Optional[WorldDescriptor] = None
        #: the intent's plan has actually been pushed through the
        #: Scaler (note_executed): the growth gate and the speculation
        #: hint honor ONLY executed intents — a RESIZE decision whose
        #: execution failed must not adopt capacity with no plan on
        #: record and no cooldown window open
        self._intent_executed: bool = False
        self._intent_from: int = 0  # seated world when the intent formed
        self._intent_ts: float = 0.0
        #: lock-free publication for the poll fast path (the same
        #: copy-on-change pattern as the rendezvous _WorldSnapshot):
        #: (hint wire dict, gate-opening world or -1), republished
        #: under the lock on every intent/execution change and read as
        #: one atomic reference by num_nodes_waiting storms — the poll
        #: path PR 13 made lock-free must not re-serialize on the
        #: planner mutex
        self._pub: tuple = ({}, -1)
        self._last_exec_ts: float = 0.0
        self._last_decide_ts: float = 0.0
        self._streak_spec: str = ""
        self._streak: int = 0

    # -- observation -------------------------------------------------------

    def observe(self, now: Optional[float] = None) -> PlannerInputs:
        """Assemble the measured inputs from the wired master ledgers.
        Missing sources degrade to neutral values — the planner HOLDs
        on ignorance, it never guesses."""
        now = self._clock() if now is None else now
        inputs = PlannerInputs(
            ts=now,
            n_slices=self._n_slices,
            min_nodes=self._min_nodes,
            max_nodes=self._max_nodes,
            node_unit=self._node_unit,
        )
        if self._rdzv is not None:
            snap = self._rdzv.world_snapshot()
            inputs.world = len(snap.latest_world)
            # the RAW waiting count — the planner must see capacity the
            # growth gate is deliberately hiding from the fleet
            inputs.waiting = snap.num_waiting
            # slice topology from the seated metas themselves: the
            # agents report their TPU slice names at join, so the
            # master derives the REAL slice count instead of needing a
            # configured one (constructor n_slices stays the fallback
            # for slice-name-less deployments). The DCN scoring model
            # and the slice-aligned candidate set key on this.
            slices = {
                getattr(m, "slice_name", "") or ""
                for m in (getattr(snap, "rdzv_nodes", None) or {}).values()
            }
            slices.discard("")
            if (
                len(slices) > 1
                and inputs.world > 0
                and inputs.world % len(slices) == 0
            ):
                inputs.n_slices = len(slices)
        if self._sm is not None:
            digests = self._sm.straggler_report().get("rank_digests", {})
            p50s = sorted(
                float(d.get("p50_s", 0.0)) for d in digests.values()
                if float(d.get("p50_s", 0.0)) > 0
            )
            if p50s:
                inputs.step_p50_s = p50s[len(p50s) // 2]
            link_report = self._sm.comm_link_report()
            links = link_report.get("per_step_bytes", {})
            inputs.comm_links = {k: int(v) for k, v in links.items()}
            inputs.overlap_ratio = float(
                link_report.get("overlap_ratio", -1.0)
            )
            inputs.resize_cost_s = self._sm.avg_downtime()
            inputs.stragglers = list(self._sm.stragglers())
            inputs.downtime_open = self._sm.downtime_in_progress()
            # per-kernel shares (the workers' kernel ledger, relayed by
            # the speed monitor when wired) — optional: an older monitor
            # without the method leaves the breakdown unmeasured and
            # the layout scorer inert
            kb = getattr(self._sm, "kernel_breakdown", None)
            if callable(kb):
                inputs.kernel_breakdown = {
                    str(k): float(v) for k, v in (kb() or {}).items()
                }
            layout = getattr(self._sm, "layout_spec", None)
            if callable(layout):
                inputs.layout_spec = str(layout() or "")
        if self._job_context is not None and self._hbm_capacity_bytes > 0:
            # the workers' reported per-device HBM occupancy (max
            # across the fleet — the tightest device gates a shrink)
            used_mb = max(
                (
                    n.used_resource.tpu_hbm_used_mb
                    for n in self._job_context.workers().values()
                    if not n.is_released
                ),
                default=0.0,
            )
            if used_mb > 0:
                inputs.hbm_used_bytes = used_mb * 1e6
                inputs.hbm_capacity_bytes = self._hbm_capacity_bytes
        return inputs

    # -- the scoring model -------------------------------------------------

    def _grad_dcn_bytes(self, inputs: PlannerInputs) -> float:
        """Reconstruct the full per-step gradient byte volume B from the
        measured DCN bytes via the hier_collectives model: on a
        hierarchical multislice world DCN carries exactly ``B / dp_in``
        (docs/design/hier_collectives.md). Single-slice worlds measure
        zero DCN and contribute zero everywhere."""
        dcn = float(inputs.comm_links.get("dcn", 0))
        if dcn <= 0 or inputs.n_slices <= 1 or inputs.world <= 0:
            return 0.0
        dp_in = max(1, inputs.world // inputs.n_slices)
        return dcn * dp_in

    def _candidate_dcn_bytes(
        self, wd: WorldDescriptor, inputs: PlannerInputs
    ) -> float:
        """Predicted per-step DCN bytes for a candidate world: the
        hierarchical ``B / dp_in`` when the candidate tiles into whole
        slices, else the flat all-reduce's ``B * (1 - 1/s)`` — the slow
        link carries dp_in x more, which is what makes a slice-aligned
        shrink beat an arbitrary one of similar size."""
        grad_b = self._grad_dcn_bytes(inputs)
        if grad_b <= 0:
            return 0.0
        if wd.n_slices <= 1:
            per_slice = (
                inputs.world // inputs.n_slices
                if inputs.n_slices > 1 else 0
            )
            if per_slice and wd.world_size > per_slice:
                # does not tile into whole surviving slices: the ragged
                # world runs the FLAT reduction across the original
                # slice spread
                s = inputs.n_slices
                return grad_b * (1.0 - 1.0 / s)
            return 0.0  # fits one slice: no DCN at all
        return grad_b / max(1, wd.dp_in)

    def predict_step_time(
        self, wd: WorldDescriptor, inputs: PlannerInputs
    ) -> float:
        """Predicted p50 step seconds at candidate ``wd``: the compute
        half scales with 1/dp (global batch is fixed across resizes —
        the elastic invariant), the DCN half re-derives from the byte
        model over the configured slow-link bandwidth."""
        base = inputs.step_p50_s
        if base <= 0 or inputs.world <= 0:
            return 0.0
        if (
            wd.world_size == inputs.world
            and wd.spec != self._current_spec(inputs)
        ):
            # same chips, different mesh factorization: the world-ratio
            # model below would predict zero change — the layout model
            # scores the comm-share delta instead
            return self.predict_layout_step_time(wd, inputs)
        # only EXPOSED DCN bytes sit on the critical path: the fleet's
        # reported overlap_ratio discounts the transfer seconds the
        # schedule hides behind compute (−1 sentinel = no discount)
        exposed = (
            1.0 - inputs.overlap_ratio
            if 0.0 <= inputs.overlap_ratio <= 1.0 else 1.0
        )
        dcn_now = (
            float(inputs.comm_links.get("dcn", 0)) * exposed
            / self._dcn_bytes_per_s
            if self._dcn_bytes_per_s > 0 else 0.0
        )
        compute = max(base - dcn_now, base * 0.05)
        dcn_next = (
            self._candidate_dcn_bytes(wd, inputs) * exposed
            / self._dcn_bytes_per_s
            if self._dcn_bytes_per_s > 0 else 0.0
        )
        return compute * (inputs.world / wd.world_size) + dcn_next

    def _current_spec(self, inputs: PlannerInputs) -> str:
        """The seated world's layout spec: the reported one, else the
        pure-dp default descriptor for (world, n_slices)."""
        if inputs.layout_spec:
            return inputs.layout_spec
        wd = self._descriptor(inputs.world, inputs.n_slices)
        return wd.spec if wd is not None else ""

    @staticmethod
    def _layout_comm_ratio(wd: WorldDescriptor) -> float:
        """Relative per-step ICI comm volume of a layout, in units of
        the global parameter bytes P (ring-collective cost model,
        docs/design/kernels.md):

        - dp axis ``d``: gradient all-reduce ``2(d-1)/d`` on the grad
          bytes the axis still carries;
        - fsdp axis ``f``: parameter all-gather fwd+bwd ``2(f-1)/f``
          plus gradient reduce-scatter ``(f-1)/f``, and the dp-axis
          all-reduce shrinks to its ``1/f`` shard;
        - zero-1: one extra sharded-parameter all-gather ``(d-1)/d``
          after the update;
        - pp axis ``p``: each device holds ``1/p`` of the layers, so
          the dp/fsdp/zero1 param-byte collectives all shrink by
          ``1/p`` (the stage-boundary activation ppermutes are
          activation bytes — ~0 in units of P, charged through the
          bubble model instead).

        A *model*, not a measurement — it only ever scales the comm
        share the kernel ledger measured, so an error here distorts a
        fraction of a fraction of the step."""
        axes = wd.axis_sizes()
        d = axes.get("dp", 1)
        f = axes.get("fsdp", 1)
        p = axes.get("pp", 1)
        grads = 2.0 * (d - 1) / d / f
        params = (2.0 * (f - 1) / f + (f - 1) / f) if f > 1 else 0.0
        z1 = (d - 1) / d if wd.zero1 else 0.0
        return (grads + params + z1) / p

    def _bubble_fraction(self, wd: WorldDescriptor) -> float:
        """Steady-state pipeline bubble of a candidate: the interleaved
        1f1b model ``(p-1)/(p*m)`` the engine's schedule contract pins
        (``parallel/pp_schedule.py``; virtual stages ``v=p``). Non-pp
        worlds idle nothing."""
        p = wd.pp
        if p <= 1:
            return 0.0
        return (p - 1) / (p * self.pp_microbatches)

    def predict_layout_step_time(
        self, wd: WorldDescriptor, inputs: PlannerInputs
    ) -> float:
        """Predicted p50 step seconds after a SAME-world layout flip:
        the kernel ledger's measured ``comm.*`` share of the step is
        rescaled by the layouts' relative comm-volume model; the
        compute share is untouched (same chips, same per-device flops).
        No measured breakdown → no predicted change → the gain gate
        HOLDs (the planner never flips a layout on an unmeasured
        claim)."""
        base = inputs.step_p50_s
        if base <= 0:
            return 0.0
        comm_share = sum(
            v for k, v in inputs.kernel_breakdown.items()
            if k.startswith("comm.")
        )
        comm_share = min(max(comm_share, 0.0), 0.95)
        if comm_share <= 0:
            return base
        cur = self._descriptor_of_spec(self._current_spec(inputs))
        cur_ratio = self._layout_comm_ratio(cur) if cur is not None \
            else None
        if not cur_ratio:
            return base
        scale = self._layout_comm_ratio(wd) / cur_ratio
        # the compute share carries the pipeline bubble: measured time
        # is ideal work / (1 - bubble), so a pp flip rescales it by
        # (1 - bubble_now) / (1 - bubble_candidate)
        bubble_now = self._bubble_fraction(cur) if cur is not None else 0.0
        compute_scale = (1.0 - bubble_now) / max(
            1.0 - self._bubble_fraction(wd), 1e-6
        )
        return (base * (1.0 - comm_share) * compute_scale
                + base * comm_share * scale)

    @staticmethod
    def _descriptor_of_spec(spec: str) -> Optional[WorldDescriptor]:
        try:
            return WorldDescriptor.parse(spec) if spec else None
        except ValueError:
            return None

    def _hbm_feasible(
        self, wd: WorldDescriptor, inputs: PlannerInputs
    ) -> bool:
        """Shrinking packs more state per device: project occupancy by
        the world ratio and reject candidates that would land inside
        the headroom reserve. Unknown occupancy gates nothing."""
        if inputs.hbm_used_bytes <= 0 or inputs.hbm_capacity_bytes <= 0:
            return True
        if wd.world_size >= inputs.world:
            return True
        projected = inputs.hbm_used_bytes * (
            inputs.world / wd.world_size
        )
        return projected <= inputs.hbm_capacity_bytes * (
            1.0 - self.hbm_headroom_frac
        )

    def score(self, wd: WorldDescriptor, inputs: PlannerInputs) -> Dict:
        """Predicted productive seconds over the payback horizon,
        normalized to current-throughput units: steps the candidate
        completes in ``horizon_s`` (paying the measured resize cost
        up-front when it differs from the current world), divided by
        the steps the current world would complete. >1 = the resize
        pays back inside the horizon."""
        t_now = inputs.step_p50_s
        t_next = self.predict_step_time(wd, inputs)
        cur_spec = self._current_spec(inputs)
        if t_now <= 0 or t_next <= 0:
            return {"spec": wd.spec, "world": wd.world_size,
                    "score": 1.0 if wd.spec == cur_spec else 0.0,
                    "t_pred_s": round(t_next, 6), "payback_s": None}
        cost = 0.0
        if wd.world_size != inputs.world:
            cost = inputs.resize_cost_s or self.default_resize_cost_s
        elif wd.spec != cur_spec:
            # same-world layout flip: a warm in-process remesh, not a
            # membership change
            cost = self.layout_cost_s
        horizon = max(self.horizon_s, cost)
        steps_next = max(0.0, horizon - cost) / t_next
        steps_now = horizon / t_now
        # payback: seconds of candidate runtime until the throughput
        # delta has earned the downtime back (None = never)
        rate_gain = 1.0 / t_next - 1.0 / t_now
        payback = (
            cost / (rate_gain * t_now) if rate_gain > 0 and cost > 0
            else (0.0 if cost == 0 else None)
        )
        return {
            "spec": wd.spec,
            "world": wd.world_size,
            "score": round(steps_next / steps_now, 6),
            "t_pred_s": round(t_next, 6),
            "resize_cost_s": round(cost, 3),
            "payback_s": round(payback, 3) if payback is not None else None,
        }

    # -- candidates --------------------------------------------------------

    def _descriptor(
        self, nodes: int, n_slices: int, pp: int = 1
    ) -> Optional[WorldDescriptor]:
        """A node-level candidate descriptor. ``pp`` > 1 preserves the
        seated pipeline axis across the size change — a pp fleet's
        resize is a per-stage dp rebalance (live_reshard
        ``stage_transfer_plan`` kind ``dp_within_stage``), never a
        silent collapse to pure dp. Falls back to the pure-dp world
        when the stage count does not divide the candidate size or the
        world is multislice (a sliced pp world moves the stage map and
        is a different decision)."""
        axes = {"dp": nodes}
        if pp > 1 and n_slices <= 1 and nodes % pp == 0:
            axes = {"dp": nodes // pp, "pp": pp}
        try:
            return WorldDescriptor.from_axis_sizes(
                axes,
                n_slices=max(1, n_slices),
                hier=n_slices > 1,
            )
        except ValueError:
            return None

    def candidates(self, inputs: PlannerInputs) -> List[WorldDescriptor]:
        """Candidate worlds worth scoring: the current world (HOLD
        baseline), adopting the waiting capacity, a slice-aligned
        shrink, and a one-unit shrink. All node-level, rounded to
        ``node_unit``, bounded by min/max and what is actually
        reachable (seated + waiting)."""
        world = inputs.world
        if world <= 0:
            return []
        unit = max(1, inputs.node_unit)
        per_slice = (
            world // inputs.n_slices if inputs.n_slices > 1 else 0
        )
        upper = world + max(0, inputs.waiting)
        if inputs.max_nodes > 0:
            upper = min(upper, inputs.max_nodes)
        raw: List[tuple] = [(world, inputs.n_slices)]
        if upper > world:
            grow = (upper // unit) * unit
            if per_slice:
                grow = (grow // per_slice) * per_slice
            if grow > world:
                raw.append((
                    grow,
                    grow // per_slice if per_slice else 1,
                ))
        if per_slice and inputs.n_slices > 1:
            raw.append((world - per_slice, inputs.n_slices - 1))
        shrink = ((world - unit) // unit) * unit
        if shrink >= inputs.min_nodes and shrink > 0:
            slices = 1
            if per_slice and shrink % per_slice == 0:
                slices = shrink // per_slice
            raw.append((shrink, slices))
        out: List[WorldDescriptor] = []
        seen = set()
        seen_nodes = set()
        # the HOLD baseline must be the CURRENT layout, not the pure-dp
        # default of the same size — a zero1/fsdp fleet scored against
        # the wrong incumbent would mistake the flip for a hold
        cur = self._descriptor_of_spec(self._current_spec(inputs))
        if cur is not None and cur.world_size == world:
            out.append(cur)
            seen.add(cur.spec)
            seen_nodes.add(world)
        # the seated pipeline axis rides every size candidate: resizing
        # a pp fleet rebalances dp within stages, it does not flatten
        cur_pp = cur.pp if cur is not None else 1
        for nodes, slices in raw:
            if nodes < max(1, inputs.min_nodes) or nodes in seen_nodes:
                continue
            if inputs.max_nodes > 0 and nodes > inputs.max_nodes:
                continue
            wd = self._descriptor(nodes, slices, pp=cur_pp)
            if wd is None:
                continue
            if not self._hbm_feasible(wd, inputs):
                continue
            seen_nodes.add(nodes)
            seen.add(wd.spec)
            out.append(wd)
        for wd in self.layout_candidates(inputs):
            if wd.spec not in seen:
                seen.add(wd.spec)
                out.append(wd)
        return out

    def _oracle_vetoes(
        self, cands: List[WorldDescriptor], inputs: PlannerInputs
    ) -> Tuple[List[WorldDescriptor], List[WorldDescriptor], List[Dict]]:
        """Price every non-incumbent candidate through the static
        headroom oracle. Returns ``(survivors, vetoed_wds,
        veto_records)`` — the records are the ledger-facing evidence
        ({spec, world, predicted/usable/budget bytes}). The incumbent
        is never vetoed: the fleet is already running it, and HOLD
        needs its baseline. Unarmed oracle -> everything survives."""
        if self._oracle is None:
            return cands, [], []
        cur_spec = self._current_spec(inputs)
        survivors: List[WorldDescriptor] = []
        vetoed: List[WorldDescriptor] = []
        records: List[Dict] = []
        for wd in cands:
            if wd.spec == cur_spec:
                survivors.append(wd)
                continue
            try:
                verdict = self._oracle.fits(wd)
            except Exception as e:
                # a broken oracle must never stall scaling decisions
                logger.warning("planner: headroom oracle failed: %s", e)
                survivors.append(wd)
                continue
            if verdict.get("fits", True):
                survivors.append(wd)
            else:
                vetoed.append(wd)
                records.append({
                    "spec": wd.spec,
                    "world": wd.world_size,
                    "predicted_bytes": int(verdict.get("peak_bytes", 0)),
                    "usable_bytes": int(verdict.get("usable_bytes", 0)),
                    "budget_bytes": int(verdict.get("budget_bytes", 0)),
                })
        return survivors, vetoed, records

    def layout_candidates(
        self, inputs: PlannerInputs
    ) -> List[WorldDescriptor]:
        """SAME-world candidates that re-factorize the mesh instead of
        changing membership: dp↔fsdp splits of the seated node count
        and the zero-1 toggle on the current factorization. Acting on
        one is a warm in-process remesh (the speculation hint carries
        the target spec, so workers warm-compile it), not a resize.
        Single-slice worlds only — a multislice layout flip also moves
        the DCN schedule and is a different decision."""
        world = inputs.world
        if world <= 0 or inputs.n_slices > 1:
            return []
        cur = self._descriptor_of_spec(self._current_spec(inputs))
        out: List[WorldDescriptor] = []

        def _add(axes: Dict[str, int], zero1: bool):
            try:
                wd = WorldDescriptor.from_axis_sizes(
                    dict(axes), n_slices=1, zero1=zero1
                )
            except ValueError:
                return
            if cur is None or wd.spec != cur.spec:
                out.append(wd)

        cur_axes = cur.axis_sizes() if cur is not None else {"dp": world}
        cur_z1 = cur.zero1 if cur is not None else False
        # dp <-> fsdp factorizations of the same node count
        for f in (1, 2, 4, 8):
            if f < world and world % f == 0:
                axes = {"dp": world // f}
                if f > 1:
                    axes["fsdp"] = f
                _add(axes, cur_z1)
        # pp re-factorizations — only when the fleet already REPORTS a
        # pp layout (the engine is proven to slab this model; the
        # planner cannot check n_layers % p from here): stage count
        # halved/doubled (per-stage dp width moves the other way) and
        # the pp exit (pure data axes) falls out of the dp/fsdp loop
        # above. Scored by the same measured-comm-share model — all
        # param collectives shrink 1/p, the compute share carries the
        # interleaved 1f1b bubble (p-1)/(p*m) — so a flip is never
        # adopted on an unmeasured claim.
        cur_pp = cur_axes.get("pp", 1)
        if cur_pp > 1:
            for p in {cur_pp // 2, cur_pp * 2}:
                if p > 1 and p != cur_pp and p <= world and world % p == 0:
                    axes = dict(cur_axes)
                    axes.pop("fsdp", None)
                    axes["pp"] = p
                    axes["dp"] = world // p
                    _add(axes, cur_z1)
        # the zero-1 toggle on the current factorization
        _add(cur_axes, not cur_z1)
        return out

    # -- the decision ------------------------------------------------------

    def decide(
        self,
        inputs: Optional[PlannerInputs] = None,
        now: Optional[float] = None,
    ) -> Dict:
        """One full observe→score→verdict pass. Appends the decision
        record to the ledger and returns it."""
        if inputs is None:
            inputs = self.observe(now)
        now = inputs.ts if now is None else now

        def record(verdict, reason, target=None, scores=None, payback=None,
                   vetoes=None):
            rec = {
                "ts": round(now, 3),
                "verdict": verdict,
                "reason": reason,
                "current_world": inputs.world,
                "target": target.spec if target is not None else "",
                "target_world": (
                    target.world_size if target is not None else 0
                ),
                "scores": scores or [],
                "payback_s": payback,
                # the oracle's oom evidence rides EVERY record of the
                # round that produced it (post-baseline ledger readers
                # use .get — wirecheck WC002 discipline), so a veto is
                # auditable even when the verdict itself is a plain
                # hold/resize on a surviving candidate
                "vetoes": list(vetoes or []),
                "inputs": inputs.snapshot(),
            }
            with self._lock:
                self._last_decide_ts = now
                self._counts[verdict] = self._counts.get(verdict, 0) + 1
                if verdict == RESIZE and target is not None:
                    self._intent = target
                    self._intent_executed = False
                    self._intent_from = inputs.world
                    self._intent_ts = now
                    self._publish_locked()
                self._ledger.append(rec)
                del self._ledger[:-LEDGER_CAP]
                self._decisions_total += 1
            if verdict == RESIZE:
                logger.info(
                    "planner: RESIZE %d -> %s (%s)",
                    inputs.world, rec["target"], reason,
                )
            return rec

        with self._lock:
            intent = self._intent
            intent_from = self._intent_from
            last_exec = self._last_exec_ts
        if intent is not None:
            target = intent.world_size
            if target == intent_from:
                # a layout intent: the node count never moves, so
                # "seated" means the fleet reports the target layout —
                # a layout-blind fleet satisfies immediately (the act
                # path is an in-process remesh; nothing to wait on)
                satisfied = (
                    not inputs.layout_spec
                    or inputs.layout_spec == intent.spec
                )
            else:
                satisfied = (
                    inputs.world >= target if target >= intent_from
                    else inputs.world <= target
                )
            reachable = inputs.world + max(0, inputs.waiting)
            expired = (
                # the capacity the intent targeted died before adoption:
                # a growth approval for nodes that no longer exist must
                # not hold the gate open for whoever joins NEXT
                (target > inputs.world and target > reachable)
                # ...and an approval never survives into instability —
                # adopting fresh capacity mid-straggler-episode is the
                # exact unapproved scale-out the gate exists to prevent
                or inputs.stragglers
                or inputs.downtime_open
            )
            if satisfied or expired:
                # satisfied: the intended world seated; expired: the
                # conditions the approval was granted under are gone.
                # Either way the growth gate closes and the speculation
                # hint clears (a stable fleet re-earns a new intent
                # through the normal hysteresis path).
                with self._lock:
                    self._intent = None
                    self._intent_executed = False
                    self._publish_locked()
                intent = None
        if inputs.world <= 0 or inputs.step_p50_s <= 0:
            self._reset_streak()
            return record(HOLD, "no_signal")
        if inputs.downtime_open or inputs.stragglers:
            # instability: a fleet mid-recovery or mid-straggler-episode
            # never triggers a resize — and the streak resets, so one
            # healthy window after the storm cannot flip the decision
            # either (hysteresis restarts from zero)
            self._reset_streak()
            return record(
                HOLD,
                "unstable:" + (
                    "downtime" if inputs.downtime_open else "stragglers"
                ),
            )
        if last_exec > 0 and now - last_exec < self.cooldown_s:
            self._reset_streak()
            return record(HOLD, "cooldown")
        cands, vetoed_wds, vetoes = self._oracle_vetoes(
            self.candidates(inputs), inputs
        )
        if not cands:
            self._reset_streak()
            return record(
                HOLD, "oom_veto" if vetoes else "no_candidates",
                vetoes=vetoes,
            )
        scores = [self.score(wd, inputs) for wd in cands]
        by_spec = {wd.spec: wd for wd in cands}
        best = max(scores, key=lambda s: (s["score"], -s["world"]))
        # the HOLD baseline is the current LAYOUT, not just the current
        # world size: same-world layout candidates share the size and
        # must not be mistaken for the incumbent
        cur_spec = self._current_spec(inputs)
        current_score = next(
            (s for s in scores if s["spec"] == cur_spec),
            next((s for s in scores if s["world"] == inputs.world), None),
        )
        baseline = current_score["score"] if current_score else 1.0
        # the oracle's vetoed candidates are still SCORED: when the
        # throughput winner is a world that cannot fit, the honest
        # verdict is "oom_veto on that world", not "no paying
        # candidate" — the ledger must show the resize the planner
        # WANTED and why it refused it. A HOLD forms no intent, so the
        # vetoed target is never gated in and never pre-warmed.
        if vetoed_wds:
            veto_scores = [self.score(wd, inputs) for wd in vetoed_wds]
            best_vetoed = max(
                veto_scores, key=lambda s: (s["score"], -s["world"])
            )
            if (
                best_vetoed["score"] > best["score"]
                and best_vetoed["score"]
                >= baseline * (1.0 + self.min_gain_frac)
            ):
                self._reset_streak()
                vetoed_by_spec = {wd.spec: wd for wd in vetoed_wds}
                return record(
                    HOLD, "oom_veto",
                    target=vetoed_by_spec[best_vetoed["spec"]],
                    scores=scores + veto_scores,
                    payback=best_vetoed.get("payback_s"),
                    vetoes=vetoes,
                )
        if (
            best["spec"] == cur_spec
            or best["score"] < baseline * (1.0 + self.min_gain_frac)
        ):
            self._reset_streak()
            return record(HOLD, "no_paying_candidate", scores=scores,
                          vetoes=vetoes)
        # hysteresis: the SAME winning candidate must survive K
        # consecutive decisions before it becomes a plan
        with self._lock:
            if self._streak_spec == best["spec"]:
                self._streak += 1
            else:
                self._streak_spec, self._streak = best["spec"], 1
            streak = self._streak
        if streak < self.hysteresis:
            return record(
                HOLD, f"hysteresis:{streak}/{self.hysteresis}",
                target=by_spec[best["spec"]], scores=scores,
                payback=best.get("payback_s"), vetoes=vetoes,
            )
        self._reset_streak()
        target = by_spec[best["spec"]]
        reason = (
            "layout_payback" if target.world_size == inputs.world
            else "payback"
        )
        return record(
            RESIZE, reason, target=target,
            scores=scores, payback=best.get("payback_s"), vetoes=vetoes,
        )

    def _reset_streak(self):
        with self._lock:
            self._streak_spec, self._streak = "", 0

    def sweep(self, now: Optional[float] = None) -> Optional[Dict]:
        """Throttled decide for poll loops (the autoscaler thread, the
        fleet harness tick loop): no-op until ``decide_interval_s`` has
        passed since the last decision."""
        now = self._clock() if now is None else now
        with self._lock:
            if now - self._last_decide_ts < self.decide_interval_s:
                return None
        return self.decide(now=now)

    # -- act plumbing ------------------------------------------------------

    def intent(self) -> Optional[WorldDescriptor]:
        with self._lock:
            return self._intent

    def _publish_locked(self):
        """Rebuild the lock-free poll publication. Caller holds the
        lock. Only an EXECUTED intent opens the gate / publishes the
        hint: a RESIZE decision whose scaler push failed leaves the
        fleet exactly as gated as before (and with no cooldown open,
        the next sweep retries the plan)."""
        if self._intent is not None and self._intent_executed:
            self._pub = (self._intent.to_wire(), self._intent.world_size)
        else:
            self._pub = ({}, -1)

    def note_executed(self, target: WorldDescriptor, now: Optional[float] = None):
        """The autoscaler pushed the plan to the scaler: start the
        cooldown window, remember the execution for the ledger (at
        most one executed plan per cooldown window by construction),
        and — only now — open the growth gate / publish the hint."""
        now = self._clock() if now is None else now
        with self._lock:
            self._last_exec_ts = now
            self._executed.append({
                "ts": round(now, 3),
                "target": target.spec,
                "target_world": target.world_size,
            })
            del self._executed[:-LEDGER_CAP]
            if (
                self._intent is not None
                and self._intent.spec == target.spec
            ):
                self._intent_executed = True
            self._publish_locked()

    def growth_allowed(self, seated_world: int) -> bool:
        """The rendezvous growth gate: waiting capacity is advertised
        to a HEALTHY seated fleet only while an EXECUTED plan grows
        past it (shrink/recovery paths never consult this). Called on
        the lock-free poll fast path and under the rendezvous lock —
        reads one published reference, takes no lock."""
        return self._pub[1] > seated_world

    def speculation_hint(self) -> Dict:
        """The rendezvous world poll's hint payload: the exact world
        the planner's EXECUTED plan targets ({} = no executed intent).
        Old agents drop the unknown field (serde), new agents
        warm-compile the target. Lock-free (published reference) — it
        rides the protocol's highest-rate poll."""
        return dict(self._pub[0])

    # -- observability / continuity ----------------------------------------

    def report(self, last_n: int = 32) -> Dict:
        """The goodput report's ``decisions`` section."""
        with self._lock:
            return {
                "counts": dict(self._counts),
                "intent": (
                    self._intent.spec if self._intent is not None else ""
                ),
                "executed": list(self._executed[-last_n:]),
                "last": list(self._ledger[-last_n:]),
                "total": self._decisions_total,
            }

    def prometheus_lines(self) -> List[str]:
        with self._lock:
            counts = dict(self._counts)
            last = self._ledger[-1] if self._ledger else None
            executed = len(self._executed)
            intent = self._intent
        lines = ["# TYPE dlrover_tpu_scale_decisions_total counter"]
        for verdict in sorted(counts):
            lines.append(
                f'dlrover_tpu_scale_decisions_total{{verdict="{verdict}"}} '
                f"{counts[verdict]}"
            )
        lines.append(
            f"dlrover_tpu_planner_executed_plans_total {executed}"
        )
        lines.append(
            "dlrover_tpu_planner_intent_world "
            f"{intent.world_size if intent is not None else 0}"
        )
        if last is not None:
            lines.append(
                f'dlrover_tpu_planner_last_decision{{verdict='
                f'"{last["verdict"]}",reason="{last["reason"]}"}} '
                f'{last["ts"]}'
            )
            lines.append(
                "dlrover_tpu_planner_last_target_world "
                f"{last['target_world']}"
            )
        return lines

    def export_state(self) -> Dict:
        """Durable ledger snapshot: decisions, executions, cooldown and
        hysteresis state survive a master relaunch — a relaunched
        planner must not re-execute a plan the dead master just paid
        for, nor forget a hysteresis streak mid-confirmation."""
        with self._lock:
            return {
                "ledger": list(self._ledger),
                "decisions_total": self._decisions_total,
                "executed": list(self._executed),
                "counts": dict(self._counts),
                "intent": (
                    self._intent.spec if self._intent is not None else ""
                ),
                "intent_executed": self._intent_executed,
                "intent_from": self._intent_from,
                "intent_ts": self._intent_ts,
                "last_exec_ts": self._last_exec_ts,
                "last_decide_ts": self._last_decide_ts,
                "streak_spec": self._streak_spec,
                "streak": self._streak,
            }

    def import_state(self, state: Dict):
        if not state:
            return
        intent = None
        spec = str(state.get("intent", "") or "")
        if spec:
            try:
                intent = WorldDescriptor.parse(spec)
            except ValueError:
                logger.warning("planner: dropping bad intent %r", spec)
        with self._lock:
            self._ledger = list(state.get("ledger") or [])[-LEDGER_CAP:]
            self._decisions_total = int(
                state.get("decisions_total", len(self._ledger))
            )
            self._executed = list(state.get("executed") or [])[-LEDGER_CAP:]
            counts = state.get("counts") or {}
            self._counts = {
                str(k): int(v) for k, v in counts.items()
            } or {HOLD: 0, RESIZE: 0}
            self._intent = intent
            self._intent_executed = bool(
                state.get("intent_executed", intent is not None)
            )
            self._intent_from = int(state.get("intent_from", 0))
            self._intent_ts = float(state.get("intent_ts", 0.0))
            self._publish_locked()
            self._last_exec_ts = float(state.get("last_exec_ts", 0.0))
            self._last_decide_ts = float(state.get("last_decide_ts", 0.0))
            self._streak_spec = str(state.get("streak_spec", ""))
            self._streak = int(state.get("streak", 0))
