"""Brain optimizer framework: pluggable per-stage algorithms.

Parity: reference ``dlrover/go/brain/pkg/optimizer`` (base_optimizer.go:
40-48 dispatch + ``optalgorithm/`` implementations). The reference's 18
algorithms are PS-era (PS cold-create/hot-resource/OOM, worker create);
the TPU set replaces PS math with what matters on slices: throughput
scaling fits for worker count, history-based cold starts, and
memory-bump OOM recovery.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.brain.datastore import BrainDataStore
from dlrover_tpu.brain.messages import (
    BrainOptimizeRequest,
    BrainResourcePlan,
    RuntimeSample,
)
from dlrover_tpu.common.log import logger

STAGE_CREATE = "job_stage_create"
STAGE_SAMPLE = "job_stage_sample"
STAGE_RUNNING = "job_stage_running"

Algorithm = Callable[[BrainDataStore, BrainOptimizeRequest], BrainResourcePlan]
_ALGORITHMS: Dict[str, Algorithm] = {}


def algorithm(stage: str):
    def wrap(fn: Algorithm) -> Algorithm:
        _ALGORITHMS[stage] = fn
        return fn

    return wrap


def _round_to_unit(n: int, req: BrainOptimizeRequest) -> int:
    unit = max(1, req.node_unit)
    lo = max(unit, req.min_workers or unit)
    hi = req.max_workers or max(lo, n)
    n = max(lo, min(n, hi))
    floored = (n // unit) * unit
    if floored < lo:
        # flooring must not violate the job minimum: round UP instead
        floored = -(-lo // unit) * unit
    return max(unit, min(floored, max((hi // unit) * unit, unit)))


@algorithm(STAGE_CREATE)
def create_plan(
    store: BrainDataStore, req: BrainOptimizeRequest
) -> BrainResourcePlan:
    """Cold start: reuse the last successful same-named job's final
    worker count; else be conservative (min) so the SAMPLE stage can
    measure before scaling out."""
    history = store.similar_job_outcome(req.job_name)
    if history is not None:
        n = _round_to_unit(history["final_workers"], req)
        return BrainResourcePlan(
            worker_count=n, comment=f"history: {history['final_workers']}"
        )
    n = _round_to_unit(req.min_workers or req.node_unit, req)
    return BrainResourcePlan(worker_count=n, comment="cold start: min")


def fit_scaling(samples: List[RuntimeSample]) -> Optional[Tuple[float, float]]:
    """Fit speed(n) ≈ a·n / (1 + b·n) (serial-fraction model) from
    (worker_num, speed) observations. Returns (a, b) or None."""
    points: Dict[int, List[float]] = {}
    for s in samples:
        if s.worker_num > 0 and s.speed_steps_per_sec > 0:
            points.setdefault(s.worker_num, []).append(s.speed_steps_per_sec)
    if len(points) < 2:
        return None
    # linearize: n/speed = (1/a) + (b/a)·n  -> least squares on (n, n/speed)
    xs, ys = [], []
    for n, speeds in points.items():
        avg = sum(speeds) / len(speeds)
        xs.append(float(n))
        ys.append(n / avg)
    n_pts = len(xs)
    sx = sum(xs)
    sy = sum(ys)
    sxx = sum(x * x for x in xs)
    sxy = sum(x * y for x, y in zip(xs, ys))
    denom = n_pts * sxx - sx * sx
    if abs(denom) < 1e-9:
        return None
    slope = (n_pts * sxy - sx * sy) / denom  # b/a
    intercept = (sy - slope * sx) / n_pts  # 1/a
    if intercept <= 0:
        return None
    a = 1.0 / intercept
    b = slope * a
    return a, max(0.0, b)


def predicted_speed(a: float, b: float, n: int) -> float:
    return a * n / (1.0 + b * n)


def cluster_saturated(store: BrainDataStore) -> bool:
    """Cluster-pressure gate (fed by ``cluster_watcher.py``, reference
    ``go/brain/pkg/platform/k8s`` watchers): TPU chips already sitting in
    Pending pods mean a grow plan would only mint more Pending pods."""
    state = store.latest_cluster_state()
    return bool(state) and state["tpu_chips_pending"] > 0


@algorithm(STAGE_SAMPLE)
def sample_plan(
    store: BrainDataStore, req: BrainOptimizeRequest
) -> BrainResourcePlan:
    """Early training: scale toward max in node_unit increments while
    each increment still pays (predicted marginal speedup ≥ 5%/host)."""
    samples = store.job_samples(req.job_uuid, limit=200)
    fit = fit_scaling(samples)
    if fit is None:
        # not enough variety yet: step one unit toward max to generate it
        # (growth, so the saturation gate applies; shrink paths never gate)
        if cluster_saturated(store):
            return BrainResourcePlan(comment="cluster saturated; hold")
        n = _round_to_unit(
            (req.current_workers or req.min_workers) + req.node_unit, req
        )
        return BrainResourcePlan(worker_count=n, comment="sampling: +unit")
    return _scale_by_fit(fit, req, store)


@algorithm(STAGE_RUNNING)
def running_plan(
    store: BrainDataStore, req: BrainOptimizeRequest
) -> BrainResourcePlan:
    samples = store.job_samples(req.job_uuid, limit=500)
    fit = fit_scaling(samples)
    if fit is None:
        return BrainResourcePlan(comment="no fit; hold")
    return _scale_by_fit(fit, req, store)


def _growth_recoups_restart(
    fit: Tuple[float, float],
    req: BrainOptimizeRequest,
    current: int,
    target: int,
) -> bool:
    """Goodput-aware growth gate: scaling up forces a re-rendezvous +
    recompile + restore costing ``restart_cost_s`` of downtime at the
    CURRENT speed; the extra throughput must win that back within the
    recoup horizon, or the scale-up lowers goodput (the ≥95% north star
    the reference reports — README.md:46-48 there). Shrinks never gate:
    they are forced by capacity, not chosen."""
    cost = req.restart_cost_s
    horizon = req.recoup_horizon_s
    if cost <= 0 or horizon <= 0:
        return True  # gate disabled or no restart ever observed
    a, b = fit
    v_cur = predicted_speed(a, b, current)
    v_new = predicted_speed(a, b, target)
    # steps lost while the world re-forms vs steps gained afterwards
    lost = v_cur * cost
    gained = (v_new - v_cur) * max(horizon - cost, 0.0)
    return gained > lost


def _scale_by_fit(
    fit: Tuple[float, float],
    req: BrainOptimizeRequest,
    store: Optional[BrainDataStore] = None,
) -> BrainResourcePlan:
    """Pick the largest worker count whose marginal goodput per added
    host clears 5% of a host's base throughput (reference analogue:
    worker speed-ratio thresholding, local_optimizer.go/py)."""
    a, b = fit
    current = req.current_workers or req.min_workers or 1
    best = current
    unit = max(1, req.node_unit)
    lo = max(unit, req.min_workers or unit)
    hi = req.max_workers or current
    candidates = range(lo, hi + 1, unit)
    base = predicted_speed(a, b, 1)
    prev_speed = predicted_speed(a, b, current)
    for n in candidates:
        if n <= best:
            continue
        gain = predicted_speed(a, b, n) - predicted_speed(a, b, best)
        if gain >= 0.05 * base * (n - best):
            best = n
    if best == current:
        return BrainResourcePlan(comment=f"hold at {current}")
    if best > current and store is not None and cluster_saturated(store):
        # shrink plans still pass: they relieve the pressure
        return BrainResourcePlan(
            comment=f"cluster saturated; hold at {current} (wanted {best})"
        )
    if best > current and not _growth_recoups_restart(fit, req, current, best):
        return BrainResourcePlan(
            comment=(
                f"growth {current}->{best} would not recoup the "
                f"{req.restart_cost_s:.0f}s restart within "
                f"{req.recoup_horizon_s:.0f}s; hold"
            )
        )
    return BrainResourcePlan(
        worker_count=_round_to_unit(best, req),
        comment=f"fit a={a:.3g} b={b:.3g}: {current}->{best} "
        f"(pred {prev_speed:.2f}->{predicted_speed(a, b, best):.2f} steps/s)",
    )


def oom_recovery_plan(
    store: BrainDataStore, req: BrainOptimizeRequest
) -> BrainResourcePlan:
    """Host OOM: bump host memory to max(2x observed peak, 1.5x historic
    peak) (reference adjust_oom_resource, job.py:313-395). HBM OOM: more
    host RAM cannot help — halve micro-batch, double grad-accum so the
    global batch is preserved (matches the local optimizer's HBM path)."""
    if not req.host_oom:
        return BrainResourcePlan(
            paral_config={
                "micro_batch_scale": 0.5,
                "grad_accum_scale": 2.0,
                "restart": True,
            },
            comment="hbm oom: micro-batch/2, grad-accum x2",
        )
    peak = store.peak_memory(req.job_name)
    samples = store.job_samples(req.job_uuid, limit=50)
    current_peak = max((s.memory_mb_max for s in samples), default=0.0)
    target = max(2 * current_peak, 1.5 * peak)
    if target <= 0:
        target = 2 * 16 * 1024  # no data: double a 16GB default
    return BrainResourcePlan(
        memory_mb_per_host=target,
        comment=f"host oom recovery: mem -> {target:.0f}MB",
    )


class BrainOptimizer:
    """Dispatch: stage -> algorithm (reference BaseOptimizer.Optimize)."""

    def __init__(self, store: BrainDataStore):
        self._store = store

    def optimize(self, req: BrainOptimizeRequest) -> BrainResourcePlan:
        if req.oom_nodes:
            return oom_recovery_plan(self._store, req)
        algo = _ALGORITHMS.get(req.stage)
        if algo is None:
            logger.warning("no algorithm for stage %r", req.stage)
            return BrainResourcePlan(comment=f"unknown stage {req.stage}")
        return algo(self._store, req)
