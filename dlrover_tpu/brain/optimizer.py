"""Brain optimizer framework: named algorithms in configurable per-stage
chains.

Parity: reference ``dlrover/go/brain/pkg/optimizer`` — ``base_optimizer.go:
40-48`` dispatches a *configured chain* of named algorithms per stage, and
``optalgorithm/`` ships 18 implementations. The reference's algorithms are
PS-era (PS cold-create / hot-PS / PS-OOM); the TPU translations here keep
the same architecture (registry + chain + config override) with slice-era
math: throughput-scaling fits for worker count, job- and slice-type
history cold starts, host-memory right-sizing, hot-host detection from the
per-host metric feed, and goodput/saturation growth gates.

Chain semantics (reference ``optimize_algorithm.go``): each algorithm
receives the plan produced so far and refines it — producers fill empty
fields, gates veto or shrink a growth the producers proposed. Chains are
configurable per stage through the datastore's master-config table under
``brain.chain.<stage>`` (comma-separated algorithm names), so an operator
can re-order, drop, or extend a chain without redeploying.
"""

from __future__ import annotations

import statistics
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.brain.datastore import BrainDataStore
from dlrover_tpu.brain.messages import (
    BrainOptimizeRequest,
    BrainResourcePlan,
    RuntimeSample,
)
from dlrover_tpu.common.log import logger

STAGE_CREATE = "job_stage_create"
STAGE_SAMPLE = "job_stage_sample"
STAGE_RUNNING = "job_stage_running"
STAGE_OOM = "job_stage_oom"

#: name -> fn(store, req, plan) mutating/refining the plan in place
Algorithm = Callable[
    [BrainDataStore, BrainOptimizeRequest, BrainResourcePlan], None
]
_ALGORITHMS: Dict[str, Algorithm] = {}

DEFAULT_CHAINS: Dict[str, List[str]] = {
    STAGE_CREATE: [
        "job_history_cold_start",
        "slice_coldstart_sizing",
        "conservative_create",
        "worker_create_resource",
    ],
    STAGE_SAMPLE: [
        "throughput_fit_scaling",
        "sample_step_up",
        "init_adjust_resource",
        "cluster_saturation_gate",
        "goodput_growth_gate",
    ],
    STAGE_RUNNING: [
        "throughput_fit_scaling",
        "hot_host_guard",
        "speed_anomaly_guard",
        "cluster_saturation_gate",
        "goodput_growth_gate",
    ],
    STAGE_OOM: [
        "oom_host_memory_bump",
        "oom_hbm_paral_adjust",
    ],
}


def algorithm(name: str):
    def wrap(fn: Algorithm) -> Algorithm:
        _ALGORITHMS[name] = fn
        return fn

    return wrap


def algorithm_names() -> List[str]:
    return sorted(_ALGORITHMS)


def _note(plan: BrainResourcePlan, text: str):
    plan.comment = f"{plan.comment}; {text}" if plan.comment else text


def _round_to_unit(n: int, req: BrainOptimizeRequest) -> int:
    unit = max(1, req.node_unit)
    lo = max(unit, req.min_workers or unit)
    hi = req.max_workers or max(lo, n)
    n = max(lo, min(n, hi))
    floored = (n // unit) * unit
    if floored < lo:
        # flooring must not violate the job minimum: round UP instead
        floored = -(-lo // unit) * unit
    return max(unit, min(floored, max((hi // unit) * unit, unit)))


# ---------------------------------------------------------------------------
# scaling fit (shared by sample/running producers)
# ---------------------------------------------------------------------------


def fit_scaling(samples: List[RuntimeSample]) -> Optional[Tuple[float, float]]:
    """Fit speed(n) ≈ a·n / (1 + b·n) (serial-fraction model) from
    (worker_num, speed) observations. Robustness: per-n medians (not
    means), outliers beyond 3x/⅓x of the per-n median dropped, and
    degenerate sets (single n, non-positive intercept) return None so
    callers hold instead of acting on a garbage fit."""
    points: Dict[int, List[float]] = {}
    for s in samples:
        if s.worker_num > 0 and s.speed_steps_per_sec > 0:
            points.setdefault(s.worker_num, []).append(s.speed_steps_per_sec)
    if len(points) < 2:
        return None
    # linearize: n/speed = (1/a) + (b/a)·n  -> least squares on (n, n/speed)
    xs, ys = [], []
    for n, speeds in points.items():
        med = statistics.median(speeds)
        kept = [v for v in speeds if med / 3.0 <= v <= med * 3.0] or [med]
        xs.append(float(n))
        ys.append(n / statistics.median(kept))
    n_pts = len(xs)
    sx = sum(xs)
    sy = sum(ys)
    sxx = sum(x * x for x in xs)
    sxy = sum(x * y for x, y in zip(xs, ys))
    denom = n_pts * sxx - sx * sx
    if abs(denom) < 1e-9:
        return None
    slope = (n_pts * sxy - sx * sy) / denom  # b/a
    intercept = (sy - slope * sx) / n_pts  # 1/a
    if intercept <= 0:
        return None
    a = 1.0 / intercept
    if a <= 0:
        return None
    return a, max(0.0, slope * a)


def predicted_speed(a: float, b: float, n: int) -> float:
    return a * n / (1.0 + b * n)


def cluster_saturated(store: BrainDataStore) -> bool:
    """Cluster-pressure gate (fed by ``cluster_watcher.py``, reference
    ``go/brain/pkg/platform/k8s`` watchers): TPU chips already sitting in
    Pending pods mean a grow plan would only mint more Pending pods."""
    state = store.latest_cluster_state()
    return bool(state) and state["tpu_chips_pending"] > 0


# ---------------------------------------------------------------------------
# CREATE-stage producers
# ---------------------------------------------------------------------------


@algorithm("job_history_cold_start")
def job_history_cold_start(store, req, plan):
    """Reuse the last successful same-named job's final worker count
    (reference ``optimize_job_ps_create_resource.go`` consults history)."""
    if plan.worker_count > 0:
        return
    history = store.similar_job_outcome(req.job_name)
    if history is not None:
        plan.worker_count = _round_to_unit(history["final_workers"], req)
        _note(plan, f"history: {history['final_workers']}")


@algorithm("slice_coldstart_sizing")
def slice_coldstart_sizing(store, req, plan):
    """No same-name history: size from what same-slice-type jobs settled
    at — the TPU translation of the reference's cold-create resource
    tables (``optimize_job_ps_cold_create_resource.go`` keyed its cold
    table by resource class; ours is keyed by tpu_type)."""
    if plan.worker_count > 0:
        return
    tpu_type = req.tpu_type or store.job_tpu_type(req.job_uuid)
    if not tpu_type:
        return
    outcomes = store.tpu_type_outcomes(tpu_type)
    if not outcomes:
        return
    n = int(statistics.median(outcomes))
    plan.worker_count = _round_to_unit(n, req)
    _note(plan, f"slice cold start ({tpu_type}): median {n} of "
                f"{len(outcomes)} runs")


@algorithm("conservative_create")
def conservative_create(store, req, plan):
    """Last resort: start at min so the SAMPLE stage can measure before
    scaling out."""
    if plan.worker_count > 0:
        return
    plan.worker_count = _round_to_unit(req.min_workers or req.node_unit, req)
    _note(plan, "cold start: min")


@algorithm("worker_create_resource")
def worker_create_resource(store, req, plan):
    """Host memory request from historic peaks x1.5 (reference
    ``optimize_job_worker_create_resource.go`` sizes worker memory from
    the job's past runs)."""
    if plan.memory_mb_per_host > 0:
        return
    peak = store.peak_memory(req.job_name)
    if peak > 0:
        plan.memory_mb_per_host = 1.5 * peak
        _note(plan, f"mem from history peak {peak:.0f}MB x1.5")


# ---------------------------------------------------------------------------
# SAMPLE/RUNNING producers
# ---------------------------------------------------------------------------


@algorithm("sample_step_up")
def sample_step_up(store, req, plan):
    """Not enough sample variety for a fit yet: step one node_unit toward
    max to generate it."""
    if plan.worker_count > 0:
        return
    if "_fit" in plan.paral_config:
        return  # fit exists; the fit producer owns the decision
    if not plan.paral_config.get("_fit_attempted"):
        # standalone chain (fit producer not configured): check ourselves
        if fit_scaling(store.job_samples(req.job_uuid, limit=200)):
            return
    n = _round_to_unit(
        (req.current_workers or req.min_workers) + req.node_unit, req
    )
    if n != req.current_workers:
        plan.worker_count = n
        _note(plan, "sampling: +unit")


@algorithm("throughput_fit_scaling")
def throughput_fit_scaling(store, req, plan):
    """Pick the largest worker count whose marginal throughput per added
    host clears 5% of a host's base throughput (reference analogue:
    worker speed-ratio thresholding, ``optimize_job_worker_resource.go``)."""
    samples = store.job_samples(req.job_uuid, limit=500)
    plan.paral_config["_fit_attempted"] = True
    fit = fit_scaling(samples)
    if fit is None:
        _note(plan, "no fit")
        return
    a, b = fit
    # record the fit before any early return: a hold decision must also
    # stop sample_step_up from blindly stepping +unit (the marker is what
    # tells it the fit producer owns the decision)
    plan.paral_config.setdefault("_fit", (a, b))
    current = req.current_workers or req.min_workers or 1
    best = current
    unit = max(1, req.node_unit)
    lo = max(unit, req.min_workers or unit)
    hi = req.max_workers or current
    base = predicted_speed(a, b, 1)
    for n in range(lo, hi + 1, unit):
        if n <= best:
            continue
        gain = predicted_speed(a, b, n) - predicted_speed(a, b, best)
        if gain >= 0.05 * base * (n - best):
            best = n
    if best == current:
        _note(plan, f"hold at {current}")
        return
    plan.worker_count = _round_to_unit(best, req)
    _note(
        plan,
        f"fit a={a:.3g} b={b:.3g}: {current}->{best} "
        f"(pred {predicted_speed(a, b, current):.2f}->"
        f"{predicted_speed(a, b, best):.2f} steps/s)",
    )


@algorithm("init_adjust_resource")
def init_adjust_resource(store, req, plan):
    """First real samples in: right-size host memory to observed peak
    x1.3 (reference ``optimize_job_ps_init_adjust_resource.go`` — adjust
    the guessed create-time resource once reality reports in)."""
    samples = store.job_samples(req.job_uuid, limit=50)
    peak = max((s.memory_mb_max for s in samples), default=0.0)
    if peak > 0 and plan.memory_mb_per_host <= 0:
        plan.memory_mb_per_host = 1.3 * peak
        _note(plan, f"mem right-size: observed peak {peak:.0f}MB x1.3")


# ---------------------------------------------------------------------------
# RUNNING guards
# ---------------------------------------------------------------------------


@algorithm("hot_host_guard")
def hot_host_guard(store, req, plan):
    """Hot-host detection (reference ``optimize_job_hot_ps_resource.go``:
    a PS whose CPU pegs while others idle gets more resource; the TPU
    translation: a *host* whose CPU pegs while its TPU duty-cycle lags
    the fleet is contended — name it so the master can cordon/migrate).
    Requires the per-host metric feed (host_metrics on samples)."""
    samples = store.job_samples(req.job_uuid, limit=20)
    per_host: Dict[str, List[List[float]]] = {}
    for s in samples:
        for host, vals in (s.host_metrics or {}).items():
            per_host.setdefault(host, []).append(vals)
    if len(per_host) < 2:
        return
    duty_by_host = {
        h: statistics.median(v[2] for v in vals if len(v) > 2)
        for h, vals in per_host.items()
        if any(len(v) > 2 for v in vals)
    }
    cpu_by_host = {
        h: statistics.median(v[0] for v in vals if v)
        for h, vals in per_host.items()
    }
    if not duty_by_host:
        return
    fleet_duty = statistics.median(duty_by_host.values())
    hot = [
        h
        for h in duty_by_host
        if cpu_by_host.get(h, 0.0) >= 90.0
        and duty_by_host[h] < 0.5 * fleet_duty
        and fleet_duty > 0
    ]
    if hot:
        plan.hot_hosts = sorted(hot)
        _note(plan, f"hot hosts (cpu pegged, duty lagging): {sorted(hot)}")


@algorithm("speed_anomaly_guard")
def speed_anomaly_guard(store, req, plan):
    """Throughput collapsed at an unchanged worker count -> the cause is
    not scale, it is a sick node or input stall; flag for the diagnosis
    pipeline instead of letting the fit request more hosts."""
    samples = store.job_samples(req.job_uuid, limit=100)
    cur_n = req.current_workers
    history = [
        s.speed_steps_per_sec
        for s in samples
        if s.worker_num == cur_n and s.speed_steps_per_sec > 0
    ]
    if len(history) < 6:
        return
    # samples come newest-first from the store
    recent = statistics.median(history[:3])
    baseline = statistics.median(history[3:])
    if baseline > 0 and recent < 0.5 * baseline:
        plan.paral_config["speed_anomaly"] = True
        if plan.worker_count > cur_n:
            plan.worker_count = 0  # veto growth while sick
        _note(
            plan,
            f"speed anomaly: {recent:.2f} vs baseline {baseline:.2f} "
            "steps/s; growth vetoed, diagnose first",
        )


# ---------------------------------------------------------------------------
# growth gates (shared by sample/running)
# ---------------------------------------------------------------------------


@algorithm("cluster_saturation_gate")
def cluster_saturation_gate(store, req, plan):
    """Growth only: a saturated cluster turns scale-ups into Pending
    pods. Shrinks pass — they relieve the pressure."""
    current = req.current_workers or req.min_workers or 1
    if plan.worker_count > current and cluster_saturated(store):
        _note(plan, f"cluster saturated; hold at {current} "
                    f"(wanted {plan.worker_count})")
        plan.worker_count = 0


@algorithm("goodput_growth_gate")
def goodput_growth_gate(store, req, plan):
    """Goodput-aware growth gate: scaling up forces a re-rendezvous +
    recompile + restore costing ``restart_cost_s`` of downtime at the
    CURRENT speed; the extra throughput must win that back within the
    recoup horizon, or the scale-up lowers goodput (the ≥95% north star
    the reference reports — README.md:46-48 there). Shrinks never gate."""
    current = req.current_workers or req.min_workers or 1
    target = plan.worker_count
    if target <= current:
        return
    cost = req.restart_cost_s
    horizon = req.recoup_horizon_s
    if cost <= 0 or horizon <= 0:
        return  # gate disabled or no restart ever observed
    fit = plan.paral_config.get("_fit") or fit_scaling(
        store.job_samples(req.job_uuid, limit=500)
    )
    if fit is None:
        return
    a, b = fit
    v_cur = predicted_speed(a, b, current)
    v_new = predicted_speed(a, b, target)
    lost = v_cur * cost
    gained = (v_new - v_cur) * max(horizon - cost, 0.0)
    if gained <= lost:
        _note(
            plan,
            f"growth {current}->{target} would not recoup the "
            f"{cost:.0f}s restart within {horizon:.0f}s; hold",
        )
        plan.worker_count = 0


# ---------------------------------------------------------------------------
# OOM chain
# ---------------------------------------------------------------------------


@algorithm("oom_host_memory_bump")
def oom_host_memory_bump(store, req, plan):
    """Host OOM: bump host memory to max(2x observed peak, 1.5x historic
    peak) (reference ``optimize_job_ps_oom_resource.go`` /
    ``optimize_job_worker_create_oom_resource.go``)."""
    if not req.host_oom:
        return
    peak = store.peak_memory(req.job_name)
    samples = store.job_samples(req.job_uuid, limit=50)
    current_peak = max((s.memory_mb_max for s in samples), default=0.0)
    target = max(2 * current_peak, 1.5 * peak)
    if target <= 0:
        target = 2 * 16 * 1024  # no data: double a 16GB default
    plan.memory_mb_per_host = target
    _note(plan, f"host oom recovery: mem -> {target:.0f}MB")


@algorithm("oom_hbm_paral_adjust")
def oom_hbm_paral_adjust(store, req, plan):
    """HBM OOM: more host RAM cannot help — halve micro-batch, double
    grad-accum so the global batch is preserved."""
    if req.host_oom:
        return
    plan.paral_config.update(
        {"micro_batch_scale": 0.5, "grad_accum_scale": 2.0, "restart": True}
    )
    _note(plan, "hbm oom: micro-batch/2, grad-accum x2")


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------


class BrainOptimizer:
    """Chain dispatch: stage -> configured list of named algorithms
    (reference ``BaseOptimizer.Optimize`` over its algorithm config)."""

    CHAIN_CONFIG_PREFIX = "brain.chain."

    def __init__(self, store: BrainDataStore):
        self._store = store

    def chain_for(self, stage: str, job_name: str = "") -> List[str]:
        """Operator override from master-config (``brain.chain.<stage>``
        = "algo1,algo2"), else the built-in default."""
        cfg = self._store.master_config(job_name)
        raw = cfg.get(f"{self.CHAIN_CONFIG_PREFIX}{stage}", "")
        if raw:
            names = [n.strip() for n in raw.split(",") if n.strip()]
            known = [n for n in names if n in _ALGORITHMS]
            unknown = set(names) - set(known)
            if unknown:
                logger.warning("unknown brain algorithms ignored: %s",
                               sorted(unknown))
            if known:
                return known
        return DEFAULT_CHAINS.get(stage, [])

    def optimize(self, req: BrainOptimizeRequest) -> BrainResourcePlan:
        stage = STAGE_OOM if req.oom_nodes else req.stage
        chain = self.chain_for(stage, req.job_name)
        if not chain:
            logger.warning("no algorithm chain for stage %r", stage)
            return BrainResourcePlan(comment=f"unknown stage {stage}")
        plan = BrainResourcePlan()
        for name in chain:
            try:
                _ALGORITHMS[name](self._store, req, plan)
            except Exception:
                logger.exception("brain algorithm %s failed; continuing",
                                 name)
        plan.paral_config.pop("_fit", None)
        plan.paral_config.pop("_fit_attempted", None)
        plan.paral_config.pop("speed_anomaly", None)
        return plan


# -- compatibility shim: the pre-chain entry point used by older callers ----


def oom_recovery_plan(
    store: BrainDataStore, req: BrainOptimizeRequest
) -> BrainResourcePlan:
    plan = BrainResourcePlan()
    oom_host_memory_bump(store, req, plan)
    oom_hbm_paral_adjust(store, req, plan)
    return plan
