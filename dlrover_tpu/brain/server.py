"""Brain service: cluster-level resource optimization over job history.

Parity: reference ``dlrover/go/brain/pkg/server/server.go:52-135``
(BrainServer.Optimize/PersistMetrics over gRPC, MySQL datastore). Runs as
``python -m dlrover_tpu.brain.server --port 50051 --db /var/lib/brain.db``;
masters connect via ``BrainResourceOptimizer``
(dlrover_tpu/master/resource/brain_optimizer.py).
"""

from __future__ import annotations

import argparse
import sys
import threading

from dlrover_tpu.brain import messages as bmsg
from dlrover_tpu.brain.datastore import BrainDataStore
from dlrover_tpu.brain.optimizer import BrainOptimizer
from dlrover_tpu.common.log import logger
from dlrover_tpu.common.messages import SimpleResponse
from dlrover_tpu.rpc.transport import RpcServer


class BrainServicer:
    def __init__(self, store: BrainDataStore):
        self.store = store
        self.optimizer = BrainOptimizer(store)

    def get(self, request, context=None):
        if isinstance(request, bmsg.BrainOptimizeRequest):
            try:
                plan = self.optimizer.optimize(request)
                return bmsg.BrainOptimizeResponse(success=True, plan=plan)
            except Exception as e:
                logger.exception("optimize failed")
                return bmsg.BrainOptimizeResponse(success=False, reason=str(e))
        if isinstance(request, bmsg.BrainConfigRequest):
            return bmsg.BrainConfigResponse(
                values=self.store.master_config(request.job_name)
            )
        if isinstance(request, bmsg.BrainJobMetricsRequest):
            return bmsg.BrainJobMetricsResponse(
                job_uuid=request.job_uuid,
                samples=self.store.job_samples(
                    request.job_uuid, request.limit
                ),
            )
        return SimpleResponse(success=False, reason="unknown message")

    def report(self, request, context=None):
        if isinstance(request, bmsg.BrainPersistMetrics):
            self.store.upsert_job(
                request.job_uuid,
                request.job_name,
                tpu_type=request.tpu_type,
                min_workers=request.min_workers,
                max_workers=request.max_workers,
                node_unit=request.node_unit,
            )
            if request.samples:
                self.store.append_samples(request.job_uuid, request.samples)
            return SimpleResponse()
        if isinstance(request, bmsg.BrainConfigUpdate):
            if not request.key:
                return SimpleResponse(success=False, reason="empty key")
            self.store.set_master_config(
                request.key, request.value, request.job_name
            )
            logger.info(
                "config update: %s[%s] = %r",
                request.job_name or "<cluster>", request.key, request.value,
            )
            return SimpleResponse()
        if isinstance(request, bmsg.BrainJobEndReport):
            self.store.finish_job(
                request.job_uuid,
                request.status,
                request.worker_num,
                request.exit_reason,
            )
            return SimpleResponse()
        return SimpleResponse(success=False, reason="unknown message")


class BrainServer:
    def __init__(self, port: int = 0, db_path: str = ":memory:"):
        self.store = BrainDataStore(db_path)
        self.servicer = BrainServicer(self.store)
        self._server = RpcServer(self.servicer, port=port)
        self.port = self._server.port

    def start(self):
        self._server.start()
        logger.info("brain service on port %s", self.port)

    def stop(self):
        self._server.stop(grace=1)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dlrover_tpu brain")
    p.add_argument("--port", type=int, default=50051)
    p.add_argument("--db", default="/tmp/dlrover_tpu_brain.db")
    p.add_argument(
        "--watch_cluster", action="store_true",
        help="poll k8s pods into cluster_state so optimize() sees cluster "
             "pressure (reference go/brain k8s watchers)",
    )
    p.add_argument("--watch_interval", type=float, default=30.0)
    args = p.parse_args(argv)
    server = BrainServer(port=args.port, db_path=args.db)
    server.start()
    if args.watch_cluster:
        from dlrover_tpu.brain.cluster_watcher import ClusterWatcher
        from dlrover_tpu.scheduler.k8s_client import get_k8s_client

        watcher = ClusterWatcher(
            get_k8s_client(), server.store,
            interval_secs=args.watch_interval,
        )
        watcher.start()
        logger.info("cluster watcher polling every %ss", args.watch_interval)
    threading.Event().wait()  # serve forever
    return 0


if __name__ == "__main__":
    sys.exit(main())
