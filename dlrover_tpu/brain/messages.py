"""Brain service wire messages (reference ``dlrover/proto/brain.proto``:
``optimize``/``persist_metrics``/``get_job_metrics``, carried here over the
same two-generic-RPC transport the master uses)."""

from __future__ import annotations

from dataclasses import field
from typing import Dict, List

# noqa import registers SimpleResponse (the ack type the brain servicer
# returns) in every process that can talk brain wire messages
from dlrover_tpu.common.messages import SimpleResponse  # noqa: F401
from dlrover_tpu.common.serde import message


@message
class RuntimeSample:
    """One observation of a running job (master's stats collector)."""

    timestamp: float = 0.0
    worker_num: int = 0
    speed_steps_per_sec: float = 0.0
    global_step: int = 0
    cpu_percent_avg: float = 0.0
    memory_mb_avg: float = 0.0
    memory_mb_max: float = 0.0
    tpu_duty_cycle_avg: float = 0.0
    #: per-host feed for hot-host detection: host -> [cpu%, mem_mb, duty]
    host_metrics: Dict[str, List[float]] = field(default_factory=dict)


@message
class BrainPersistMetrics:
    """report: append runtime samples for a job."""

    job_uuid: str = ""
    job_name: str = ""
    samples: List[RuntimeSample] = field(default_factory=list)
    # static config of the job, persisted once (idempotent upsert)
    tpu_type: str = ""
    min_workers: int = 0
    max_workers: int = 0
    node_unit: int = 1


@message
class BrainJobEndReport:
    """report: the job finished (captures outcome for cold-start reuse)."""

    job_uuid: str = ""
    status: str = ""  # succeeded | failed | oom
    worker_num: int = 0
    exit_reason: str = ""


@message
class BrainOptimizeRequest:
    """get: produce a resource plan for a job stage."""

    job_uuid: str = ""
    job_name: str = ""
    stage: str = ""  # JobOptStage values
    strategy: str = "allreduce"
    min_workers: int = 0
    max_workers: int = 0
    node_unit: int = 1
    current_workers: int = 0
    oom_nodes: List[str] = field(default_factory=list)
    host_oom: bool = False
    # goodput-aware growth gate: a scale-up forces re-rendezvous +
    # recompile + restore, costing ~restart_cost_s of downtime (the
    # master's observed average); growth must recoup it within the
    # horizon. 0 disables the gate.
    restart_cost_s: float = 0.0
    recoup_horizon_s: float = 1800.0
    #: slice type (e.g. v5p-32) for slice-keyed cold-start sizing
    tpu_type: str = ""


@message
class BrainResourcePlan:
    worker_count: int = 0
    memory_mb_per_host: float = 0.0
    paral_config: Dict = field(default_factory=dict)
    comment: str = ""
    #: hosts the hot-host guard flagged (cpu pegged, TPU duty lagging) —
    #: the master cordons/migrates these
    hot_hosts: List[str] = field(default_factory=list)

    def empty(self) -> bool:
        return (
            self.worker_count <= 0
            and self.memory_mb_per_host <= 0
            and not self.paral_config
            and not self.hot_hosts
        )


@message
class BrainOptimizeResponse:
    success: bool = True
    reason: str = ""
    plan: BrainResourcePlan = field(default_factory=BrainResourcePlan)


@message
class BrainConfigUpdate:
    """report: admin write of a master-config override (e.g. a
    ``brain.chain.<stage>`` algorithm chain) — the runtime-mutable knob
    path; ``job_name=''`` sets the cluster-wide default."""

    job_name: str = ""
    key: str = ""
    value: str = ""


@message
class BrainConfigRequest:
    """get: master tunable overrides for a job (consumed by
    ``common/global_context.py``; the reference's
    ``set_params_from_brain`` was a TODO — this is the real path)."""

    job_name: str = ""


@message
class BrainConfigResponse:
    success: bool = True
    values: Dict = field(default_factory=dict)


@message
class BrainJobMetricsRequest:
    job_uuid: str = ""
    job_name: str = ""
    limit: int = 100


@message
class BrainJobMetricsResponse:
    job_uuid: str = ""
    samples: List[RuntimeSample] = field(default_factory=list)
