"""Brain cluster watcher: k8s pods → sqlite cluster-pressure snapshots.

Parity: reference ``dlrover/go/brain/pkg/platform/k8s/`` (watchers that
persist pod/job/node state into the brain DB so optimizers see *cluster*
pressure, not just per-job history; ~2k LoC of Go informers). The TPU-lean
version: one poller lists pods through the same stdlib K8s client the
master uses, aggregates running/pending pod counts and their
``google.com/tpu`` chip requests, and records a snapshot. The optimizer's
growth gate (`optimizer.py cluster_saturated`) reads the latest snapshot:
pending TPU chips in the cluster mean a grow plan would just mint more
Pending pods, so plans hold instead.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from dlrover_tpu.brain.datastore import BrainDataStore
from dlrover_tpu.common.log import logger
from dlrover_tpu.scheduler.job import _parse_quantity

TPU_RESOURCE_KEY = "google.com/tpu"

#: a freshly created pod is normally scheduled within seconds; don't call
#: it pressure during that window
PENDING_GRACE_S = 120.0
#: a pod pending for this long is stuck (quota, bad selector), not a sign
#: the cluster is momentarily full — counting it would gate all growth
#: forever on one misconfigured pod
PENDING_STUCK_S = 3600.0


def _pod_tpu_chips(pod: Dict) -> int:
    chips = 0
    for c in pod.get("spec", {}).get("containers", []):
        req = c.get("resources", {}).get("requests", {})
        chips += int(_parse_quantity(req.get(TPU_RESOURCE_KEY, 0)))
    return chips


def _pod_age_s(pod: Dict, now: Optional[float] = None) -> float:
    created = pod.get("metadata", {}).get("creationTimestamp", "")
    if not created:
        return PENDING_GRACE_S + 1  # unknown age: count it
    try:
        ts = time.mktime(time.strptime(created, "%Y-%m-%dT%H:%M:%SZ"))
        # creationTimestamp is UTC; mktime assumes local — correct it
        ts -= time.timezone
    except ValueError:
        return PENDING_GRACE_S + 1
    return (now or time.time()) - ts


def aggregate_pods(pods, now: Optional[float] = None) -> Tuple[int, int, int, int]:
    """(running_pods, pending_pods, chips_running, chips_pending).

    Pending pods only count as pressure inside the
    (PENDING_GRACE_S, PENDING_STUCK_S) age window — younger ones are in a
    normal scheduling transit, older ones are stuck, and neither says the
    cluster is out of capacity."""
    running = pending = chips_running = chips_pending = 0
    for pod in pods:
        phase = pod.get("status", {}).get("phase", "")
        chips = _pod_tpu_chips(pod)
        if phase == "Running":
            running += 1
            chips_running += chips
        elif phase == "Pending":
            age = _pod_age_s(pod, now)
            if PENDING_GRACE_S < age < PENDING_STUCK_S:
                pending += 1
                chips_pending += chips
    return running, pending, chips_running, chips_pending


class ClusterWatcher:
    """Periodic pod-list poller feeding ``cluster_state`` snapshots."""

    def __init__(
        self,
        client,  # scheduler.k8s_client.K8sClient
        store: BrainDataStore,
        interval_secs: float = 30.0,
        label_selector: str = "",
    ):
        self._client = client
        self._store = store
        self._interval = interval_secs
        self._selector = label_selector
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def collect_once(self) -> Dict:
        pods = self._client.list_pods(self._selector)
        running, pending, c_run, c_pend = aggregate_pods(pods)
        self._store.record_cluster_state(running, pending, c_run, c_pend)
        snapshot = {
            "running_pods": running,
            "pending_pods": pending,
            "tpu_chips_running": c_run,
            "tpu_chips_pending": c_pend,
        }
        logger.debug("cluster snapshot: %s", snapshot)
        return snapshot

    def start(self):
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="brain-cluster-watcher", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop_evt.set()

    def _loop(self):
        # first snapshot immediately: otherwise the saturation gate is
        # silently absent for the entire first interval
        while True:
            try:
                self.collect_once()
            except Exception:
                logger.exception("cluster snapshot failed")
            if self._stop_evt.wait(self._interval):
                return
